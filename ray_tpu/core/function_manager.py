"""Function/actor-class export over GCS KV.

Reference equivalent: `python/ray/_private/function_manager.py` (export at
`:228`, fetch at `:297`) + `GcsFunctionManager`: a function is pickled once
per job, stored under a content-hash key in the GCS KV, and fetched+cached by
workers on first use.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Callable, Dict

import cloudpickle


def _hash_blob(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()[:32]




class FunctionManager:
    def __init__(self, kv_put, kv_get):
        """kv_put(key: str, value: bytes, overwrite) / kv_get(key) -> bytes;
        both synchronous callables provided by the runtime."""
        self._kv_put = kv_put
        self._kv_get = kv_get
        self._exported: Dict[int, str] = {}   # id(obj) -> key
        self._cache: Dict[str, Any] = {}      # key -> callable/class
        self._lock = threading.Lock()

    def export(self, obj: Callable) -> str:
        with self._lock:
            key = self._exported.get(id(obj))
            if key is not None:
                return key
        blob = cloudpickle.dumps(obj)
        key = f"fn:{_hash_blob(blob)}"
        self._kv_put(key, blob, False)
        with self._lock:
            self._exported[id(obj)] = key
            self._cache[key] = obj
        return key

    def fetch(self, key: str) -> Any:
        with self._lock:
            obj = self._cache.get(key)
            if obj is not None:
                return obj
        blob = self._kv_get(key)
        if blob is None:
            raise KeyError(f"function blob {key} not found in GCS")
        obj = cloudpickle.loads(blob)
        with self._lock:
            self._cache[key] = obj
        return obj
