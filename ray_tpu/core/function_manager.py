"""Function/actor-class export over GCS KV.

Reference equivalent: `python/ray/_private/function_manager.py` (export at
`:228`, fetch at `:297`) + `GcsFunctionManager`: a function is pickled once
per job, stored under a content-hash key in the GCS KV, and fetched+cached by
workers on first use.
"""

from __future__ import annotations

import hashlib
import threading
import weakref
from typing import Any, Callable, Dict, Tuple

import cloudpickle


def _hash_blob(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()[:32]


class FunctionManager:
    def __init__(self, kv_put, kv_get):
        """kv_put(key: str, value: bytes, overwrite) / kv_get(key) -> bytes;
        both synchronous callables provided by the runtime."""
        self._kv_put = kv_put
        self._kv_get = kv_get
        # id(obj) -> (weakref(obj), key). The weakref is re-verified on every
        # hit: CPython recycles ids of collected objects, so a bare id-keyed
        # cache can hand a *different* closure at a reused address the old
        # function's blob (wrong-code execution). Content addressing is the
        # source of truth (reference: _private/function_manager.py:61,228);
        # this map is only a skip-the-pickle fast path.
        self._exported: Dict[int, Tuple[Any, str]] = {}
        self._cache: Dict[str, Any] = {}      # key -> callable/class
        self._lock = threading.Lock()

    def export(self, obj: Callable) -> str:
        oid = id(obj)
        with self._lock:
            entry = self._exported.get(oid)
            if entry is not None:
                ref, key = entry
                if ref() is obj:
                    return key
                del self._exported[oid]
        blob = cloudpickle.dumps(obj)
        key = f"fn:{_hash_blob(blob)}"
        self._kv_put(key, blob, False)
        with self._lock:
            try:
                # Eviction callback bounds _exported: once the object is
                # collected its entry can never validate again, so drop it.
                self._exported[oid] = (
                    weakref.ref(obj, lambda _, oid=oid:
                                self._exported.pop(oid, None)), key)
            except TypeError:
                pass  # not weakref-able: no fast path, re-pickle each time
            self._cache[key] = obj
        return key

    def fetch(self, key: str) -> Any:
        with self._lock:
            obj = self._cache.get(key)
            if obj is not None:
                return obj
        blob = self._kv_get(key)
        if blob is None:
            raise KeyError(f"function blob {key} not found in GCS")
        obj = cloudpickle.loads(blob)
        with self._lock:
            self._cache[key] = obj
        return obj
