"""Per-process flight recorder: always-on event rings + stall forensics.

Reference intuition: Dapper (Sigelman et al., 2010) and "The Tail at
Scale" (Dean & Barroso, 2013) — tail anomalies are only fixable once
*always-on, low-overhead* recording makes individual episodes
attributable after the fact. PROFILE.md round 10 measured whole-process
stall episodes of hundreds of ms that swing every task-plane number
2-3x run to run; nothing in the tree could say what the loop was doing
when one hit. This module is that capability:

1. **Event ring.** A fixed-capacity ring of the most recent events
   ``(t_monotonic, tid, category, label, dur_us, arg)``, written
   lock-free (single list store per event; racing writers on distinct
   threads are GIL-benign exactly like ``attribution.record`` — a rare
   collision loses one event, never corrupts). Hot-path call sites
   guard with the module-level ``enabled`` bool, same zero-cost-off
   discipline as ``attribution.enabled`` — when the recorder is off a
   call site pays one global load. Unlike attribution (off by default,
   an explicit profiling mode) the flight recorder defaults ON: its
   purpose is to already hold the evidence when an *unplanned* episode
   hits. The perf guard (`tests/test_perf_guards.py::
   test_flight_recorder_overhead`) pins the "cheap when on" claim to
   <=10% of tasks/s.

2. **GC source.** ``install_gc_hook`` registers a `gc.callbacks` pair:
   every collection becomes one event with generation + duration — a
   gen-2 pause sitting exactly under a task-plane latency spike stops
   being a mystery.

3. **Loop-lag watchdog.** ``watch_loop(loop, name)`` schedules a
   heartbeat coroutine on the asyncio loop (it records its own
   scheduling delay whenever that exceeds 1 ms) and starts one
   monitor *thread* per process. When a loop's heartbeat goes overdue
   past ``stall_threshold_ms`` the monitor opens a **stall episode**
   — capturing an all-threads stack dump via ``sys._current_frames()``
   *while the loop is still blocked* (no py-spy dependency; this is
   what names the blocking frame) — and when the loop resumes it
   finalizes the episode: measured lag, the stack dump, and the
   surrounding ring events are written as a self-contained JSON report
   under the session log dir and kept in ``stalls()`` for the
   dashboard's ``/api/stalls``.

4. **Merged timeline.** ``dump()`` exports this process's ring with a
   wall<->monotonic clock anchor; ``to_chrome_trace`` merges any set
   of process dumps into one Chrome-trace/Perfetto JSON, aligning
   clocks through the anchors (the raylet's ``dump_flight_record`` RPC
   fans the dump out to its workers; the dashboard's ``/api/timeline``
   merges the cluster; ``python -m ray_tpu.perf --timeline`` brackets
   a bench burst and writes the file).

Event categories in the tree today: ``task`` (submit tiers, push RTT,
worker exec; round 16 adds ``caller_enq``/``caller_fallback`` instants
for the caller-thread dispatch tier and ``inline_revoked`` for the
cost-model-v2 pressure gate), ``lease`` (acquire wait / return),
``ring`` (SPSC enq/deq/doorbell traffic; round 16 adds ``handoff``
producer-ownership migrations, ``busy_poll`` spin windows, and the
raylet-side ``pin``/``unpin`` instants bracketing a worker's
ring-attached span), ``gc`` (collector pauses), ``loop`` (heartbeat
scheduling delays), ``stall`` (finalized episodes), ``engine`` (serve
decode/prefill steps).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

ENV_FLAG = "RAY_TPU_FLIGHT_RECORDER"

# How overdue (vs stall_threshold_ms) a heartbeat must be before the
# monitor opens an episode, and how often the monitor checks. The check
# period bounds detection latency: a stall shorter than one check can
# slip by (the heartbeat's own lag event still records it).
_MONITOR_PERIOD_S = 0.02

# Heartbeat delays under this are normal scheduler jitter — recording
# them would wash task events out of the ring at 20 Hz per loop.
_LAG_RECORD_FLOOR_US = 1000

# Bounded forensics: episodes kept in memory / reports written per
# process (a wedged box must not fill its disk with reports).
_MAX_STALLS = 32
_MAX_REPORTS = 64


def _env_enabled() -> bool:
    v = os.environ.get(ENV_FLAG)
    if v is None:
        return True
    return v.strip().lower() in ("1", "true", "yes", "on")


# Module-level guard, read directly by hot-path call sites:
#   if flight.enabled: flight.record(...)
enabled = _env_enabled()

# Wall<->monotonic anchor for cross-process clock alignment: an event's
# wall time is t_mono - anchor_mono + anchor_wall. Captured once per
# process (both reads back to back, so the pair is self-consistent).
_anchor_wall = time.time()
_anchor_mono = time.monotonic()

_capacity = 4096
_ring: List[Any] = [None] * _capacity
_idx = 0   # total events ever recorded (mod nothing; slot = _idx % cap)

_stall_threshold_ms = 100.0
_heartbeat_s = 0.05
_report_dir: Optional[str] = None
_reports_written = 0

_meta: Dict[str, Any] = {"role": "unknown", "worker_id": None,
                         "node_id": None}

_stalls: List[Dict[str, Any]] = []
_loops: Dict[str, Dict[str, Any]] = {}
_monitor_thread: Optional[threading.Thread] = None
_lock = threading.Lock()   # cold-path state only (loops, stalls, config)


# ----------------------------------------------------------------------
# recording
# ----------------------------------------------------------------------
def record(category: str, label: str, dur_us: int = 0,
           arg: Any = None, t: Optional[float] = None) -> None:
    """Fold one event into the ring. `t` is the event START in
    time.monotonic seconds (defaults to now); `dur_us` > 0 renders as a
    duration slice in the merged trace, 0 as an instant. `arg` must be
    JSON/msgpack-scalar (str/int/float/None) — it rides RPC dumps.

    Lock-free: one counter bump + one list store. Racing threads can
    collide on a slot (one event lost) or undercount — the benign-race
    trade attribution.record documents, taken for the same reason.
    """
    global _idx
    if not enabled:
        return
    i = _idx
    _idx = i + 1
    # Slot derived from the captured list's own length (not _capacity):
    # a concurrent configure() swap can lose this event but can never
    # index out of range.
    ring = _ring
    ring[i % len(ring)] = (
        t if t is not None else time.monotonic(),
        threading.get_ident(), category, label, int(dur_us), arg)


def instant(category: str, label: str, arg: Any = None) -> None:
    record(category, label, 0, arg)


def enable() -> None:
    """Turn the recorder on for this process AND processes spawned
    after this call (children read the env flag)."""
    global enabled
    enabled = True
    os.environ[ENV_FLAG] = "1"


def disable() -> None:
    """Off for this process and subsequently spawned children. The env
    var is SET to 0 (not popped): the recorder defaults on, so absence
    means enabled."""
    global enabled
    enabled = False
    os.environ[ENV_FLAG] = "0"


def reset() -> None:
    """Clear the ring and captured episodes (tests; the ring otherwise
    never needs clearing — it overwrites itself)."""
    global _ring, _idx
    with _lock:
        _ring = [None] * _capacity
        _idx = 0
        _stalls.clear()


def configure(capacity: Optional[int] = None,
              stall_threshold_ms: Optional[float] = None,
              heartbeat_ms: Optional[float] = None,
              report_dir: Optional[str] = None) -> None:
    """Apply config (flight_events / stall_threshold_ms /
    flight_heartbeat_ms flags, called once at runtime construction).
    Resizing drops recorded events (a boot-time operation)."""
    global _ring, _idx, _capacity, _stall_threshold_ms, _heartbeat_s
    global _report_dir
    with _lock:
        if capacity is not None and capacity != _capacity:
            _capacity = max(16, int(capacity))
            _ring = [None] * _capacity
            _idx = 0
        if stall_threshold_ms is not None:
            _stall_threshold_ms = float(stall_threshold_ms)
        if heartbeat_ms is not None:
            _heartbeat_s = max(0.005, float(heartbeat_ms) / 1000.0)
        if report_dir is not None:
            _report_dir = report_dir


def set_role(role: str, worker_id: Optional[str] = None,
             node_id: Optional[str] = None) -> None:
    _meta["role"] = role
    if worker_id is not None:
        _meta["worker_id"] = worker_id
    if node_id is not None:
        _meta["node_id"] = node_id


# ----------------------------------------------------------------------
# reading
# ----------------------------------------------------------------------
def snapshot(window_s: Optional[float] = None,
             categories: Optional[set] = None) -> List[tuple]:
    """The ring's events, oldest first, optionally filtered to the last
    `window_s` seconds and/or a category set. Reads race writers
    benignly: a concurrent burst can overwrite the oldest slots
    mid-scan, so the result is sorted by timestamp before returning."""
    i = _idx
    ring = _ring
    cap = len(ring)
    n = min(i, cap)
    cutoff = (time.monotonic() - window_s) if window_s else None
    out = []
    for k in range(i - n, i):
        ev = ring[k % cap]
        if ev is None:
            continue
        if cutoff is not None and ev[0] < cutoff:
            continue
        if categories is not None and ev[2] not in categories:
            continue
        out.append(ev)
    out.sort(key=lambda e: e[0])
    return out


def dropped() -> int:
    """Events that have been overwritten (ever recorded - capacity)."""
    return max(0, _idx - _capacity)


def stalls() -> List[Dict[str, Any]]:
    """Finalized stall episodes, oldest first (bounded)."""
    with _lock:
        return list(_stalls)


def dump(window_s: Optional[float] = None,
         include_events: bool = True) -> Dict[str, Any]:
    """Self-contained process record for cross-process merging: ring
    events + clock anchor + identity + captured stall episodes (the
    payload of the `dump_flight_record` RPC)."""
    return {
        "pid": os.getpid(),
        "role": _meta["role"],
        "worker_id": _meta["worker_id"],
        "node_id": _meta["node_id"],
        "anchor_wall": _anchor_wall,
        "anchor_mono": _anchor_mono,
        "enabled": enabled,
        "dropped": dropped(),
        "events": ([list(e) for e in snapshot(window_s=window_s)]
                   if include_events else []),
        "stalls": [dict(s, events=None) for s in stalls()],
    }


# ----------------------------------------------------------------------
# GC source
# ----------------------------------------------------------------------
_gc_installed = False
_gc_t0 = 0.0


def _gc_callback(phase: str, info: Dict[str, Any]) -> None:
    # GC is stop-the-world for this process: one module global is
    # enough to pair start/stop.
    global _gc_t0
    if phase == "start":
        _gc_t0 = time.monotonic()
    elif phase == "stop":
        now = time.monotonic()
        if enabled:
            record("gc", f"gen{info.get('generation', '?')}",
                   dur_us=int((now - _gc_t0) * 1e6),
                   arg=info.get("collected", 0), t=_gc_t0)


def install_gc_hook() -> None:
    """Register the gc.callbacks pair (idempotent). The callback costs
    two clock reads per collection — nothing on the allocation path."""
    global _gc_installed
    import gc

    with _lock:
        if _gc_installed:
            return
        gc.callbacks.append(_gc_callback)
        _gc_installed = True


def uninstall_gc_hook() -> None:
    global _gc_installed
    import gc

    with _lock:
        if not _gc_installed:
            return
        try:
            gc.callbacks.remove(_gc_callback)
        except ValueError:
            pass
        _gc_installed = False


# ----------------------------------------------------------------------
# loop-lag watchdog
# ----------------------------------------------------------------------
def watch_loop(loop, name: str) -> str:
    """Start a heartbeat on `loop` and ensure the monitor thread runs.
    Returns a handle for `unwatch_loop`. Re-watching a name replaces
    the old entry (a fresh runtime after shutdown/init)."""
    entry = {
        "name": name,
        "loop": loop,
        "period": _heartbeat_s,
        "last_beat": time.monotonic(),
        "thread_ident": None,
        "stop": False,
        # episode state, owned by the monitor thread:
        "open": False,
        "stalled_since": 0.0,
        "frames": None,
    }
    with _lock:
        old = _loops.get(name)
        if old is not None:
            old["stop"] = True
        _loops[name] = entry
    _ensure_monitor()

    async def _beat() -> None:
        entry["thread_ident"] = threading.get_ident()
        while not entry["stop"] and not loop.is_closed():
            entry["last_beat"] = time.monotonic()
            try:
                import asyncio

                await asyncio.sleep(entry["period"])
            except Exception:
                return
            lag = time.monotonic() - entry["last_beat"] - entry["period"]
            lag_us = int(lag * 1e6)
            if enabled and lag_us > _LAG_RECORD_FLOOR_US:
                record("loop", f"lag.{name}", dur_us=lag_us,
                       t=entry["last_beat"] + entry["period"])

    def _start() -> None:
        import asyncio

        entry["task"] = asyncio.ensure_future(_beat())

    try:
        loop.call_soon_threadsafe(_start)
    except RuntimeError:
        # Loop already closed: leave the entry stopped so the monitor
        # skips it.
        entry["stop"] = True
    return name


def unwatch_loop(name: str) -> None:
    with _lock:
        entry = _loops.pop(name, None)
    if entry is not None:
        entry["stop"] = True


def _ensure_monitor() -> None:
    global _monitor_thread
    with _lock:
        if _monitor_thread is not None and _monitor_thread.is_alive():
            return
        _monitor_thread = threading.Thread(
            target=_monitor_loop, daemon=True, name="flight-watchdog")
        _monitor_thread.start()


def _capture_stacks(skip_ident: Optional[int] = None) -> Dict[str, Any]:
    """All-threads stack dump via sys._current_frames() — captured from
    the monitor thread WHILE the watched loop is still blocked, so the
    blocking frame itself is on its thread's stack. No py-spy, no
    subprocess: the forensic must work inside the wedged process."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        if ident == skip_ident:
            continue
        out[str(ident)] = {
            "name": names.get(ident, "?"),
            "frames": traceback.format_stack(frame),
        }
    return out


def _monitor_loop() -> None:
    my_ident = threading.get_ident()
    while True:
        time.sleep(_MONITOR_PERIOD_S)
        now = time.monotonic()
        with _lock:
            entries = list(_loops.values())
        for entry in entries:
            if entry["stop"]:
                continue
            beat = entry["last_beat"]
            overdue_ms = (now - beat - entry["period"]) * 1e3
            if not entry["open"]:
                if overdue_ms > _stall_threshold_ms:
                    # The loop is blocked RIGHT NOW: capture the stacks
                    # before it resumes — this is the whole reason the
                    # monitor is a thread and not a coroutine.
                    entry["open"] = True
                    entry["stalled_since"] = beat
                    try:
                        entry["frames"] = _capture_stacks(my_ident)
                    except Exception:
                        entry["frames"] = {}
            elif beat > entry["stalled_since"]:
                # Heartbeat moved: the loop resumed. Finalize.
                frames = entry["frames"]
                entry["open"] = False
                entry["frames"] = None
                lag_ms = (beat - entry["stalled_since"]
                          - entry["period"]) * 1e3
                try:
                    _finalize_stall(entry, lag_ms, frames)
                except Exception:
                    pass  # forensics must never hurt the process


def report_dir() -> str:
    global _report_dir
    if _report_dir is None:
        _report_dir = os.environ.get("RAY_TPU_LOG_DIR") or \
            "/tmp/ray_tpu_flight"
    os.makedirs(_report_dir, exist_ok=True)
    return _report_dir


def _finalize_stall(entry: Dict[str, Any], lag_ms: float,
                    frames: Optional[Dict[str, Any]]) -> None:
    global _reports_written
    t_end = time.monotonic()
    episode = {
        "ts_wall": _anchor_wall + (t_end - _anchor_mono),
        "loop": entry["name"],
        "pid": os.getpid(),
        "role": _meta["role"],
        "worker_id": _meta["worker_id"],
        "node_id": _meta["node_id"],
        "lag_ms": round(lag_ms, 1),
        "threshold_ms": _stall_threshold_ms,
        "loop_thread": str(entry.get("thread_ident")),
        "stacks": frames or {},
        # The surrounding ring events — what the process was doing in
        # the seconds leading into (and out of) the episode.
        "events": [list(e) for e in snapshot(window_s=10.0)],
        "dropped": dropped(),
        "report_path": None,
    }
    if _reports_written < _MAX_REPORTS:
        _reports_written += 1
        path = os.path.join(
            report_dir(),
            f"stall-{_meta['role']}-{os.getpid()}-"
            f"{_reports_written}.json")
        try:
            with open(path, "w") as f:
                json.dump(episode, f, indent=1, default=str)
            episode["report_path"] = path
        except OSError:
            pass
    with _lock:
        _stalls.append(episode)
        del _stalls[:-_MAX_STALLS]
    # The episode itself becomes a ring event, so a later, larger dump
    # shows stalls inline with the traffic they interrupted.
    record("stall", f"stall.{entry['name']}", dur_us=int(lag_ms * 1e3),
           arg=episode["report_path"], t=entry["stalled_since"])


# ----------------------------------------------------------------------
# merged Chrome-trace export
# ----------------------------------------------------------------------
def to_chrome_trace(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-process dump() records into one Chrome-trace JSON
    (chrome://tracing, Perfetto). Clock alignment: each record carries
    its own wall<->monotonic anchor, so every event maps onto the
    shared wall clock regardless of per-process monotonic epochs; the
    earliest event becomes ts=0. pid/tid map to the real process/thread
    ids with `process_name` metadata naming role/worker/node."""
    events: List[Dict[str, Any]] = []
    base_wall: Optional[float] = None
    walls = []
    for rec in records:
        if not isinstance(rec, dict):
            continue
        off = rec.get("anchor_wall", 0.0) - rec.get("anchor_mono", 0.0)
        walls.extend(ev[0] + off for ev in rec.get("events", ()))
    base_wall = min(walls) if walls else 0.0
    for rec in records:
        if not isinstance(rec, dict):
            continue
        pid = rec.get("pid", 0)
        role = rec.get("role") or "proc"
        wid = rec.get("worker_id") or ""
        nid = rec.get("node_id") or ""
        pname = f"{role}" + (f" {wid[:8]}" if wid else "") + \
            f" pid={pid}" + (f" @{nid[:8]}" if nid else "")
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": pname}})
        off = rec.get("anchor_wall", 0.0) - rec.get("anchor_mono", 0.0)
        for ev in rec.get("events", ()):
            t, tid, cat, label, dur, arg = ev[:6]
            e: Dict[str, Any] = {
                "name": label, "cat": cat, "pid": pid, "tid": tid,
                "ts": round((t + off - base_wall) * 1e6, 1),
            }
            if dur and dur > 0:
                e["ph"] = "X"
                e["dur"] = dur
            else:
                e["ph"] = "i"
                e["s"] = "t"
            if arg is not None:
                e["args"] = {"arg": arg}
            events.append(e)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": {"tool": "ray_tpu flight recorder",
                         "processes": len(records)}}


def write_chrome_trace(records: List[Dict[str, Any]],
                       path: str) -> str:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(records), f)
    return path
