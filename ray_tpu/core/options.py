"""Validation/normalization of `@remote(...)` / `.options(...)` arguments.

Reference equivalent: `python/ray/_private/ray_option_utils.py` — one table of
allowed options for tasks vs actors with type checks and defaults.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

_COMMON_OPTIONS = {
    "num_cpus", "num_gpus", "resources", "memory", "accelerator_type",
    "runtime_env", "scheduling_strategy", "_metadata", "name", "namespace",
    "lifetime", "max_concurrency", "num_returns", "max_retries",
    "retry_exceptions", "max_restarts", "max_task_retries",
    "placement_group", "placement_group_bundle_index",
    "placement_group_capture_child_tasks", "max_pending_calls",
    "concurrency_groups", "enable_task_events", "label_selector",
}

TASK_ONLY = {"num_returns", "max_retries", "retry_exceptions"}
ACTOR_ONLY = {"max_restarts", "max_task_retries", "name", "namespace",
              "lifetime", "max_concurrency", "max_pending_calls",
              "concurrency_groups"}


@dataclass
class TaskOptions:
    num_cpus: float = 1.0
    num_gpus: float = 0.0
    resources: Dict[str, float] = field(default_factory=dict)
    memory: Optional[int] = None
    num_returns: Any = 1  # int | "streaming" | "dynamic"
    max_retries: int = 3
    retry_exceptions: Any = False
    runtime_env: Optional[dict] = None
    scheduling_strategy: Any = None
    placement_group: Any = None  # PlacementGroup | pg_id hex | None
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False
    enable_task_events: bool = True
    label_selector: Optional[dict] = None
    accelerator_type: Optional[str] = None
    _metadata: Optional[dict] = None


@dataclass
class ActorOptions:
    # None (unlike tasks): an actor with unspecified num_cpus needs 1 CPU to
    # be placed but 0 while running (reference: ray_option_utils actor
    # defaults).
    num_cpus: Optional[float] = None
    num_gpus: float = 0.0
    resources: Dict[str, float] = field(default_factory=dict)
    memory: Optional[int] = None
    name: Optional[str] = None
    namespace: Optional[str] = None
    lifetime: Optional[str] = None  # None | "detached" | "non_detached"
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: Optional[int] = None
    max_pending_calls: int = -1
    concurrency_groups: Optional[dict] = None
    runtime_env: Optional[dict] = None
    scheduling_strategy: Any = None
    placement_group: Any = None  # PlacementGroup | pg_id hex | None
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False
    enable_task_events: bool = True
    label_selector: Optional[dict] = None
    accelerator_type: Optional[str] = None
    _metadata: Optional[dict] = None


def _validate(updates: Dict[str, Any], *, for_actor: bool) -> None:
    for k in updates:
        if k not in _COMMON_OPTIONS:
            raise ValueError(f"Invalid option keyword: '{k}'")
        if for_actor and k in TASK_ONLY:
            raise ValueError(f"Option '{k}' is not valid for actors")
        if not for_actor and k in ACTOR_ONLY:
            raise ValueError(f"Option '{k}' is not valid for tasks")
    nr = updates.get("num_returns")
    if nr is not None and not (
            isinstance(nr, int) and nr >= 0) and nr not in ("streaming", "dynamic"):
        raise ValueError(f"num_returns must be int>=0 or 'streaming'/'dynamic', got {nr!r}")
    groups = updates.get("concurrency_groups")
    if groups:
        if not isinstance(groups, dict) or not all(
                isinstance(k, str) and isinstance(v, int) and v > 0
                for k, v in groups.items()):
            raise ValueError(
                "concurrency_groups must be {group_name: max_concurrency "
                "(int > 0)}")


def task_options(updates: Dict[str, Any],
                 base: Optional[TaskOptions] = None) -> TaskOptions:
    _validate(updates, for_actor=False)
    import dataclasses
    opts = dataclasses.replace(base) if base else TaskOptions()
    for k, v in updates.items():
        setattr(opts, k, v)
    if opts.num_cpus is None:
        opts.num_cpus = 1.0
    return opts


def actor_options(updates: Dict[str, Any],
                  base: Optional[ActorOptions] = None) -> ActorOptions:
    _validate(updates, for_actor=True)
    import dataclasses
    opts = dataclasses.replace(base) if base else ActorOptions()
    for k, v in updates.items():
        setattr(opts, k, v)
    return opts


class OptionsProxy:
    """Returned by `.options(...)`: a rebindable target with overridden opts.

    `submit(args, kwargs, opts)` is supplied by the owner; `bind` builds a DAG
    node when the owner supports it.
    """

    def __init__(self, submit, bind=None):
        self._submit = submit
        self._bind = bind

    def remote(self, *args, **kwargs):
        return self._submit(args, kwargs)

    def bind(self, *args, **kwargs):
        if self._bind is None:
            raise AttributeError("bind() is not supported on this target")
        return self._bind(args, kwargs)


def resource_demand(opts) -> Dict[str, float]:
    """Flatten options into a resource demand map {resource: amount}."""
    demand: Dict[str, float] = {}
    if opts.num_cpus:
        demand["CPU"] = float(opts.num_cpus)
    if opts.num_gpus:
        demand["GPU"] = float(opts.num_gpus)
    if opts.memory:
        demand["memory"] = float(opts.memory)
    for k, v in (opts.resources or {}).items():
        if v:
            demand[k] = float(v)
    return demand
