"""`@remote` functions.

Reference equivalent: `python/ray/remote_function.py` (`RemoteFunction` at
`:40`, `._remote` at `:261`): a decorated function gains `.remote(*a, **kw)`
returning ObjectRef(s), and `.options(**opts)` for per-call overrides.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu.core.options import TaskOptions, task_options


class FunctionDescriptor:
    """Stable identity of a remote function: module + qualname + a pickle of
    the function exported once per job (reference: function_manager.py:228
    export over GCS KV, keyed by a function hash)."""

    __slots__ = ("module", "qualname", "function_hash")

    def __init__(self, module: str, qualname: str, function_hash: bytes):
        self.module = module
        self.qualname = qualname
        self.function_hash = function_hash

    def key(self) -> bytes:
        return self.function_hash

    def __repr__(self):
        return f"FunctionDescriptor({self.module}.{self.qualname})"


class RemoteFunction:
    def __init__(self, function, options_dict: Optional[Dict[str, Any]] = None):
        if not callable(function):
            raise TypeError("@remote must decorate a callable")
        self._function = function
        self._default_options = task_options(options_dict or {})
        self._descriptor: Optional[FunctionDescriptor] = None
        functools.update_wrapper(self, function)

    @property
    def _function_name(self) -> str:
        return getattr(self._function, "__qualname__", repr(self._function))

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._function_name}' cannot be called "
            "directly. Use '.remote()'."
        )

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._default_options)

    def options(self, **updates):
        from ray_tpu.core.options import OptionsProxy
        new_opts = task_options(updates, base=self._default_options)
        return OptionsProxy(
            submit=lambda args, kwargs: self._remote(args, kwargs, new_opts),
            bind=lambda args, kwargs: self._bind_node(args, kwargs, new_opts))

    def _bind_node(self, args, kwargs, opts):
        from ray_tpu.dag import FunctionNode
        return FunctionNode(self, args, kwargs, opts)

    def bind(self, *args, **kwargs):
        """Lazy DAG-node construction (reference: python/ray/dag)."""
        from ray_tpu.dag import FunctionNode
        return FunctionNode(self, args, kwargs, self._default_options)

    def _remote(self, args, kwargs, opts: TaskOptions):
        from ray_tpu.core.worker import current_runtime
        rt = current_runtime()
        return rt.submit_task(self, opts, args, kwargs)


def remote(*args, **kwargs):
    """The `@remote` decorator for both functions and classes.

    Usage:
        @remote
        def f(): ...
        @remote(num_cpus=2, num_gpus=0, resources={"TPU": 4})
        def g(): ...
        @remote
        class A: ...
    """
    from ray_tpu.core.actor import ActorClass

    if len(args) == 1 and not kwargs and callable(args[0]):
        target = args[0]
        if isinstance(target, type):
            return ActorClass(target, {})
        return RemoteFunction(target)
    if args:
        raise TypeError("@remote takes only keyword arguments")

    def decorator(target):
        if isinstance(target, type):
            return ActorClass(target, kwargs)
        return RemoteFunction(target, kwargs)

    return decorator
