"""Worker process entry point.

Reference equivalent: `python/ray/_private/workers/default_worker.py` +
`Worker.main_loop` (`_private/worker.py:799`): construct the core-worker
runtime in worker mode, register with the raylet, and serve task pushes
until told to exit.

Round 10: a worker is no longer a pure RPC server. When its lease's
driver attaches a worker-direct dispatch ring (`submit_ring` mode,
`cluster_runtime.handle_attach_task_ring`), the runtime's event loop
also consumes task-spec deltas straight off the shared-memory ring —
doorbell-fd wakeups plus an adaptive backstop poll — and feeds them
through the same `_execute_task` path the RPC pushes take, with replies
riding the twin ring. Steady state, dispatch costs this process zero
syscalls per task in each direction.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet", required=True)
    parser.add_argument("--gcs", required=True)
    parser.add_argument("--worker-id", required=True)
    parser.add_argument("--node-id", required=True)
    args = parser.parse_args()

    logging.basicConfig(
        level=logging.INFO,
        format=f"[worker {args.worker_id[:8]}] %(message)s")

    # SIGUSR1 dumps all thread stacks to stderr (the worker log file):
    # the debugging affordance for "worker stuck in what?" (reference:
    # ray stack / py-spy integration).
    import faulthandler

    faulthandler.register(signal.SIGUSR1, all_threads=True, chain=False)

    # Task workers must not initialize the host's TPU runtime unless their
    # lease grants chips (site PJRT plugins ignore JAX_PLATFORMS, so this
    # is a config-level pin applied lazily at jax import).
    from ray_tpu.core.jax_platform import pin_worker_platform

    pin_worker_platform()

    from ray_tpu.core.cluster_runtime import ClusterRuntime
    from ray_tpu.core.worker import set_runtime

    runtime = ClusterRuntime(
        gcs_address=args.gcs, raylet_address=args.raylet, mode="worker",
        node_id=args.node_id, worker_id=args.worker_id)
    set_runtime(runtime)

    ok = runtime._loop.run(runtime._raylet.call(
        "register_worker", worker_id=args.worker_id,
        address=runtime.address))
    if not ok:
        logging.error("raylet rejected registration; exiting")
        sys.exit(1)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    # Watchdog: a worker must not outlive its raylet (reference: workers
    # exit on raylet socket EOF, node_manager disconnect handling).
    while not stop.wait(timeout=1.0):
        if not runtime._raylet.connected:
            logging.info("raylet connection lost; exiting")
            break
    # Graceful shutdown can wedge on non-daemon task threads (a user task
    # blocked in get() against a dying cluster); the process must still
    # exit promptly or it orphans past the raylet's kill window. Arm a
    # hard-exit backstop, attempt the clean path, then force the issue.
    import os

    killer = threading.Timer(3.0, lambda: os._exit(1))
    killer.daemon = True
    killer.start()
    try:
        runtime.shutdown()
    except BaseException:
        logging.exception("shutdown failed")
        os._exit(1)
    os._exit(0)


if __name__ == "__main__":
    main()
