"""Seeded deterministic fault injection at the RPC boundary.

Reference coverage class: the chaos tooling around
`release/nightly_tests/setup_chaos.py` and gRPC fault-injection
interceptors — but deterministic: every decision is a pure function of
(seed, rule, edge, per-edge message index), so a failure found under a
schedule is a *failing seed*, not an anecdote. Re-running the same seed
against the same workload replays the identical fault schedule.

Two consumption points:

- `core/simcluster.py` routes every simulated RPC through
  `FaultPlan.apply()` with explicit (src, dst) identities — the scale
  harness's whole fault surface.
- `core/rpc.py` consults the module-level `plan` (when `enabled`) on the
  real client call path and server dispatch path, so socket clusters can
  be driven with the same rules (e.g. tests/test_gcs_ft.py delays
  `commit_bundle` to land a GCS kill between the 2PC phases). Zero-cost
  when off: one module-global bool test per call.

Rule semantics (all matching is (src, dst, method) with "*" wildcards,
applied in registration order; several rules can fire on one message):

- drop      — the message never arrives; the caller sees ConnectionLost
              (the transport signal every retry path already handles).
- delay     — delivery is postponed `delay_s` seconds.
- duplicate — the server dispatches the message twice (at-least-once
              delivery; flushes out non-idempotent handlers).
- partition — a one-way cut: every src->dst message drops until healed.
- crash     — when dst has received its nth matching message, a crash
              callback fires (simcluster kills the component; real
              clusters can os.kill) and the message is lost.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.core.rpc import ConnectionLost

__all__ = ["FaultPlan", "FaultAction", "FaultInjected", "enabled",
           "install", "uninstall", "get_plan"]

# Module-level switch consumed by core/rpc.py. Off by default; install()
# flips it. Kept as a plain bool so the hot path pays one attribute load.
enabled = False
_plan: Optional["FaultPlan"] = None


@dataclass
class FaultAction:
    """One applied (or scheduled) fault, for the replay log."""
    kind: str
    src: str
    dst: str
    method: str
    n: int            # per-edge message index the decision keyed on
    arg: Any = None

    def key(self) -> Tuple:
        return (self.kind, self.src, self.dst, self.method, self.n)


@dataclass
class _Rule:
    kind: str                      # drop | delay | duplicate | partition | crash
    src: str = "*"
    dst: str = "*"
    method: str = "*"
    p: float = 1.0
    delay_s: float = 0.0
    after_n: int = 0               # crash: fire on the nth matching message
    start: int = 0                 # active for edge msg index >= start
    end: Optional[int] = None      # ... and < end
    active: bool = True            # partitions can be healed
    on_crash: Optional[Callable[[str], Any]] = None
    idx: int = 0                   # registration order, part of the seed
    # crash rules count matching messages per dst
    _crash_counts: Dict[str, int] = field(default_factory=dict)
    fired: bool = False

    def matches(self, src: str, dst: str, method: str, n: int) -> bool:
        if not self.active:
            return False
        if self.src != "*" and self.src != src:
            return False
        if self.dst != "*" and self.dst != dst:
            return False
        if self.method != "*" and self.method != method:
            return False
        if n < self.start or (self.end is not None and n >= self.end):
            return False
        return True


class FaultInjected(ConnectionLost):
    """Raised where a dropped message surfaces to the caller. Subclasses
    rpc.ConnectionLost so every transport-loss retry path treats it
    exactly like a dead socket."""


class FaultPlan:
    """A seeded, replayable schedule of RPC faults.

    Decisions are PURE: `decide(src, dst, method, n)` derives each
    rule's verdict from `random.Random(f"{seed}:{rule.idx}:{edge}:{n}")`
    — no RNG state is consumed across calls, so the schedule is
    identical regardless of async interleaving, retries, or wall time.
    `apply()` additionally tracks per-edge message counters and records
    what actually fired into `self.log`.
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self.rules: List[_Rule] = []
        self.log: List[FaultAction] = []
        self._edge_counts: Dict[Tuple[str, str], int] = {}

    # -- rule builders --------------------------------------------------
    def _add(self, rule: _Rule) -> _Rule:
        rule.idx = len(self.rules)
        self.rules.append(rule)
        return rule

    def drop(self, src: str = "*", dst: str = "*", method: str = "*",
             p: float = 0.01, start: int = 0,
             end: Optional[int] = None) -> _Rule:
        return self._add(_Rule("drop", src, dst, method, p=p,
                               start=start, end=end))

    def delay(self, src: str = "*", dst: str = "*", method: str = "*",
              p: float = 1.0, delay_s: float = 0.01, start: int = 0,
              end: Optional[int] = None) -> _Rule:
        return self._add(_Rule("delay", src, dst, method, p=p,
                               delay_s=delay_s, start=start, end=end))

    def duplicate(self, src: str = "*", dst: str = "*", method: str = "*",
                  p: float = 0.05, start: int = 0,
                  end: Optional[int] = None) -> _Rule:
        return self._add(_Rule("duplicate", src, dst, method, p=p,
                               start=start, end=end))

    def partition(self, src: str = "*", dst: str = "*") -> _Rule:
        """One-way cut src->dst (the reverse direction still flows);
        heal with `plan.heal(rule)`."""
        return self._add(_Rule("partition", src, dst, "*", p=1.0))

    def isolate(self, node: str,
                peers: Optional[List[str]] = None) -> List[_Rule]:
        """Two-way cut: `node` can neither reach nor be reached by each
        of `peers` (default: everyone). The building block for HA GCS
        partition scenarios — a minority-partitioned replica must stop
        winning elections, not just stop hearing the leader. Heal each
        returned rule to reconnect."""
        out: List[_Rule] = []
        for p in (list(peers) if peers else ["*"]):
            out.append(self.partition(node, p))
            out.append(self.partition(p, node))
        return out

    def heal(self, rule: _Rule) -> None:
        rule.active = False

    def crash_after(self, dst: str, n_messages: int, method: str = "*",
                    on_crash: Optional[Callable[[str], Any]] = None
                    ) -> _Rule:
        """Crash `dst` when it has received its `n_messages`th matching
        message. The callback receives dst (simcluster wires it to kill
        the component); the triggering message is lost either way."""
        return self._add(_Rule("crash", "*", dst, method,
                               after_n=int(n_messages), on_crash=on_crash))

    # -- pure decision function ----------------------------------------
    def _roll(self, rule: _Rule, src: str, dst: str, n: int) -> float:
        # str seeds hash via sha512: stable across processes and runs
        # (unlike hash(), which is salted per interpreter).
        return random.Random(
            f"{self.seed}:{rule.idx}:{src}>{dst}:{n}").random()

    def decide(self, src: str, dst: str, method: str,
               n: int) -> List[FaultAction]:
        """The fault schedule for message `n` on edge src->dst — pure,
        no state consumed (crash rules excepted: they key on the dst's
        receive count, tracked by apply())."""
        out: List[FaultAction] = []
        for rule in self.rules:
            if rule.kind == "crash" or not rule.matches(src, dst, method, n):
                continue
            if rule.p < 1.0 and self._roll(rule, src, dst, n) >= rule.p:
                continue
            arg = rule.delay_s if rule.kind == "delay" else None
            out.append(FaultAction(rule.kind, src, dst, method, n, arg))
        return out

    def preview(self, src: str, dst: str, method: str,
                n_messages: int) -> List[FaultAction]:
        """The full deterministic schedule for one edge's first
        `n_messages` messages — what the determinism test compares
        across plans built from the same seed."""
        out: List[FaultAction] = []
        for n in range(n_messages):
            out.extend(self.decide(src, dst, method, n))
        return out

    # -- application ----------------------------------------------------
    def next_index(self, src: str, dst: str) -> int:
        edge = (src, dst)
        n = self._edge_counts.get(edge, 0)
        self._edge_counts[edge] = n + 1
        return n

    async def apply(self, src: str, dst: str, method: str) -> bool:
        """Consume one message on edge src->dst. Sleeps for delays,
        raises FaultInjected for drops/partitions/crash-triggering
        messages, returns True when the message should be DUPLICATED at
        the receiver. Called before delivery."""
        import asyncio

        n = self.next_index(src, dst)
        duplicate = False
        for act in self.decide(src, dst, method, n):
            self.log.append(act)
            if act.kind == "delay":
                await asyncio.sleep(act.arg)
            elif act.kind in ("drop", "partition"):
                raise FaultInjected(
                    f"fault[{act.kind}] {src}->{dst} {method} #{n}")
            elif act.kind == "duplicate":
                duplicate = True
        # Crash rules: keyed on the dst's matching-receive count, not the
        # pure per-edge index (a crash is a property of the target).
        for rule in self.rules:
            if rule.kind != "crash" or rule.fired:
                continue
            if not rule.matches(src, dst, method, n):
                continue
            count = rule._crash_counts.get(dst, 0) + 1
            rule._crash_counts[dst] = count
            if count >= rule.after_n:
                rule.fired = True
                act = FaultAction("crash", src, dst, method, n)
                self.log.append(act)
                if rule.on_crash is not None:
                    res = rule.on_crash(dst)
                    if asyncio.iscoroutine(res):
                        await res
                raise FaultInjected(
                    f"fault[crash] {dst} on msg #{count} ({method})")
        return duplicate

    def log_keys(self) -> List[Tuple]:
        return [a.key() for a in self.log]


# -- module-level hooks for core/rpc.py ----------------------------------
def install(plan: FaultPlan) -> None:
    """Route the REAL RPC layer through `plan` (client calls keyed by
    peer address, server dispatch keyed by method). Process-local."""
    global enabled, _plan
    _plan = plan
    enabled = True


def uninstall() -> None:
    global enabled, _plan
    enabled = False
    _plan = None


def get_plan() -> Optional[FaultPlan]:
    return _plan


async def on_client_call(peer_address: str, method: str) -> None:
    """Hook on RpcClient.call (src = this process). Raises ConnectionLost
    via FaultInjected for drops so the caller's transport-loss handling
    engages."""
    plan = _plan
    if plan is None:
        return
    await plan.apply("client", peer_address, method)


async def on_server_dispatch(method: str) -> bool:
    """Hook on ServerConnection._dispatch; True means dispatch the
    handler twice (duplicate delivery)."""
    plan = _plan
    if plan is None:
        return False
    return await plan.apply("peer", "server", method)
