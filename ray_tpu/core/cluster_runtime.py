"""The distributed core-worker runtime, used by drivers AND workers.

Reference equivalent: `src/ray/core_worker/` — one library linked into every
process (`core_worker.h`): task submission over leased workers
(`direct_task_transport.cc`), direct actor transport, ownership + in-process
memory store (`memory_store.h`), plasma provider, and the owner-side object
directory (`ownership_based_object_directory.h`).

Call stack parity with SURVEY.md §3.2: submit_task -> lease from raylet
(spillback honored) -> push_task direct to the leased worker -> returns
inline (small) or sealed into the node store (large) -> owner records
locations; `get` merges the memory store and shm store and pulls remote
copies through the local raylet.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import contextlib
import logging
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle
import msgpack

from ray_tpu.core import attribution, flight, serialization
from ray_tpu.core.config import ray_config
from ray_tpu.core.function_manager import FunctionManager
from ray_tpu.core.gcs.client import GcsClient
from ray_tpu.core.generator import ObjectRefGenerator
from ray_tpu.core.ids import (ActorID, JobID, NodeID, ObjectID, TaskID,
                              WorkerID, _Counter)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.object_store import WorkerStoreClient, _WriteIntoShm
from ray_tpu.core.runtime_env import env_hash
from ray_tpu.core.wire import (ActorTaskSpec as WireActorTaskSpec,
                               LeaseRequest as WireLeaseRequest,
                               SpecTemplate,
                               TaskSpec as WireTaskSpec, from_wire,
                               from_wire_fast, to_wire)
from ray_tpu.core import lineage as lineage_mod
from ray_tpu.core.lineage import LineageTable
from ray_tpu.core.rpc import (ConnectionLost, EventLoopThread, RpcClient,
                              RpcError, RpcServer, ServerConnection)
from ray_tpu.util.tracing import (current_traceparent, span,
                                  tracing_enabled)
from ray_tpu.exceptions import (ActorDiedError, ActorUnavailableError,
                                GetTimeoutError, ObjectLostError,
                                OwnerDiedError, RayActorError, RayTaskError,
                                TaskCancelledError)

logger = logging.getLogger(__name__)

# Per-thread deserialization context (suppress_borrow while unpacking
# task args — the submitter pins those for the task's duration).
_deser_ctx = threading.local()

# "Not resolvable on this thread" sentinel for _read_resolved_local
# (None is a legitimate stored value).
_MISS = object()

INLINE_LIMIT_KEY = "max_direct_call_object_size"


async def schedule_placement_group(gcs, raylet_client_for, pg_id: str,
                                   info: dict, *, attempts: int = 8
                                   ) -> str:
    """Owner-led placement-group 2PC (reference:
    gcs_placement_group_scheduler.h, run from the creating worker here
    like actor placement): select nodes against the GCS view, PREPARE a
    reservation on each, COMMIT all on success, then CAS the group
    CREATED — rolling back every reservation of a failed attempt,
    committed ones included, so a crash anywhere in the protocol never
    leaks capacity.

    Factored out of ClusterRuntime so `core/simcluster.py` drives the
    IDENTICAL protocol over in-process loopback clients: the 100-node
    fault schedules exercise this code, not a re-implementation.

    `gcs` needs get_placement_group/get_nodes/update_placement_group;
    `raylet_client_for(address)` returns an object with `.call`.
    Returns the terminal state written ("CREATED"/"INFEASIBLE"), or
    the observed foreign state when someone else terminated the group
    (e.g. "REMOVED"), or "UNKNOWN" when the control plane stayed
    unreachable past every retry."""
    from ray_tpu.core import flight
    from ray_tpu.core.pg_scheduler import select_pg_nodes

    bundles = info["bundles"]
    detail = "no feasible placement"
    for attempt in range(attempts):
        try:
            # The user may have removed the group while we were
            # retrying; never resurrect it.
            current = await gcs.get_placement_group(pg_id)
            state = (current or {}).get("state")
            if state != "PENDING":
                return state or "UNKNOWN"
            nodes = [n for n in await gcs.get_nodes()
                     if n.get("alive")]
            placement = select_pg_nodes(bundles, nodes,
                                        info["strategy"],
                                        info.get("target_node_ids"))
            if placement is None:
                await asyncio.sleep(0.25 * (attempt + 1))
                continue
            prepared: List[Tuple[int, dict]] = []
            failure = None
            try:
                for idx, node in enumerate(placement):
                    client = await raylet_client_for(node["address"])
                    r = await client.call(
                        "prepare_bundle", pg_id=pg_id, bundle_index=idx,
                        resources=bundles[idx], timeout=10.0)
                    if not r.get("ok"):
                        failure = r.get("reason", "prepare rejected")
                        break
                    prepared.append((idx, node))
                if failure is None:
                    for idx, node in prepared:
                        client = await raylet_client_for(node["address"])
                        ok = await client.call("commit_bundle",
                                               pg_id=pg_id,
                                               bundle_index=idx,
                                               timeout=10.0)
                        if not ok:
                            # Reservation vanished between prepare and
                            # commit (raylet restart, concurrent
                            # return): a CREATED verdict over it would
                            # be a group nothing can lease against.
                            failure = (f"commit rejected for bundle "
                                       f"{idx}")
                            break
                if failure is None:
                    # CAS on PENDING, INSIDE the try: a CAS that raises
                    # must reach this attempt's rollback below — an
                    # escaped exception here once leaked every committed
                    # bundle when a later attempt landed on different
                    # nodes (invisible to the reconciler, which skips
                    # CREATED groups).
                    ok = await gcs.update_placement_group(pg_id, {
                        "state": "CREATED",
                        "bundle_locations": [
                            {"node_id": n["node_id"],
                             "address": n["address"]} for n in placement],
                    }, expect_state="PENDING")
                    if ok:
                        return "CREATED"
                    failure = "cas rejected"
            except Exception as e:  # noqa: BLE001
                failure = str(e)
            # CAS miss or error: only this owner ever writes CREATED, so
            # a CREATED read means OUR update applied (at-least-once
            # retry whose first ack was lost) — don't roll back a live
            # group. Any other state (REMOVED by the user, INFEASIBLE by
            # a reconciling raylet) means roll back and let the terminal
            # state stand.
            try:
                cur = await gcs.get_placement_group(pg_id)
                if (cur or {}).get("state") == "CREATED":
                    return "CREATED"
            except Exception:
                pass  # unreachable: roll back; the reconciler re-syncs
            # Roll back EVERYTHING reserved this attempt — including
            # already-committed bundles — or the reservation leaks
            # (neither the reaper nor remove would ever see it). A
            # rollback that cannot reach its raylet (node died
            # mid-2PC) is safe to skip: the dead node's ledger died
            # with it, and a NOT-dead-but-partitioned raylet returns
            # the orphan itself via _maybe_reconcile_bundles.
            detail = failure or "removed concurrently"
            if flight.enabled:
                flight.instant("pg", "pg.rollback",
                               arg=f"{pg_id[:8]} n={len(prepared)}")
            for idx, node in prepared:
                try:
                    client = await raylet_client_for(node["address"])
                    await client.call("return_bundle", pg_id=pg_id,
                                      bundle_index=idx, timeout=10.0)
                except Exception:
                    pass
            await asyncio.sleep(0.25 * (attempt + 1))
        except Exception as e:  # noqa: BLE001
            detail = str(e)
            await asyncio.sleep(0.25 * (attempt + 1))
    try:
        ok = await gcs.update_placement_group(
            pg_id, {"state": "INFEASIBLE", "detail": detail},
            expect_state="PENDING")
        if ok:
            return "INFEASIBLE"
        # CAS miss: someone else terminated the group (user remove, a
        # reconciling raylet) while we backed off — report the state
        # that actually stands, not a verdict that never wrote.
        cur = await gcs.get_placement_group(pg_id)
        return (cur or {}).get("state") or "UNKNOWN"
    except Exception:
        # Control plane unreachable for the whole schedule + final
        # verdict: raylet-side reconciliation returns any committed
        # bundles of the still-PENDING group after pg_stuck_commit_s.
        logger.warning("could not record INFEASIBLE for pg %s", pg_id,
                       exc_info=True)
        return "UNKNOWN"


def _pg_id_of(pg: Any) -> Optional[str]:
    """Normalize a placement-group option value (PlacementGroup object or
    hex id string) to the hex id, or None."""
    if pg is None:
        return None
    if isinstance(pg, str):
        return pg
    pid = getattr(pg, "id", None)
    if isinstance(pid, str):
        return pid
    if pid is not None and hasattr(pid, "hex"):
        return pid.hex()
    raise ValueError(f"invalid placement_group option: {pg!r}")


class _Owned:
    """Owner-side record of one object (reference: reference_count.h entry +
    memory-store slot)."""

    __slots__ = ("fut", "nodes", "refcount", "is_stored")

    def __init__(self):
        self.fut: concurrent.futures.Future = concurrent.futures.Future()
        self.nodes: List[str] = []
        self.refcount = 0
        self.is_stored = False  # True once sealed into a node store


class _ActorState:
    def __init__(self, actor_id_hex: str):
        self.actor_id_hex = actor_id_hex
        self.address: Optional[str] = None
        self.state = "PENDING"
        self.client: Optional[RpcClient] = None
        self.restarts_remaining = 0
        self.task_retries = 0     # max_task_retries (system failures)
        self.creation: Optional[dict] = None  # for owner-led restart
        self.lock = None  # asyncio.Lock, created lazily on the loop
        self.alive_event: Optional[object] = None
        self.restart_inflight = False  # guards concurrent restart attempts
        self.pinned_args: List[ObjectID] = []  # ctor-arg refs, pinned until DEAD


def _prepared_env(rt, opts):
    env = getattr(opts, "runtime_env", None)
    if not env:
        return None
    from ray_tpu.core.runtime_env import prepare_spec_env

    return prepare_spec_env(rt, env)


class _TaskCancelledBeforePush(Exception):
    """Internal: cancel() landed while the task was queued for a lease."""


class _WorkerOOMKilled(RpcError):
    """Internal: the raylet's memory monitor killed the worker mid-task.
    Retryable like any worker death, but surfaces as a typed
    OutOfMemoryError when retries run out (reference: the OOM task
    failure reason from worker_killing_policy.h)."""


class _LeasePool:
    """Per-scheduling-key worker leases (reference: direct_task_transport
    SchedulingKey entries + pipelined lease requests,
    max_pending_lease_requests_per_scheduling_category)."""

    @property
    def MAX_INFLIGHT(self) -> int:
        # Snapshot on first read: a config attribute read costs an
        # os.environ lookup, and this sits on the per-submit path.
        v = self._max_inflight
        if v is None:
            from ray_tpu.core.config import ray_config

            v = self._max_inflight = ray_config(
            ).max_pending_lease_requests_per_scheduling_category
        return v

    def __init__(self):
        self.idle: List[dict] = []
        # Grants expected from in-flight lease RPCs (a batched request
        # counts for its whole `count`); the RPC count itself is
        # bounded separately by MAX_INFLIGHT via inflight_rpcs.
        self.inflight_leases = 0
        self.inflight_rpcs = 0          # lease RPCs in flight to raylets
        self.waiters: List[Any] = []    # futures of queued acquires
        self.pump_scheduled = False     # a coalesced pump is queued
        self._max_inflight: Optional[int] = None


class _CallerTask:
    """Bookkeeping record for one caller-thread ring enqueue (round 16).

    The loop-hop path parks a per-task asyncio future in the ring's
    waiter map and resumes a coroutine per completion; the caller tier
    parks THIS record instead, and the reply-ring drain finishes the
    task inline on the loop thread — N completions per wakeup, zero
    future-resolution hops. Carries exactly what the completion (or the
    ConnectionLost retry resumption) needs."""

    __slots__ = ("spec", "refs", "pinned", "sched_key", "tmpl", "worker",
                 "fn_key", "args_len", "push_t0")

    def __init__(self, spec, refs, pinned, sched_key, tmpl, worker,
                 fn_key, args_len, push_t0):
        self.spec = spec
        self.refs = refs
        self.pinned = pinned
        self.sched_key = sched_key
        self.tmpl = tmpl
        self.worker = worker
        self.fn_key = fn_key
        self.args_len = args_len
        self.push_t0 = push_t0


# Inline cost model v2 (round 16): arg-size buckets for the per-fn exec
# EMA. Boundaries are coarse on purpose — the gate estimates sizes from
# raw args (pre-serialization) while the EMA keys on the serialized
# blob length, and wide buckets keep boundary-crossing mismatches rare.
_SIZE_BUCKETS = (1024, 16 * 1024, 256 * 1024)


def _size_bucket(nbytes: int) -> int:
    for i, bound in enumerate(_SIZE_BUCKETS):
        if nbytes <= bound:
            return i
    return len(_SIZE_BUCKETS)


class ClusterRuntime:
    is_local_mode = False

    # ==================================================================
    # construction
    # ==================================================================
    def __init__(self, *, gcs_address: str, raylet_address: str,
                 mode: str = "driver", worker_id: Optional[str] = None,
                 node_id: Optional[str] = None,
                 namespace: Optional[str] = None, node=None,
                 log_to_driver: bool = True):
        self.mode = mode
        self._log_to_driver = log_to_driver and mode == "driver"
        self.namespace = namespace or "default"
        self.gcs_address = gcs_address
        self.raylet_address = raylet_address
        self.job_id = JobID.from_int(os.getpid() % 2**31)
        self.worker_id = (WorkerID(bytes.fromhex(worker_id))
                          if worker_id and len(worker_id) == 56
                          else WorkerID.from_random())
        # The id the RAYLET knows this worker by (spawn-time id) — the
        # blocked/unblocked notifications key on it.
        self._raylet_worker_id = worker_id or self.worker_id.hex()
        self._blocked_depth = 0
        self._blocked_lock = threading.Lock()
        self.node_id = (NodeID(bytes.fromhex(node_id))
                        if node_id else None)
        self._node = node  # owned process supervisor (head driver only)

        self._loop = EventLoopThread(name=f"{mode}-rpc")
        # Must run on the importing (main) thread: signal.signal rejects
        # non-main threads, and _async_start runs on the loop thread.
        self._install_task_dumper()
        self._gcs = GcsClient(gcs_address)
        self._raylet = RpcClient(raylet_address)
        self._server = RpcServer(self)
        self._loop.run(self._async_start())

        self._shm = WorkerStoreClient()
        self._shm_by_oid: Dict[str, str] = {}  # fetched oid -> segment
        # Releases queued by ObjectRef finalizers (see deferred_release).
        from collections import deque as _deque

        self._pending_releases: Any = _deque()
        self._release_drain_scheduled = False
        # Submit coalescing (see submit_task): queued submissions drained
        # by ONE loop wakeup per burst instead of one self-pipe write per
        # task (a syscall that costs 20+ us on virtualized hosts).
        self._pending_submits: Any = _deque()
        self._submit_drain_scheduled = False
        # Template-spec caches (wire.SpecTemplate): invariant wire dicts
        # for repeated task/actor-method submissions, keyed by every
        # invariant field so an options/runtime-env change misses.
        self._spec_templates: Dict[tuple, Tuple[SpecTemplate, str]] = {}
        self._actor_templates: Dict[tuple, SpecTemplate] = {}
        # Node-local shm objects this process wrote (put path): get()
        # reads them back without the raylet pull_object round trip.
        self._local_shm: Dict[str, dict] = {}
        # Sharded puts: manifest oid -> shard oids (each shard holds one
        # reference released when the manifest entry dies).
        self._shard_children: Dict[str, List[str]] = {}
        # Syscall caches: getpid costs ~20 us on virtualized hosts and
        # the task path reads it 3x per task; config attribute reads do
        # an os.environ lookup each. Snapshot both per process.
        self._pid = os.getpid()
        cfg = ray_config()
        self._pipeline_depth = cfg.worker_pipeline_depth
        self._pipeline_svc_threshold = cfg.pipeline_service_threshold_s
        # Round-8 task-plane fast paths, each independently guarded:
        # same-process inline execution (cost-model gated), batched
        # lease grants, and the shm submission ring (see core/ring.py).
        self._inline_enabled = cfg.task_inline_execution
        self._inline_threshold_s = cfg.task_inline_threshold_ms / 1000.0
        self._lease_batching = cfg.lease_batching
        self._lease_batch_max = max(1, cfg.lease_batch_max)
        self._ring_enabled = cfg.submit_ring
        self._ring_slots = cfg.submit_ring_slots
        self._ring_slot_bytes = cfg.submit_ring_slot_bytes
        self._lease_return_batching = cfg.lease_return_batching
        # Round-16 caller-thread dispatch tier: the submitting thread
        # pushes template deltas onto an already-attached worker ring
        # directly (no loop hop), under per-ring ProducerLatch handoff.
        # Only meaningful on top of worker-direct rings.
        self._caller_dispatch = (cfg.task_caller_dispatch
                                 and self._ring_enabled)
        self._caller_push_wait_s = max(
            0.0, cfg.caller_push_wait_ms / 1000.0)
        self._busy_poll_s = max(0, cfg.ring_busy_poll_us) / 1e6
        # Round-16 inline cost model v2: arg-size-conditional EMAs +
        # revocation under caller-thread dispatch pressure.
        self._inline_v2 = cfg.inline_cost_model_v2
        self._inline_revoke_pressure = max(1, cfg.inline_revoke_pressure)
        self._inline_revoke_window_s = max(
            0.001, cfg.inline_revoke_window_ms / 1000.0)
        self._inline_revoked_until = 0.0
        self._caller_window_start = 0.0
        self._caller_window_count = 0
        # Caller-dispatch registry: sched_key -> {worker_id: (worker,
        # ring_st)} for ring-attached leased workers the caller thread
        # may target directly. Maintained by the loop thread (offer on
        # successful loop-path ring publish, removal in ring teardown);
        # read by caller threads under _caller_lock.
        self._caller_rings: Dict[str, dict] = {}
        self._caller_lock = threading.Lock()
        # Flight recorder (round 12): always-on event ring + loop-lag
        # watchdog on this process's RPC loop. The config flag gates
        # the whole subsystem per process (workers read it through the
        # inherited RAY_TPU_FLIGHT_RECORDER env; _system_config applies
        # driver-side only).
        if not cfg.flight_recorder:
            flight.enabled = False
        if flight.enabled:
            # Workers/raylets inherit RAY_TPU_LOG_DIR; the head driver
            # owns the session and points its reports at the same logs
            # dir, so every process's stall reports land together.
            flight.configure(capacity=cfg.flight_events,
                             stall_threshold_ms=cfg.stall_threshold_ms,
                             heartbeat_ms=cfg.flight_heartbeat_ms,
                             report_dir=(node.log_dir if node is not None
                                         else None))
            flight.set_role(mode, worker_id=self.worker_id.hex(),
                            node_id=node_id)
            flight.install_gc_hook()
            self._flight_watch = flight.watch_loop(
                self._loop.loop, name=f"{mode}-loop")
            if mode == "driver":
                # Workers reach the merged timeline through their
                # raylet's registration table; a driver must announce
                # itself or its submit-side ring (and its stall
                # episodes) never show up at /api/timeline.
                try:
                    self._loop.run(self._raylet.notify(
                        "register_flight_source", address=self.address),
                        timeout=5)
                except Exception:
                    pass  # observability must not fail bring-up
        else:
            self._flight_watch = None
        # Per-function exec-time EMA (seconds), fed by exec_us riding
        # every task reply and by inline runs; the inline gate admits
        # only functions whose EMA is KNOWN and below the threshold, so
        # a long or blocking task is never inlined on spec.
        self._fn_cost: Dict[str, float] = {}
        # Worker-direct dispatch rings (round 10): worker_id -> ring
        # state dict while live, False once that worker's pair failed
        # or died (RPC push path for the rest of the lease). Driver
        # side only; the worker side lives in conn.metadata of the
        # attaching connection (handle_attach_task_ring).
        self._worker_rings: Dict[str, Any] = {}
        self._worker_ring_setups: Dict[str, Any] = {}
        # Worker-mode: live task-ring states (for shutdown cleanup).
        self._task_rings: List[dict] = []
        # Batched lease returns (round 10): raylet address -> pending
        # batch, flushed by one deferred pump per burst.
        self._pending_lease_returns: Dict[str, dict] = {}
        # Strong refs for fire-and-forget ring/return tasks: the event
        # loop only keeps WEAK task references (the _BatchQueue
        # rationale) — a collected flush task would strand its batch's
        # awaiters and leak the leases at the raylet.
        self._ring_bg_tasks: set = set()
        # Every granted task lease, until returned — the lease watchdog
        # sweeps this for orphans (see _lease_watchdog).
        self._live_leases: List[dict] = []
        self._owned: Dict[str, _Owned] = {}
        self._owned_lock = threading.Lock()
        # Refs this process BORROWS (owner elsewhere): oid -> [owner
        # address, local count, owner-ACKed]; zero -> release_borrow.
        self._borrowed: Dict[str, list] = {}
        self._borrowed_lock = threading.Lock()
        self._generators: Dict[str, ObjectRefGenerator] = {}
        self._put_counter = _Counter()
        self._lease_pools: Dict[str, _LeasePool] = {}
        # cancel(): owner-side cancel flags + where each task is running
        # (address, is_actor_task).
        self._cancel_requested: set = set()
        self._inflight_task_workers: Dict[str, Tuple[str, bool]] = {}
        # worker-side: task_id -> executing thread ident (for async-raise)
        self._running_task_threads: Dict[str, int] = {}
        # worker-side: task_id -> run_coroutine_threadsafe future (async
        # actor methods cancel through the coroutine, not the thread)
        self._running_task_cfuts: Dict[str, Any] = {}
        # worker-side: cancels that arrived before their task started
        self._cancelled_pending: set = set()
        # worker-side actor sequencing: caller address -> {next, cond}
        self._actor_seq: Dict[str, dict] = {}
        # driver-side: actor_id -> next seq to stamp
        self._actor_call_seq: Dict[str, int] = {}
        self._actor_seq_lock = threading.Lock()
        self._raylet_clients: Dict[str, RpcClient] = {self.raylet_address:
                                                      self._raylet}
        self._actors: Dict[str, _ActorState] = {}
        self._actor_meta: Dict[str, Tuple[str, dict]] = {}
        self._fn = FunctionManager(
            kv_put=lambda k, v, ow: self._loop.run(
                self._gcs.kv_put(k, v, ow)),
            kv_get=lambda k: self._loop.run(self._gcs.kv_get(k)))

        # worker-mode execution state
        self._exec_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="task-exec")
        self._actor_instance: Any = None
        self._actor_executor: Optional[
            concurrent.futures.ThreadPoolExecutor] = None
        self._actor_group_executors: Dict[str, Any] = {}
        self._actor_loop = None
        self._actor_id_hex: Optional[str] = None
        self._shutdown = False

        self._job_envs_applied: set = set()
        self._job_env_lock = threading.Lock()
        self._pg_cache: Dict[str, dict] = {}
        self._pg_rr: Dict[str, int] = {}
        # Lineage: return-oid -> shared task record, kept while any return
        # ref lives so lost objects can be re-executed (reference:
        # task_manager.h:424 RetryTaskIfPossible + lineage pinning).
        # Policy (retention gate, budget, inflight dedup) lives in
        # core/lineage.py so the simcluster harness exercises the same
        # state machine.
        self._lineage = LineageTable()
        if mode == "driver":
            import sys
            # sys_path lets workers import driver-local modules (test files,
            # scripts) so functions pickle by reference (reference:
            # runtime-env working_dir / job_config code paths).
            self._loop.run(self._gcs.add_job(self.job_id.hex(), {
                "driver_pid": os.getpid(), "namespace": self.namespace,
                "sys_path": [p for p in sys.path if p],
                "cwd": os.getcwd()}))

    async def _async_start(self) -> None:
        await self._server.start()
        await self._gcs.connect()
        await self._raylet.connect()
        self.address = self._server.address
        self._event_flusher = asyncio.ensure_future(
            self._flush_task_events_loop())
        # Proactive location pruning: learn of node deaths from the GCS
        # instead of waiting for a puller to trip over a stale location
        # (reference: ownership-based object directory subscribes to
        # node removal).
        try:
            await self._gcs.subscribe("node", self._on_node_event)
        except Exception:
            logger.warning("node-event subscription failed", exc_info=True)
        self._lease_watchdog_task = asyncio.ensure_future(
            self._lease_watchdog())
        if self._log_to_driver:
            # Remote prints/tracebacks stream to this driver's stderr
            # (reference: _private/worker.py:812 print_logs over GCS
            # pubsub, fed by log_monitor.py:103 tails on each node).
            try:
                await self._gcs.subscribe("worker_logs",
                                          self._on_worker_logs)
            except Exception:
                logger.warning("worker-log subscription failed",
                               exc_info=True)
        self._start_metrics_push()

    def _on_worker_logs(self, data: dict) -> None:
        import sys

        if not isinstance(data, dict):
            return
        my_job = self.job_id.hex()
        for entry in data.get("entries", ()):
            job = entry.get("job_id")
            if job and job != my_job:
                continue  # another driver's worker
            tag = entry.get("actor_id") or entry.get("worker_id", "?")[:8]
            prefix = f"({tag}, pid={entry.get('pid', '?')})"
            for line in entry.get("lines", ()):
                print(f"{prefix} {line}", file=sys.stderr)

    def _install_task_dumper(self) -> None:
        """SIGUSR2 prints every asyncio task's stack on the RPC loop —
        faulthandler (SIGUSR1) shows only THREAD frames, and scheduling
        wedges live in coroutines (reference affordance: ray stack)."""
        import signal as _signal

        def _dump() -> None:
            import sys
            import traceback

            # sys.__stderr__: bypass pytest/driver capture so the dump
            # is visible even when the process dies before reporting.
            err = sys.__stderr__ or sys.stderr
            tasks = asyncio.all_tasks(self._loop.loop)
            print(f"=== {len(tasks)} asyncio tasks ===", file=err,
                  flush=True)
            for t in tasks:
                print(f"-- {t.get_coro()}", file=err, flush=True)
                for frame in t.get_stack(limit=4):
                    traceback.print_stack(frame, limit=1, file=err)

        def _on_sig(*_a) -> None:
            try:
                self._loop.call_soon(_dump)
            except Exception:
                pass

        try:
            _signal.signal(_signal.SIGUSR2, _on_sig)
        except (ValueError, OSError):
            pass  # not the main thread / unsupported: debug-only

    async def _on_node_event(self, data: dict) -> None:
        if not isinstance(data, dict) or data.get("alive", True):
            return
        node_id = data.get("node_id")
        addr = data.get("address")
        if not addr:
            # Older event shape: resolve via the node table.
            try:
                for n in await self._gcs.get_nodes():
                    if n.get("node_id") == node_id:
                        addr = n.get("address")
                        break
            except Exception:
                return
        if not addr:
            return
        # Drop cached placement-group location tables naming the dead
        # node: the GCS is rescheduling those bundles, and the next
        # _pg_location refetches (waiting out RESCHEDULING) instead of
        # leasing against a dead address forever.
        for pg_id, info in list(self._pg_cache.items()):
            if any(loc.get("address") == addr or loc.get("node_id")
                   == node_id
                   for loc in info.get("bundle_locations") or []):
                self._pg_cache.pop(pg_id, None)
        lost = []
        with self._owned_lock:
            for oid, entry in self._owned.items():
                if addr in entry.nodes:
                    entry.nodes = [n for n in entry.nodes if n != addr]
                    if not entry.nodes and entry.is_stored:
                        lost.append(oid)
        for oid in lost:
            self._trigger_reconstruction(oid)

    def _start_metrics_push(self) -> None:
        """Flush this process's app metrics (`ray_tpu.util.metrics`) to
        the node's raylet on the configured interval (reference: the
        worker->metrics-agent export path). With the round-17 pipeline
        on, the same push carries the process's delta-encoded
        time-series batch; the raylet folds every process's batch into
        ONE payload on its next GCS heartbeat."""
        from ray_tpu.core import metrics_ts
        from ray_tpu.core.config import ray_config
        from ray_tpu.util.metrics import start_metrics_push

        wid = (self.worker_id.hex() if self.worker_id is not None
               else f"driver-{os.getpid()}")
        pipeline = metrics_ts.enabled and ray_config().metrics_pipeline
        if pipeline:
            metrics_ts.recorder().configure(ray_config().metrics_ts_ring)

        def push(snapshot):
            ts_batch = None
            if pipeline:
                metrics_ts.capture(snapshot)
                ts_batch = metrics_ts.pending() or None
            # Outer timeout bounds the push thread even when shutdown
            # halts the event loop mid-call (no future to resolve).
            self._loop.run(self._raylet.call(
                "report_metrics", worker_id=wid, snapshot=snapshot,
                ts_batch=ts_batch, timeout=5.0), timeout=10.0)
            if ts_batch:
                # Clear-on-ack: a raylet hiccup leaves the batch queued
                # (bounded ring) for the next interval's retry.
                metrics_ts.ack(len(ts_batch))

        start_metrics_push(
            push, ray_config().metrics_report_interval_ms / 1000.0)

    # -- task events (reference: task_event_buffer.h flush loop) --------
    def _record_task_event(self, task_id: str, name: str, event: str,
                           job_id: Optional[str] = None, **extra) -> None:
        from ray_tpu.core.task_events import task_event_buffer

        task_event_buffer().record(
            task_id, name, event, job_id=job_id or self.job_id.hex(),
            node_id=self.node_id.hex(), worker_id=self.address,
            pid=self._pid, **extra)

    async def _flush_task_events_loop(self) -> None:
        from ray_tpu.core.task_events import task_event_buffer

        while True:
            await asyncio.sleep(1.0)
            events = task_event_buffer().drain()
            if not events:
                continue
            try:
                await self._gcs.add_task_events(events)
            except Exception:
                pass  # GCS down: events drop (bounded-loss contract)

    def task_events(self, job_id: Optional[str] = None):
        """Flush this process's buffer and fetch the job's events from
        the GCS store (the single entry used by timeline + state API)."""
        from ray_tpu.core.task_events import task_event_buffer

        local = task_event_buffer().drain()
        if local:
            try:
                self._loop.run(self._gcs.add_task_events(local),
                               timeout=10)
            except Exception:
                pass
        return self._loop.run(
            self._gcs.get_task_events(job_id), timeout=30)

    def timeline(self, filename: Optional[str] = None):
        """Chrome-trace export of this job's task events (reference:
        ray timeline / state_api timeline)."""
        from ray_tpu.core.task_events import (events_to_chrome_trace,
                                              write_trace)

        trace = events_to_chrome_trace(
            self.task_events(self.job_id.hex()))
        return write_trace(trace, filename)

    # -- bring-up helpers ----------------------------------------------
    @classmethod
    def connect_or_start(cls, address: Optional[str] = None,
                         num_cpus: Optional[int] = None,
                         num_gpus: Optional[int] = None,
                         resources: Optional[dict] = None,
                         namespace: Optional[str] = None,
                         object_store_memory: Optional[int] = None,
                         log_to_driver: bool = True,
                         **_: Any) -> "ClusterRuntime":
        from ray_tpu.core.node import NodeSupervisor

        if address in (None, "local"):
            node = NodeSupervisor.start_head(
                num_cpus=num_cpus, num_gpus=num_gpus, resources=resources,
                object_store_memory=object_store_memory)
            return cls(gcs_address=node.gcs_address,
                       raylet_address=node.raylet_address,
                       namespace=namespace, node=node,
                       node_id=node.node_id,
                       log_to_driver=log_to_driver)
        if address.startswith("ray://"):
            address = address[len("ray://"):]
        # Connect to an existing cluster: find this machine's raylet (or the
        # head raylet) from the GCS node table. `address` may be an HA
        # replica set ("host:p0,host:p1,host:p2"): the probe and every
        # client built from it rotate the set and follow NOT_LEADER
        # redirects onto whichever replica currently leads.
        probe = GcsClient(address)
        loop = EventLoopThread(name="probe")
        try:
            loop.run(probe.connect())
            nodes = loop.run(probe.get_nodes())
            loop.run(probe.close())
        finally:
            loop.stop()
        alive = [n for n in nodes if n.get("alive")]
        if not alive:
            raise ConnectionError(f"no alive nodes at GCS {address}")
        head = next((n for n in alive if n.get("is_head")), alive[0])
        return cls(gcs_address=address, raylet_address=head["address"],
                   namespace=namespace, node_id=head["node_id"],
                   log_to_driver=log_to_driver)

    def check_alive(self) -> bool:
        """Cheap liveness probe: is our GCS still answering?

        Used by init(ignore_reinit_error=True) to avoid silently reusing a
        runtime whose cluster has been torn down (stale function caches,
        leaked leases). Reference contract: ray.init reconnects rather than
        reusing a dead worker (_private/worker.py:1152).
        """
        if self._shutdown:
            return False
        try:
            self._loop.run(self._gcs.get_nodes(), timeout=5)
            return True
        except Exception:
            return False

    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        if self._flight_watch is not None:
            # Stop the heartbeat before the loop dies: a stale entry
            # would read as a permanent stall to the watchdog thread.
            flight.unwatch_loop(self._flight_watch)
        try:
            from ray_tpu.util.metrics import stop_metrics_push

            stop_metrics_push()
        except Exception:
            pass
        try:
            if self.mode == "driver":
                self._loop.run(self._gcs.mark_job_finished(
                    self.job_id.hex()), timeout=2)
        except Exception:
            pass
        try:
            self._loop.run(self._server.stop(), timeout=2)
        except Exception:
            pass
        self._close_worker_rings()
        self._shm.close()
        self._exec_pool.shutdown(wait=False, cancel_futures=True)
        pool = getattr(self, "_cgraph_deposit_pool", None)
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)
        if self._node is not None:
            self._node.stop()
        self._loop.stop()

    # ==================================================================
    # ownership / reference counting
    # ==================================================================
    def _owned_entry(self, oid_hex: str) -> _Owned:
        with self._owned_lock:
            entry = self._owned.get(oid_hex)
            if entry is None:
                entry = _Owned()
                self._owned[oid_hex] = entry
            return entry

    def add_local_reference(self, object_id: ObjectID) -> None:
        oid = object_id.hex()
        with self._owned_lock:
            entry = self._owned.get(oid)
            if entry is not None:
                entry.refcount += 1
                return
        with self._borrowed_lock:
            if oid in self._borrowed:
                self._borrowed[oid][1] += 1

    def deferred_release(self, object_id: ObjectID) -> None:
        """Lock-free release entry point for ObjectRef.__del__.

        A finalizer can fire at ANY allocation in ANY thread — including
        while this runtime's own locks are held (observed: GC during
        handle_get_object_locations, which holds _owned_lock, fired a
        ref's __del__ whose remove_local_reference re-acquired
        _owned_lock and self-deadlocked the entire RPC loop). Finalizers
        therefore only APPEND (GIL-atomic) here; the real release runs
        on the event loop outside any lock."""
        self._pending_releases.append(object_id)
        if not self._release_drain_scheduled:
            self._release_drain_scheduled = True
            try:
                self._loop.call_soon(self._drain_releases)
            except Exception:
                pass  # loop stopping at shutdown: releases are moot

    def _drain_releases(self) -> None:
        self._release_drain_scheduled = False
        while self._pending_releases:
            try:
                self.remove_local_reference(
                    self._pending_releases.popleft())
            except IndexError:
                break
            except Exception:
                pass

    def remove_local_reference(self, object_id: ObjectID) -> None:
        if self._shutdown:
            return
        oid = object_id.hex()
        with self._owned_lock:
            entry = self._owned.get(oid)
            if entry is None:
                self._release_borrow(oid)
                return
            entry.refcount -= 1
            if entry.refcount > 0 or not entry.fut.done():
                return
            del self._owned[oid]
            nodes = list(entry.nodes)
        self._release_shm_mapping(oid)
        for child in self._shard_children.pop(oid, ()):
            # Shard objects live exactly as long as their manifest.
            self.remove_local_reference(ObjectID(bytes.fromhex(child)))
        lineage_pins = self._lineage.release(oid)
        if lineage_pins:
            # Last return ref gone: lineage no longer needs the task's
            # argument objects pinned.
            self._unpin_args(lineage_pins)
        if nodes:
            async def _delete():
                for addr in nodes:
                    try:
                        client = await self._raylet_client(addr)
                        await client.call("delete_objects", oids=[oid],
                                          timeout=5.0)
                    except Exception:
                        pass
            self._loop.spawn(_delete())

    def on_ref_deserialized(self, ref: ObjectRef) -> None:
        oid = ref.hex()
        with self._owned_lock:
            entry = self._owned.get(oid)
            if entry is not None:
                entry.refcount += 1
                return
        # A ref we do NOT own (e.g. embedded in a task's return value):
        # register a borrow with its owner so the object outlives the
        # owner process's own local references (reference:
        # reference_count.h borrowing protocol). The owner's escrow pin
        # (_escrow_pin) bridges the gap until this lands. Refs inside
        # TASK ARGS take a *local-only* pin instead — the submitter pins
        # them for the task's whole duration, so no owner RPC is needed
        # on the hot path; if the task retains the ref past completion,
        # _commit_arg_borrows upgrades the pin to a real owner-registered
        # borrow before the reply releases the submitter's pin
        # (reference: the borrowed-refs report in the task reply,
        # reference_count.h).
        owner = ref._owner
        if getattr(_deser_ctx, "suppress_borrow", False):
            if isinstance(owner, str) and owner != self.address:
                with self._borrowed_lock:
                    rec = self._borrowed.get(oid)
                    if rec is None:
                        # [owner, local count, owner ACKed the borrow]
                        self._borrowed[oid] = [owner, 1, False]
                    else:
                        rec[1] += 1
                collected = getattr(_deser_ctx, "arg_refs", None)
                if collected is not None:
                    collected.append((oid, owner))
            return
        if not isinstance(owner, str) or owner == self.address:
            return
        register = False
        with self._borrowed_lock:
            rec = self._borrowed.get(oid)
            if rec is None:
                # [owner, local count, owner ACKed the borrow]
                rec = self._borrowed[oid] = [owner, 1, False]
                register = True
            else:
                rec[1] += 1
        if register:
            async def _register(rec=rec):
                try:
                    client = await self._worker_client(owner)
                    ok = await client.call("register_borrow", oid=oid,
                                           timeout=30.0)
                except Exception:
                    return  # never ACKed: matching release stays local
                with self._borrowed_lock:
                    alive = self._borrowed.get(oid) is rec
                    if alive:
                        rec[2] = bool(ok)
                if not alive and ok:
                    # Released locally while the ACK was in flight: the
                    # owner counted us, so compensate now.
                    try:
                        await client.call("release_borrow", oid=oid,
                                          timeout=30.0)
                    except Exception:
                        pass

            self._loop.spawn(_register())

    def _release_shm_mapping(self, oid: str) -> None:
        """Unmap the local view of a fetched object once the last local
        reference drops; deferred (object_store._deferred) while
        deserialized zero-copy views still alias the mapping."""
        name = self._shm_by_oid.pop(oid, None)
        local = self._local_shm.pop(oid, None)
        if name is None and local is not None:
            # Locally-put object that was only ever read via the bypass:
            # release the probe attachment too.
            name = local["shm_name"]
        if name is not None:
            try:
                self._shm.release(name)
            except Exception:
                pass

    def _release_borrow(self, oid: str) -> None:
        with self._borrowed_lock:
            rec = self._borrowed.get(oid)
            if rec is None:
                return
            rec[1] -= 1
            if rec[1] > 0:
                return
            del self._borrowed[oid]
            owner = rec[0]
        self._release_shm_mapping(oid)
        if not rec[2]:
            # The owner never ACKed our register_borrow: sending a
            # release would decrement a count that was never
            # incremented (premature free at the owner).
            return

        async def _release():
            try:
                client = await self._worker_client(owner)
                await client.call("release_borrow", oid=oid, timeout=30.0)
            except Exception:
                pass

        self._loop.spawn(_release())

    async def handle_register_borrow(self, conn, *, oid: str) -> bool:
        """A remote process holds a ref to an object we own."""
        with self._owned_lock:
            entry = self._owned.get(oid)
            if entry is None:
                # Likely an escrow window that lapsed before the consumer
                # first deserialized the containing object — the borrow
                # cannot be honored and the consumer's get will fail.
                logger.warning(
                    "register_borrow for already-freed object %s "
                    "(escrow window borrow_escrow_s=%.0fs lapsed?)",
                    oid[:16], ray_config().borrow_escrow_s)
                return False
            entry.refcount += 1
        return True

    async def handle_release_borrow(self, conn, *, oid: str) -> bool:
        self.remove_local_reference(ObjectID(bytes.fromhex(oid)))
        return True

    # ==================================================================
    # objects: put / get / wait
    # ==================================================================
    def put(self, value: Any) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("Calling put() on an ObjectRef is not allowed.")
        from ray_tpu.util import device_arrays as _da

        if _da.is_multishard(value):
            return self._put_sharded(value)
        task_id = TaskID.for_task(self.job_id)
        object_id = ObjectID.for_put(task_id, self._put_counter.next())
        oid = object_id.hex()
        so = serialization.serialize(value)
        entry = self._owned_entry(oid)
        self._store_serialized(oid, so, entry)
        return ObjectRef(object_id, owner=self.address, runtime=self)

    def _put_sharded(self, value: Any) -> ObjectRef:
        """Sharded put of a multi-device jax.Array: exactly one store
        object per addressable shard (array-native format, no pickle)
        plus one manifest object; the returned ref names the manifest.
        Shard objects live exactly as long as the manifest object — each
        holds one reference released when the manifest entry dies."""
        from ray_tpu.util import device_arrays as _da

        task_id = TaskID.for_task(self.job_id)

        def store_shard(np_view) -> str:
            object_id = ObjectID.for_put(task_id, self._put_counter.next())
            oid = object_id.hex()
            so = serialization.serialize_array(np_view)
            entry = self._owned_entry(oid)
            entry.refcount += 1   # held by the manifest (child pin)
            self._store_serialized(oid, so, entry)
            return oid

        stored: List[str] = []

        def store_shard_tracked(np_view) -> str:
            oid = store_shard(np_view)
            stored.append(oid)
            return oid

        try:
            manifest = _da.build_manifest(value, store_shard_tracked)
            manifest.owner = self.address
            object_id = ObjectID.for_put(task_id, self._put_counter.next())
            mid = object_id.hex()
            so = serialization.serialize(manifest)
            entry = self._owned_entry(mid)
            self._store_serialized(mid, so, entry)
        except BaseException:
            # Shard storage OR the manifest store failed partway: the
            # already-stored shards hold a manifest pin that no manifest
            # will ever release — drop them now or they stay pinned in
            # the store until process shutdown.
            for oid in stored:
                try:
                    self.remove_local_reference(
                        ObjectID(bytes.fromhex(oid)))
                except Exception:
                    pass
            raise
        self._shard_children[mid] = list(manifest.shard_oids)
        if attribution.enabled:
            attribution.count("put.sharded")
        return ObjectRef(object_id, owner=self.address, runtime=self)

    def _maybe_assemble(self, value: Any,
                        timeout: Optional[float] = None) -> Any:
        """Reassemble a sharded array from its manifest: fetch only the
        locally-addressable shards (zero-copy shm views) and land each
        on its own device — no host-side gather of the full array."""
        from ray_tpu.util import device_arrays as _da

        if not isinstance(value, _da.ShardManifest):
            return value
        return self._assemble_all([value], timeout)[0]

    def _assemble_all(self, values: List[Any],
                      timeout: Optional[float] = None) -> List[Any]:
        """Reassemble every ShardManifest in `values` (others pass
        through), resolving ALL manifests' not-yet-local shards in ONE
        gathered batch — a get(list) of k borrower-side manifests costs
        one pull round-trip latency, not k, and within each manifest
        the shards resolve concurrently too."""
        from ray_tpu.util import device_arrays as _da

        manifests = [v for v in values
                     if isinstance(v, _da.ShardManifest)]
        if not manifests:
            return values
        import jax

        local_ids = {d.id for d in jax.local_devices()}
        fetched: Dict[str, Any] = {}
        pending: List[Tuple[str, str]] = []   # (oid, owner_addr)
        for m in manifests:
            owner = m.owner or self.address
            for oid, did in zip(m.shard_oids, m.shard_device_ids):
                if did not in local_ids or oid in fetched:
                    continue   # another host's shard: never touched here
                got = self._read_resolved_local(oid)
                if got is not _MISS:
                    fetched[oid] = got   # writer-local: dict hit + view
                elif all(o != oid for o, _ in pending):
                    pending.append((oid, owner))
        if pending:
            async def _all():
                return await asyncio.gather(*(
                    self._resolve_async(
                        ObjectRef(ObjectID(bytes.fromhex(o)),
                                  owner=own, runtime=self), timeout)
                    for o, own in pending))
            for (o, _), res in zip(pending,
                                   self._loop.run(_all(), timeout=None)):
                fetched[o] = self._materialize(res)
        if attribution.enabled:
            attribution.count("get.sharded", len(manifests))
        out = [(_da.assemble_from_manifest(v, lambda oid: fetched[oid])
                if isinstance(v, _da.ShardManifest) else v)
               for v in values]
        # Pulled shards were resolved through bare ObjectRefs that never
        # registered a borrow, so no later release will ever unmap them
        # — drop their mappings here, now that assembly has landed every
        # shard on its device (a still-live view defers the close). The
        # writer-local `_read_resolved_local` hits stay mapped: their
        # lifetime belongs to the owned manifest entry.
        for o, _ in pending:
            self._release_shm_mapping(o)
        return out

    def _store_serialized(self, oid: str, so, entry: _Owned) -> None:
        size = so.total_size()
        if size <= ray_config().max_direct_call_object_size:
            entry.fut.set_result(("inline", so.to_bytes()))
            return
        shm_name = self._loop.run(
            self._raylet.call("create_object", oid=oid, size=size))
        self._shm.write_chunks(shm_name, so.chunks())
        # Fire-and-forget: frames are processed in order on this
        # connection, and remote pulls poll until the seal lands
        # (handle_pull_object), so nothing needs the round trip.
        self._loop.run(self._raylet.notify("seal_object", oid=oid))
        # Remember the segment so a local get() reads it back without a
        # raylet round trip (pull_object exists for REMOTE resolution;
        # a node-local read needs neither the RPC nor any pull-manager
        # bookkeeping). Invalidation: try_attach fails after eviction.
        # The writer also keeps the segment MAPPED (plasma clients keep
        # their store files mmapped): a local get of a just-put object
        # is then a dict hit + np view — no shm_open/mmap on the read
        # path. `_release_shm_mapping` drops it with the last local ref.
        self._local_shm[oid] = {"shm_name": shm_name, "size": size}
        self._shm.try_attach(shm_name)
        if self.raylet_address not in entry.nodes:
            entry.nodes.append(self.raylet_address)
        entry.is_stored = True
        entry.fut.set_result(("node", self.raylet_address))

    def _deserialize_payload(self, data) -> Any:
        return serialization.deserialize(data)

    def _read_local_shm(self, info: dict, oid: Optional[str] = None) -> Any:
        view = self._shm.read(info["shm_name"], info["size"])
        if oid is not None:
            # Remember the mapping so the segment can be unmapped when
            # the last local reference to this object drops (deferred if
            # zero-copy views still alias it).
            self._shm_by_oid[oid] = info["shm_name"]
        return self._deserialize_payload(view)

    async def _resolve_async(self, ref: ObjectRef,
                             timeout: Optional[float]):
        """The IO half of a fetch (local future / raylet pull); returns
        ("inline", bytes) or ("shm", info) without deserializing, so a
        multi-ref get can gather many of these concurrently on the RPC
        loop (reference: batched plasma Get, core_worker.cc:1358-1430)
        and deserialize on the caller's thread."""
        oid = ref.hex()
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._owned_lock:
            entry = self._owned.get(oid)
        if entry is not None:
            # NOT wait_for: cancelling the wrapper on timeout propagates
            # into entry.fut (wrap_future chains cancellation), which
            # would permanently poison the ref — a later get() must
            # still be able to succeed. Waiting is SLICED because
            # reconstruction REPLACES entry.fut with a fresh Future
            # without resolving the old one (the same trap
            # _resolve_dependencies polls around): re-read the entry
            # each slice so a reconstructed object still materializes.
            wrapped = asyncio.wrap_future(entry.fut)
            wrapped_fut = entry.fut
            while True:
                remaining = (None if deadline is None
                             else max(0.0, deadline - time.monotonic()))
                slice_t = (0.5 if remaining is None
                           else min(0.5, remaining))
                done, _ = await asyncio.wait({wrapped}, timeout=slice_t)
                if done:
                    kind, payload = wrapped.result()
                    break
                if remaining is not None and remaining <= slice_t:
                    raise GetTimeoutError(f"timed out waiting for {ref}")
                with self._owned_lock:
                    latest = self._owned.get(oid)
                if latest is not None:
                    entry = latest
                # Re-wrap ONLY when the underlying future was replaced
                # (reconstruction): wrapping per slice would chain one
                # callback + abandoned wrapper onto entry.fut per 0.5s
                # of waiting, unboundedly.
                if entry.fut is not wrapped_fut:
                    wrapped = asyncio.wrap_future(entry.fut)
                    wrapped_fut = entry.fut
            if kind == "inline":
                return ("inline", payload, oid)
            # Node-local fast path: an object THIS process wrote to the
            # local store is read straight from its shm segment — no
            # pull_object RPC, no pull-manager admission (the budget is
            # for genuinely remote transfers). try_attach doubles as the
            # eviction check: an unlinked segment fails to attach and we
            # fall through to the raylet, which restores/re-pulls.
            info = self._local_shm.get(oid)
            if info is not None:
                if self._shm.try_attach(info["shm_name"]):
                    if attribution.enabled:
                        attribution.count("get.local_shm")
                    return ("shm", info, oid)
                self._local_shm.pop(oid, None)   # evicted: re-resolve
            # stored on some node; pull through the local raylet
            owner_addr = self.address
        else:
            owner = ref.owner_address
            owner_addr = (owner.decode() if isinstance(owner, bytes)
                          else owner)
        remaining = (None if deadline is None
                     else max(0.0, deadline - time.monotonic()))
        if attribution.enabled:
            attribution.count("get.pull_rpc")
        try:
            res = await asyncio.wait_for(self._raylet.call(
                "pull_object", oid=oid, owner_address=owner_addr,
                pull_timeout=remaining, timeout=None), remaining)
        except (asyncio.TimeoutError, TimeoutError):
            raise GetTimeoutError(f"timed out fetching {ref}")
        if res is None:
            raise ObjectLostError(oid)
        if res.get("error"):
            if res.get("owner_dead"):
                # The raylet held the pull through the owner-unreachable
                # grace window and the owner never came back: fail the
                # borrower's get LOUDLY with the typed cause instead of
                # a generic loss (reference: owner-died unrecoverable).
                raise OwnerDiedError(oid)
            if "timeout" in res["error"]:
                raise GetTimeoutError(f"timed out fetching {ref}: "
                                      f"{res['error']}")
            raise ObjectLostError(oid)
        if "inline" in res and res["inline"] is not None:
            return ("inline", res["inline"], oid)
        return ("shm", res, oid)

    def _materialize(self, resolved) -> Any:
        kind, payload, oid = resolved
        if kind == "inline":
            return self._deserialize_payload(payload)
        return self._read_local_shm(payload, oid)

    def _read_resolved_local(self, oid: str) -> Any:
        """Thread-local read of an already-landed owned object (inline
        result, or a node-local shm segment we wrote): no event-loop
        round trip — that costs a self-pipe write plus a futex wait per
        call and dominated the sequential-get p50 on syscall-expensive
        hosts. Returns the `_MISS` sentinel when resolution needs IO."""
        with self._owned_lock:
            entry = self._owned.get(oid)
        if entry is None or not entry.fut.done():
            return _MISS
        kind, payload = entry.fut.result()
        if kind == "inline":
            return self._deserialize_payload(payload)
        info = self._local_shm.get(oid)
        if info is not None and self._shm.try_attach(info["shm_name"]):
            if attribution.enabled:
                attribution.count("get.local_shm")
            return self._read_local_shm(info, oid)
        return _MISS

    def _fetch(self, ref: ObjectRef, timeout: Optional[float]) -> Any:
        """Blocking fetch of one object's value (resolved-owned objects
        read on the caller's thread via `_read_resolved_local`)."""
        value = self._read_resolved_local(ref.hex())
        if value is not _MISS:
            return self._maybe_assemble(value, timeout)
        return self._maybe_assemble(self._materialize(
            self._loop.run(self._resolve_async(ref, timeout),
                           timeout=None)), timeout)

    def _in_executing_task(self) -> bool:
        return (self.mode == "worker" and threading.get_ident()
                in self._running_task_threads.values())

    def _notify_block_state(self, blocked: bool) -> None:
        """Tell our raylet this worker's task is blocked in get() (CPU
        released for downstream work) / resumed. Reference:
        NotifyDirectCallTaskBlocked — without it, consumers blocked on
        not-yet-scheduled producers hold every CPU and the node
        deadlocks."""
        method = "worker_blocked" if blocked else "worker_unblocked"
        try:
            self._loop.run(self._raylet.notify(
                method, worker_id=self._raylet_worker_id), timeout=5)
        except Exception:
            pass

    def _get_would_wait(self, refs) -> bool:
        """Cheap pre-check: does this get have a chance of blocking on a
        not-yet-produced object? Resolved owned refs skip the
        blocked/unblocked raylet round trips entirely."""
        ref_list = ([refs] if isinstance(refs, ObjectRef)
                    else refs if isinstance(refs, (list, tuple)) else None)
        if ref_list is None:
            return True
        for ref in ref_list:
            if not isinstance(ref, ObjectRef):
                return True
            with self._owned_lock:
                entry = self._owned.get(ref.hex())
            if entry is None or not entry.fut.done():
                return True
        return False

    def get(self, refs, timeout: Optional[float] = None):
        if self._in_executing_task() and self._get_would_wait(refs):
            with self._blocked_lock:
                self._blocked_depth += 1
                fire = self._blocked_depth == 1
            if fire:
                self._notify_block_state(True)
            try:
                return self._get_inner(refs, timeout)
            finally:
                with self._blocked_lock:
                    self._blocked_depth -= 1
                    fire = self._blocked_depth == 0
                if fire:
                    self._notify_block_state(False)
        return self._get_inner(refs, timeout)

    def _get_inner(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, (ObjectRef, ObjectRefGenerator))
        if not single and not hasattr(refs, "__iter__"):
            raise TypeError(
                "get() expects an ObjectRef or a list of ObjectRefs, got "
                f"{type(refs).__name__}")
        ref_list = [refs] if single else list(refs)
        for ref in ref_list:
            if isinstance(ref, ObjectRefGenerator):
                raise TypeError(
                    "Cannot get() an ObjectRefGenerator; iterate it.")
            if not isinstance(ref, ObjectRef):
                raise TypeError(
                    f"get() expects ObjectRef(s), got {type(ref).__name__}")
        if single or len(ref_list) == 1:
            value = self._fetch(ref_list[0], timeout)
            return value if single else [value]
        # All-resolved fast path: a batched get over refs that are all
        # locally landed (the shape every inline burst produces) reads
        # on the caller thread — no event-loop round trip, no gather of
        # N no-op coroutines. ANY miss falls back to the concurrent
        # resolve below.
        values: List[Any] = []
        for ref in ref_list:
            got = self._read_resolved_local(ref.hex())
            if got is _MISS:
                values = None
                break
            values.append(got)
        if values is not None:
            return self._assemble_all(values, timeout)

        async def _resolve_all():
            # Concurrent: N remote objects cost one round-trip latency,
            # not N (the round-3 sequential-get finding).
            return await asyncio.gather(
                *(self._resolve_async(r, timeout) for r in ref_list))

        resolved = self._loop.run(_resolve_all(), timeout=None)
        return self._assemble_all(
            [self._materialize(r) for r in resolved], timeout)

    async def _ask_owner_locations_batch(self, owner_addr: str,
                                         oids: List[str]):
        client = await self._worker_client(owner_addr)
        return await client.call("get_object_locations_batch", oids=oids,
                                 timeout=10.0)

    def wait(self, refs, num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True):
        if isinstance(refs, ObjectRef):
            raise TypeError("wait() expects a list of ObjectRefs")
        refs = list(refs)
        if len(set(refs)) != len(refs):
            raise ValueError("wait() got duplicate ObjectRefs")
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds the number of refs")
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        ready: List[ObjectRef] = []
        pending = list(refs)
        tick = 0.002
        while len(ready) < num_returns:
            # Owned refs resolve on local futures (no RPC); borrowed refs
            # are batched into one locations RPC per owner per tick.
            borrowed: Dict[str, List[ObjectRef]] = {}
            for ref in list(pending):
                oid = ref.hex()
                with self._owned_lock:
                    entry = self._owned.get(oid)
                if entry is not None:
                    if entry.fut.done():
                        ready.append(ref)
                        pending.remove(ref)
                    continue
                owner = ref.owner_address
                owner = (owner.decode() if isinstance(owner, bytes)
                         else owner)
                borrowed.setdefault(owner, []).append(ref)
            for owner, owner_refs in borrowed.items():
                if len(ready) >= num_returns:
                    break
                try:
                    locs = self._loop.run(self._ask_owner_locations_batch(
                        owner, [r.hex() for r in owner_refs]), timeout=15)
                except Exception:
                    continue
                for ref in owner_refs:
                    loc = locs.get(ref.hex())
                    if loc is not None and not loc.get("pending"):
                        ready.append(ref)
                        pending.remove(ref)
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(tick)
            tick = min(tick * 2, 0.05)  # back off toward 50 ms
        # Reference contract: ready holds at most num_returns; anything
        # extra that completed in the same scan stays in pending.
        if len(ready) > num_returns:
            extra = ready[num_returns:]
            ready = ready[:num_returns]
            pending = extra + pending
        return ready, pending

    # ==================================================================
    # task submission (reference: direct_task_transport.cc)
    # ==================================================================
    def submit_task(self, remote_function, opts, args, kwargs):
        _t0 = time.perf_counter() if attribution.enabled else 0.0
        fn_key = self._fn.export(remote_function._function)
        if (self._inline_enabled
                and self._inline_eligible(fn_key, opts, args, kwargs)):
            return self._submit_inline(remote_function, fn_key, opts,
                                       args, kwargs)
        if attribution.enabled:
            attribution.count("submit.remote")
        if flight.enabled:
            flight.instant("task", "submit",
                           arg=remote_function._function_name)
        task_id = TaskID.for_task(self.job_id)
        streaming = opts.num_returns in ("streaming", "dynamic")
        num_returns = 1 if streaming else opts.num_returns
        args_blob, pinned = self._serialize_args(args, kwargs)
        # Propagate the caller's span so the worker-side execution span
        # parents across the process boundary — INCLUDING unsampled
        # contexts: the head decision must ride the flags byte, or the
        # worker would re-roll sampling per task and record orphan
        # roots. Unsampled propagation is near-free since span() takes
        # the PRNG fast path for it (util/tracing.py).
        trace_ctx = current_traceparent() if tracing_enabled() else None
        spec, sched_key, tmpl = self._encode_task_spec(
            remote_function, opts, fn_key, num_returns, streaming,
            task_id=task_id.hex(), args=args_blob,
            # TOP-LEVEL arg refs only, for pre-lease dependency
            # resolution (reference: dependency_resolver.h — deps resolve
            # BEFORE a worker is leased, so a blocked dependency never
            # holds a worker slot hostage). Nested refs (inside
            # lists/dicts) are pass-by-reference — the worker never
            # fetches them, so submission must not block on them.
            arg_oids=[a.hex() for a in
                      list(args) + list(kwargs.values())
                      if isinstance(a, ObjectRef)],
            trace_ctx=trace_ctx)
        if attribution.enabled:
            attribution.record("submit.encode", time.perf_counter() - _t0)
        refs = self._make_return_refs(task_id, num_returns)
        gen = None
        if streaming:
            gen = ObjectRefGenerator()
            self._generators[task_id.hex()] = gen
        self._record_task_event(task_id.hex(),
                                remote_function._function_name,
                                "SUBMITTED")
        rec = None
        if not streaming and opts.num_returns != 0 and opts.max_retries > 0:
            # Retain the spec (and keep its arg refs pinned) for lineage
            # re-execution; released when the last return ref is freed —
            # or early, when the reply shows every result landed inline
            # (owner-future values cannot be lost). None when the
            # lineage_reconstruction flag is off.
            rec = self._lineage.retain([r.hex() for r in refs], spec,
                                       pinned, opts.max_retries)
        # Round-16 caller-thread dispatch (tier 5): a ring-eligible
        # submit against an already-leased, already-ringed worker whose
        # template is registered publishes from THIS thread — no loop
        # wakeup, no coroutine. Any miss falls through to the loop-hop
        # queue below, byte-identically.
        if (self._caller_dispatch and tmpl is not None and not streaming
                and self._try_caller_dispatch(
                    spec, refs, pinned if rec is None else None,
                    sched_key, tmpl)):
            if opts.num_returns == 0:
                return None
            return refs[0] if opts.num_returns == 1 else refs
        self._enqueue_submit(
            ("task", spec, refs, pinned if rec is None else None,
             sched_key, tmpl))
        if streaming:
            return gen
        if opts.num_returns == 0:
            return None
        return refs[0] if opts.num_returns == 1 else refs

    # -- same-process inline fast path (round 8) -----------------------
    def _inline_eligible(self, fn_key: str, opts, args, kwargs) -> bool:
        """Per-task dynamic inline decision (reference: the local-mode
        short circuit, promoted to a cost-model gate). True only when
        the scheduler would co-locate the task anyway AND it is known
        to be tiny:

        - exec-time EMA for this function is KNOWN and below the
          threshold (first calls always go remote and report exec_us in
          their replies — a long or blocking task is never inlined on
          spec);
        - pure-default demand (1 CPU, nothing else): any explicit
          resource/env/placement request means the user asked for a
          scheduling decision, which inlining would bypass;
        - every top-level ObjectRef arg is locally resolved (owned,
          value landed) — anything else needs IO the worker path
          overlaps with other tasks;
        - not streaming (generators hold the caller arbitrarily long).

        `.options(_metadata={"inline": False})` opts a call site out
        (perf.py uses it to keep measuring the remote plane).

        Cost model v2 (round 16): the EMA is arg-size-conditional —
        keyed by (fn, size bucket) — and the whole tier is revocable
        under caller-thread dispatch pressure (the caller thread that
        would run this inline is busy being a ring producer; stealing
        it starves every worker the ring feeds).
        """
        if self._inline_v2 and self._inline_revoked_until:
            if time.monotonic() < self._inline_revoked_until:
                return False
            self._inline_revoked_until = 0.0
        ema = self._fn_cost_lookup(fn_key, args, kwargs)
        if ema is None or ema > self._inline_threshold_s:
            return False
        if opts.num_returns in ("streaming", "dynamic"):
            return False
        if (opts.num_cpus != 1.0 or opts.num_gpus or opts.resources
                or opts.memory or opts.runtime_env
                or opts.placement_group is not None
                or opts.scheduling_strategy is not None
                or opts.accelerator_type):
            return False
        md = opts._metadata
        if md is not None and (md.get("inline") is False
                               or md.get("profile")):
            # Profiled tasks always go remote: the pstats dump belongs
            # next to a WORKER log where /api/logs can surface it.
            return False
        for a in args:
            if isinstance(a, ObjectRef) and not self._resolved_locally(a):
                return False
        for a in kwargs.values():
            if isinstance(a, ObjectRef) and not self._resolved_locally(a):
                return False
        return True

    def _resolved_locally(self, ref: ObjectRef) -> bool:
        """True only when the arg's VALUE is readable on this node with
        no IO: an inline payload, or a node-local shm segment we wrote.
        A done future whose copy lives on a REMOTE node is not enough —
        inlining would turn .remote() into a blocking cross-node pull
        on the caller thread."""
        oid = ref.hex()
        with self._owned_lock:
            entry = self._owned.get(oid)
        if entry is None or not entry.fut.done():
            return False
        kind, _payload = entry.fut.result()
        if kind == "inline":
            return True
        # Stored object: local only if this process holds the segment
        # (liveness re-checked by try_attach at read time; a rare
        # eviction just makes the inline run pull like a worker would).
        return oid in self._local_shm

    def _update_fn_cost(self, fn_key: str, dt: float,
                        arg_bytes: Optional[int] = None) -> None:
        """Feed the exec-time EMA. V2 keys it by (fn, arg-size bucket)
        when the observation carries the serialized-args length; v1 (or
        an observation without one) keeps the plain scalar key."""
        key: Any = fn_key
        if self._inline_v2 and arg_bytes is not None:
            key = (fn_key, _size_bucket(arg_bytes))
        prev = self._fn_cost.get(key)
        self._fn_cost[key] = (dt if prev is None
                              else 0.7 * prev + 0.3 * dt)
        if len(self._fn_cost) > 4096:
            self._fn_cost.clear()  # bounded, simple (re-learns)

    def _fn_cost_lookup(self, fn_key: str, args, kwargs
                        ) -> Optional[float]:
        """Gate-side EMA lookup. V2: estimate the call's arg footprint
        cheaply (no serialization — this runs per submit) and read the
        matching bucket; an unknown bucket inherits *downward* from a
        known-tiny LARGER bucket (a fn observed cheap on bigger args is
        cheap on smaller ones — the converse never holds, so small-arg
        evidence can't promote big-arg calls)."""
        if not self._inline_v2:
            return self._fn_cost.get(fn_key)
        b = _size_bucket(self._arg_size_estimate(args, kwargs))
        ema = self._fn_cost.get((fn_key, b))
        if ema is not None:
            return ema
        for bigger in range(b + 1, len(_SIZE_BUCKETS) + 1):
            bema = self._fn_cost.get((fn_key, bigger))
            if bema is not None and bema <= self._inline_threshold_s:
                return bema
        # Legacy scalar observations (v1 runs, or updates without a
        # size) still count — the tier must not go cold on upgrade.
        return self._fn_cost.get(fn_key)

    @staticmethod
    def _arg_size_estimate(args, kwargs) -> int:
        """Cheap (non-serializing) arg-footprint estimate for bucket
        selection: exact for bytes/str/arrays, shallow for small
        containers, a fixed opaque default otherwise. Only needs to
        land in the right coarse bucket, not be right."""
        total = 0
        items = list(args) + list(kwargs.values())
        for a in items:
            if isinstance(a, (bytes, bytearray, str)):
                total += len(a)
            elif isinstance(a, (int, float, bool)) or a is None:
                total += 8
            elif isinstance(a, ObjectRef):
                total += 64  # passed by reference
            elif hasattr(a, "nbytes"):
                try:
                    total += int(a.nbytes)
                except Exception:
                    total += 512
            elif isinstance(a, (list, tuple, set)) and len(a) <= 64:
                for x in a:
                    if isinstance(x, (bytes, bytearray, str)):
                        total += len(x)
                    elif isinstance(x, (int, float, bool)) or x is None:
                        total += 8
                    else:
                        total += 512
            elif isinstance(a, dict) and len(a) <= 64:
                total += 64 * (len(a) + 1)
            else:
                total += 512
        return total

    def _note_caller_pressure(self) -> None:
        """Caller-thread dispatch pressure signal (v2 revocation): a
        sustained run of caller enqueues within one sliding window
        means the caller thread IS the dispatch tier right now —
        revoke inlining for a window so it keeps producing instead of
        stealing itself for user code. Runs on the caller thread; the
        fields are process-local and a lost update under the GIL just
        shifts the window by one sample."""
        if not self._inline_v2:
            return
        now = time.monotonic()
        if now - self._caller_window_start > self._inline_revoke_window_s:
            self._caller_window_start = now
            self._caller_window_count = 0
        self._caller_window_count += 1
        if self._caller_window_count >= self._inline_revoke_pressure:
            self._inline_revoked_until = (
                now + self._inline_revoke_window_s)
            self._caller_window_start = now
            self._caller_window_count = 0
            if attribution.enabled:
                attribution.count("inline.revoked")
            if flight.enabled:
                flight.instant("task", "inline_revoked")

    def _submit_inline(self, remote_function, fn_key: str, opts,
                       args, kwargs):
        """Execute an inline-eligible task on the caller thread through
        the SAME `_execute_task` the worker runs: task_events and the
        execution span are emitted exactly once, exceptions take the
        identical typed packaging (`_package_error` → RayTaskError
        surfacing at `get`), and results land as real owned ObjectRefs
        — already resolved, no lease, no push, no store round trip for
        inline-sized values."""
        task_id = TaskID.for_task(self.job_id)
        num_returns = opts.num_returns
        args_blob, pinned = self._serialize_args(args, kwargs)
        trace_ctx = current_traceparent() if tracing_enabled() else None
        spec = {
            "task_id": task_id.hex(),
            "job_id": self.job_id.hex(),
            "name": remote_function._function_name,
            "fn_key": fn_key,
            "args": args_blob,
            "num_returns": num_returns,
            "trace_ctx": trace_ctx,
        }
        refs = self._make_return_refs(task_id, num_returns)
        self._record_task_event(task_id.hex(), spec["name"], "SUBMITTED")
        if attribution.enabled:
            attribution.count("submit.inline")
        reply = self._execute_task(spec)
        # Feed the cost model from exec_us (user-code wall time), the
        # same signal remote replies carry — NOT the full inline wall
        # time, whose first run carries one-time costs (job-env fetch,
        # import warmup) that would evict a genuinely tiny function
        # from the inline tier for the next ~7 calls.
        exec_us = reply.get("exec_us")
        if exec_us is not None:
            self._update_fn_cost(fn_key, exec_us / 1e6, len(args_blob))
        if attribution.enabled:
            split = reply.pop("attr_exec", None)
            if split:
                # The caller-thread analogue of the worker split — NOT
                # folded under worker.* so the --attribute table keeps
                # the inline-vs-remote budget separable.
                attribution.fold(split, prefix="inline.")
        else:
            reply.pop("attr_exec", None)
        self._record_task_reply(spec, reply)
        # Lineage parity: inline results that were large enough to be
        # sealed into the node store are as losable as remote ones —
        # retain the (lazily wire-encoded) spec for reconstruction and
        # keep the arg pins alive with it, exactly like submit_task's
        # retain branch. Purely-inline results live in the owner future
        # and cannot be lost, so they skip the bookkeeping.
        stored = any(r.get("node") for r in reply.get("results", ()))
        rec = None
        if (stored and opts.max_retries > 0 and num_returns != 0
                and self._lineage.enabled()):
            wire_spec, _, _ = self._encode_task_spec(
                remote_function, opts, fn_key, num_returns, False,
                task_id=task_id.hex(), args=args_blob,
                arg_oids=[a.hex() for a in
                          list(args) + list(kwargs.values())
                          if isinstance(a, ObjectRef)],
                trace_ctx=trace_ctx)
            rec = self._lineage.retain([r.hex() for r in refs], wire_spec,
                                       pinned, opts.max_retries)
        if rec is None:
            self._unpin_args(pinned)
        if num_returns == 0:
            return None
        return refs[0] if num_returns == 1 else refs

    def _encode_task_spec(self, remote_function, opts, fn_key: str,
                          num_returns: int, streaming: bool, *,
                          task_id: str, args: bytes, arg_oids: list,
                          trace_ctx: Optional[str]
                          ) -> Tuple[dict, str, Optional[SpecTemplate]]:
        """Wire dict + lease scheduling key for one task submission.

        Template-spec encoding (reference: the TaskSpec invariants
        `direct_task_transport` re-ships unchanged thousands of times):
        the first submission of a (function, options, runtime-env) shape
        builds a fully-validated WireTaskSpec and caches its wire dict;
        repeats copy the template and overwrite only task_id/args/
        arg_oids/trace_ctx. The cache key carries every invariant field,
        so ANY options or runtime-env change misses and re-validates —
        that is the invalidation contract tests/test_unit_spec_template
        pins down."""
        from ray_tpu.core.options import resource_demand

        raw_env = getattr(opts, "runtime_env", None)
        # working_dir/pip envs re-prepare per call (their content can
        # change under the same raw spec — a template would freeze a
        # stale upload key); env_vars-only envs are value-stable and
        # cacheable via their hash.
        cacheable = not raw_env or set(raw_env) <= {"env_vars"}
        resources = resource_demand(opts)
        md = getattr(opts, "_metadata", None)
        profile = bool(md and md.get("profile"))
        tkey = (fn_key, num_returns, streaming, opts.max_retries,
                env_hash(raw_env) if raw_env else "",
                _pg_id_of(getattr(opts, "placement_group", None)),
                getattr(opts, "placement_group_bundle_index", -1),
                tuple(sorted(resources.items())), profile)
        hit = self._spec_templates.get(tkey) if cacheable else None
        if hit is None:
            env = _prepared_env(self, opts)
            pg = tkey[5]
            # Typed wire message (core/wire.py TaskSpec): field presence
            # and types are enforced at construction AND on the
            # receiver's validated decode.
            proto = WireTaskSpec(
                task_id=task_id,
                job_id=self.job_id.hex(),
                fn_key=fn_key,
                name=remote_function._function_name,
                args=args,
                arg_oids=arg_oids,
                num_returns=num_returns,
                streaming=streaming,
                owner=self.address,
                resources=resources,
                max_retries=opts.max_retries,
                runtime_env=env or None,
                pg=(None if pg is None else {
                    "pg_id": pg, "bundle_index": tkey[6]}),
                trace_ctx=trace_ctx,
                profile=profile or None,
            )
            sched_key = self._sched_key_of(proto)
            hit = (SpecTemplate(proto), sched_key)
            if cacheable:
                if len(self._spec_templates) >= 512:
                    self._spec_templates.clear()  # bounded, simple
                self._spec_templates[tkey] = hit
        tmpl, sched_key = hit
        return (tmpl.encode(task_id=task_id, args=args,
                            arg_oids=arg_oids, trace_ctx=trace_ctx),
                sched_key,
                # The template is handed down the submit path only when
                # it is CACHED (stable identity): the submission ring
                # registers it with the raylet once and then ships
                # per-call deltas against it.
                tmpl if cacheable else None)

    @staticmethod
    def _sched_key_of(spec) -> str:
        """Lease scheduling key (worker-compatibility class) of a task
        spec. Distinct runtime envs never share a leased worker."""
        pg = spec.get("pg")
        key = (f"{spec['fn_key']}:{sorted(spec['resources'].items())}"
               f":{pg['pg_id']}:{pg['bundle_index']}" if pg else
               f"{spec['fn_key']}:{sorted(spec['resources'].items())}")
        return key + f":{env_hash(spec.get('runtime_env'))}"

    def _enqueue_submit(self, item: tuple) -> None:
        """Queue a submission for the RPC loop, coalescing loop wakeups.

        `loop.spawn` per task means one `call_soon_threadsafe` — and one
        self-pipe write syscall — per submission; at 20+ us/syscall on
        virtualized hosts that alone capped the submit rate (measured
        round 5). Appends are GIL-atomic (same discipline as
        deferred_release); one scheduled drain spawns every queued
        submission in FIFO order, so a burst pays one wakeup."""
        if self._shutdown:
            # Unlike dropped releases, a dropped SUBMISSION has
            # observable results — the caller already holds ObjectRefs
            # and a later get() would hang forever. Fail loudly at the
            # submit site. (A stopped-but-not-closed loop accepts the
            # call_soon below and simply never runs it — same silent
            # outcome loop.spawn had — so the flag check, not the
            # except, is what actually covers the shutdown race.)
            raise RuntimeError("runtime is shut down; cannot submit")
        self._pending_submits.append(item)
        if not self._submit_drain_scheduled:
            self._submit_drain_scheduled = True
            try:
                self._loop.call_soon(self._drain_submits)
            except Exception:
                self._submit_drain_scheduled = False
                raise  # loop closed: surface at the submit call site

    def _drain_submits(self) -> None:
        while True:
            while self._pending_submits:
                item = self._pending_submits.popleft()
                if item[0] == "task":
                    _, spec, refs, pinned, sched_key, tmpl = item
                    asyncio.ensure_future(self._submit_async(
                        spec, refs, pinned, sched_key=sched_key,
                        tmpl=tmpl))
                else:
                    _, spec, refs, pinned = item
                    asyncio.ensure_future(
                        self._submit_actor_async(spec, refs, pinned))
            # Going idle: clear the armed flag FIRST, then re-check the
            # queue. An enqueue racing the final empty check either saw
            # the flag still armed (caught by this re-check — the
            # burst's LAST submission must not wait for the next
            # enqueue's wakeup) or saw it cleared and scheduled a fresh
            # drain itself. The previous scheme cleared at drain ENTRY,
            # which made every mid-drain enqueue schedule a spurious
            # extra wakeup — one self-pipe syscall per task in a
            # sustained cross-thread burst.
            self._submit_drain_scheduled = False
            if not self._pending_submits:
                return
            self._submit_drain_scheduled = True

    def _make_return_refs(self, task_id: TaskID,
                          num_returns: int) -> List[ObjectRef]:
        """Create owner entries BEFORE the ObjectRefs so each ref's
        constructor registers a local reference (baseline refcount 1);
        otherwise a later pin/unpin cycle can free a still-live ref."""
        refs = []
        for i in range(max(num_returns, 1)):
            oid = ObjectID.for_return(task_id, i + 1)
            self._owned_entry(oid.hex())
            refs.append(ObjectRef(oid, owner=self.address, runtime=self))
        return refs

    _empty_args_blob: Optional[bytes] = None

    def _serialize_args(self, args, kwargs) -> Tuple[bytes, List[ObjectID]]:
        """Serialize task arguments, pinning every contained ObjectRef so the
        owner does not free it while the task spec is in flight (reference:
        reference_count.h submitted-task counts)."""
        if not args and not kwargs:
            # Zero-arg calls share one precomputed blob: nothing to pin,
            # and the ~25 us cloudpickle pass is identical every time.
            blob = ClusterRuntime._empty_args_blob
            if blob is None:
                blob = ClusterRuntime._empty_args_blob = (
                    serialization.serialize(((), {})).to_bytes())
            return blob, []
        pinned: List[ObjectID] = []
        blob = serialization.serialize(
            (args, kwargs),
            ref_serializer=lambda r: pinned.append(r.id())).to_bytes()
        for oid in pinned:
            self.add_local_reference(oid)
        return blob, pinned

    def _unpin_args(self, pinned: List[ObjectID]) -> None:
        for oid in pinned:
            self.remove_local_reference(oid)

    async def _resolve_dependencies(self, spec: dict) -> None:
        """Wait until every OWNED arg object exists (inline value or a
        stored copy) before leasing a worker (reference:
        dependency_resolver.h via direct_task_transport.cc:24). Without
        this, a task whose upstream is being reconstructed occupies a
        worker slot while it pulls — and a chain of such tasks can
        starve the very re-executions that would unblock them
        (chaos-suite deadlock). Borrowed refs (owned elsewhere) resolve
        worker-side as before."""
        for oid in spec.get("arg_oids", ()):
            while True:
                with self._owned_lock:
                    entry = self._owned.get(oid)
                    ready = entry is None or entry.fut.done()
                if ready:
                    break
                # Poll: entry.fut can be REPLACED by a reconstruction
                # reset, so awaiting one future instance would hang.
                await asyncio.sleep(0.02)

    async def _submit_async(self, spec: dict, refs: List[ObjectRef],
                            pinned: Optional[List[ObjectID]] = None,
                            sched_key: Optional[str] = None,
                            tmpl: Optional[SpecTemplate] = None) -> None:
        retries = spec.get("max_retries", 0)
        attempt = 0
        try:
            while True:
                try:
                    # (Re-)resolve on every attempt: a retry often means
                    # a node died, taking this task's upstream objects
                    # with it.
                    await self._resolve_dependencies(spec)
                    await self._run_on_leased_worker(spec, sched_key,
                                                     tmpl)
                    return
                except (ConnectionLost, RpcError, TimeoutError,
                        asyncio.TimeoutError, OSError) as e:
                    # TimeoutError/OSError cover leases stranded on a
                    # node that died while the request was queued there
                    # — transient cluster faults, retryable like a
                    # dropped connection (chaos-suite finding).
                    if spec["task_id"] in self._cancel_requested:
                        # A force-cancel kills the worker mid-task; that
                        # must surface as cancellation, not retry.
                        self._fail_task_cancelled(spec, refs)
                        return
                    attempt += 1
                    if attempt > max(retries, 0):
                        oom = isinstance(e, _WorkerOOMKilled)
                        self._fail_task(
                            spec, refs,
                            ("killed by the memory monitor (node OOM); "
                             "retries exhausted" if oom else
                             f"worker died ({e}); retries exhausted"),
                            oom=oom)
                        return
                    logger.info("retrying task %s (attempt %d): %s",
                                spec["name"], attempt, e)
                    delay = ray_config().task_retry_delay_ms / 1000.0
                    if delay:
                        await asyncio.sleep(delay)
                except _TaskCancelledBeforePush:
                    self._fail_task_cancelled(spec, refs)
                    return
                except Exception as e:  # noqa: BLE001
                    self._fail_task(spec, refs, f"submission failed: {e}")
                    return
        finally:
            if pinned:
                self._unpin_args(pinned)

    def _fail_task_cancelled(self, spec: dict,
                             refs: List[ObjectRef]) -> None:
        self._cancel_requested.discard(spec["task_id"])
        err = serialization.serialize_error(
            TaskCancelledError(spec["task_id"]))
        blob = err.to_bytes()
        for r in refs:
            entry = self._owned_entry(r.hex())
            if not entry.fut.done():
                entry.fut.set_result(("inline", blob))
        gen = self._generators.pop(spec["task_id"], None)
        if gen is not None:
            gen._finish(TaskCancelledError(spec["task_id"]))

    async def _worker_was_oom_killed(self, worker: dict) -> bool:
        # Short dial: if the worker died because its whole NODE died,
        # this probe must cost ~2s, not a full connect window per retry.
        try:
            client = await self._raylet_client(worker["raylet_address"],
                                               connect_timeout=2.0)
            cause = await client.call("worker_death_cause",
                                      worker_id=worker["worker_id"],
                                      timeout=5.0)
        except Exception:
            return False
        return cause == "oom"

    def _fail_task(self, spec: dict, refs: List[ObjectRef],
                   message: str, oom: bool = False) -> None:
        from ray_tpu.exceptions import OutOfMemoryError, WorkerCrashedError
        exc_cls = OutOfMemoryError if oom else WorkerCrashedError
        err = serialization.serialize_error(
            exc_cls(f"task {spec['name']}: {message}"))
        blob = err.to_bytes()
        for r in refs:
            entry = self._owned_entry(r.hex())
            if not entry.fut.done():
                entry.fut.set_result(("inline", blob))
        gen = self._generators.pop(spec["task_id"], None)
        if gen is not None:
            from ray_tpu.exceptions import WorkerCrashedError as WCE
            gen._finish(WCE(f"task {spec['name']}: {message}"))

    async def _run_on_leased_worker(self, spec: dict,
                                    sched_key: Optional[str] = None,
                                    tmpl: Optional[SpecTemplate] = None
                                    ) -> None:
        pg = spec.get("pg")
        # The submit path hands the template-cached scheduling key down;
        # resubmits (lineage re-execution) recompute it.
        key = sched_key if sched_key is not None else self._sched_key_of(
            spec)
        _t0 = time.perf_counter() if attribution.enabled else 0.0
        _m0 = time.monotonic() if flight.enabled else 0.0
        worker = await self._acquire_worker(key, spec["resources"], pg=pg)
        if attribution.enabled:
            attribution.record("submit.lease", time.perf_counter() - _t0)
        if flight.enabled:
            flight.record("lease", "acquire",
                          dur_us=int((time.monotonic() - _m0) * 1e6),
                          arg=worker.get("worker_id", "")[:8], t=_m0)
        if spec["task_id"] in self._cancel_requested:
            # Cancelled while queued for a lease: never push.
            self._offer_worker(key, worker)
            raise _TaskCancelledBeforePush()
        if worker.get("chip_ids"):
            spec = (spec.replace(visible_chips=worker["chip_ids"])
                    if hasattr(spec, "replace")
                    else dict(spec, visible_chips=worker["chip_ids"]))
        self._inflight_task_workers[spec["task_id"]] = (
            worker["worker_address"], False)
        worker["pipeline"] = worker.get("pipeline", 0) + 1
        push_t0 = time.monotonic()
        worker["push_started"] = push_t0
        worker["push_task_name"] = spec.get("name")
        try:
            # Worker-direct ring push (round 10, core/ring.py): a
            # template-encoded, non-streaming spec bound for a
            # ring-capable chip-less worker on OUR node rides a
            # dedicated driver<->worker shm ring pair — no raylet, no
            # socket on the per-task path; the reply (exec_us,
            # attribution split) comes back on the twin ring. Any miss
            # (ring off/failed, no template, remote node, streaming,
            # ring full, oversized delta) falls through to the RPC
            # push, byte-identically.
            ring_fut = None
            if (self._ring_enabled and tmpl is not None
                    and worker.get("ring_capable")
                    and not spec.get("streaming")
                    and worker.get("raylet_address")
                    == self.raylet_address
                    and not worker.get("chip_ids")):
                ring_fut = await self._worker_ring_enqueue(
                    spec, tmpl, worker, sched_key=key)
            if ring_fut is not None:
                # Pipelining: the lease recirculates once the entry is
                # published, exactly like a wire push (see below).
                self._offer_worker(key, worker)
                reply = await ring_fut
            else:
                client = await self._worker_client(
                    worker["worker_address"])
                # Pipelining: once the push is on the wire the lease
                # goes back into circulation (bounded by
                # worker_pipeline_depth), so the worker's execution
                # queue stays fed across the push/reply round trip
                # instead of idling one RTT per task. _offer_worker
                # gates this on the worker's observed service time —
                # queueing behind a LONG task would serialize work that
                # fresh leases (and spillback) could run in parallel.
                self._offer_worker(key, worker)
                reply = await client.call(
                    "push_task",
                    spec=(to_wire(spec) if hasattr(spec, "_wire_name")
                          else spec),
                    timeout=None)
        except BaseException as push_err:
            # BaseException on purpose: a CancelledError that skipped the
            # decrement would wedge the lease at pipeline>0 forever — the
            # linger loop then never returns it and the raylet's CPUs
            # leak (observed as a cluster-wide scheduling stall).
            worker["pipeline"] -= 1
            if isinstance(push_err, Exception):
                worker["dead"] = True
                if not worker.get("returned"):
                    worker["returned"] = True
                    # Fire-and-forget: retrying against a DEAD raylet
                    # takes tens of seconds; the task's resubmission
                    # must not stall behind it.
                    self._loop.spawn(
                        self._return_worker(worker, dead=True))
                if await self._worker_was_oom_killed(worker):
                    raise _WorkerOOMKilled(str(push_err)) from push_err
            raise
        finally:
            self._inflight_task_workers.pop(spec["task_id"], None)
        # Only a completed task clears its cancel flag — on a push
        # failure _submit_async must still see it to suppress the retry.
        self._cancel_requested.discard(spec["task_id"])
        worker["pipeline"] -= 1
        # Per-worker service-time EMA (push->reply, which bounds task
        # duration): drives the deep-pipelining gate in _offer_worker.
        rtt = time.monotonic() - push_t0
        prev = worker.get("svc_ema")
        worker["svc_ema"] = (rtt if prev is None
                             else 0.7 * prev + 0.3 * rtt)
        if attribution.enabled:
            attribution.record("submit.push_rtt", rtt)
        if flight.enabled:
            flight.record("task", "push_rtt", dur_us=int(rtt * 1e6),
                          arg=spec.get("name"), t=push_t0)
        # Feed the inline cost model: exec_us rides every task reply (a
        # single int), so the EMA converges to the TRUE exec time — a
        # function that went remote because of one slow run can earn
        # its way back under the inline threshold.
        exec_us = reply.get("exec_us") if isinstance(reply, dict) else None
        if exec_us is not None and spec.get("fn_key"):
            args_blob = spec.get("args")
            self._update_fn_cost(spec["fn_key"], exec_us / 1e6,
                                 len(args_blob) if args_blob else None)
        self._record_task_reply(spec, reply)
        self._offer_worker(key, worker)

    # -- worker-direct dispatch rings: driver side (round 10) ----------
    async def _ensure_worker_ring(self, worker: dict) -> Optional[dict]:
        """Ring pair for one leased worker, established lazily on the
        lease's first ring-eligible push (we own the segments/FIFOs;
        the worker attaches). Single-flight per worker: a cold burst's
        coroutines all await ONE setup instead of racing orphan pairs.
        A failed or dead pair latches False — the RPC push path serves
        the rest of the lease, never retried per task."""
        wid = worker["worker_id"]
        st = self._worker_rings.get(wid)
        if st is not None:
            return st if isinstance(st, dict) and st.get("live") else None
        setup = self._worker_ring_setups.get(wid)
        if setup is None:
            setup = self._worker_ring_setups[wid] = asyncio.ensure_future(
                self._setup_worker_ring(worker))
            # The SETUP task owns its registry entry: a cancelled
            # awaiter (push coroutines can be cancelled mid-await)
            # must not pop a still-running setup — that would let a
            # second setup race the first and orphan a pair whose
            # waiters nobody ever completes.
            setup.add_done_callback(
                lambda _f: self._worker_ring_setups.pop(wid, None))
        await setup
        st = self._worker_rings.get(wid)
        return st if isinstance(st, dict) and st.get("live") else None

    async def _setup_worker_ring(self, worker: dict) -> None:
        from ray_tpu.core import ring as ringmod

        wid = worker["worker_id"]
        files: List[Tuple[str, str]] = []
        writer = reader = None
        registered_fd = None
        loop = asyncio.get_running_loop()
        try:
            sub_name, sub_fifo = ringmod.create_ring(
                "rtwsub", self._ring_slots, self._ring_slot_bytes)
            files.append((sub_name, sub_fifo))
            comp_name, comp_fifo = ringmod.create_ring(
                "rtwcmp", self._ring_slots, self._ring_slot_bytes)
            files.append((comp_name, comp_fifo))
            writer = ringmod.RingWriter(sub_name, sub_fifo)
            reader = ringmod.RingReader(comp_name, comp_fifo)
            client = await self._worker_client(worker["worker_address"])
            st = {
                "worker_id": wid,
                "writer": writer, "reader": reader, "files": files,
                "templates": {}, "next_tmpl": 0,
                "waiters": {}, "client": client, "live": True,
                # Round 16: producer-side ownership latch (caller tier
                # <-> loop handoff) + templates the caller thread may
                # reference (id(tmpl) -> (tmpl_id, strong tmpl ref),
                # registration CONFIRMED — the caller must never ship
                # a delta against an id still in flight).
                "latch": ringmod.ProducerLatch(), "caller_tmpls": {},
            }
            # Reply fallback (full reply ring / oversized reply) rides
            # a server push on the worker connection; register before
            # attach so no reply can beat the handler. The handler
            # resolves the CURRENT ring through the registry instead
            # of capturing `st`: the cached client outlives any one
            # ring, and a captured state would pin a torn-down pair
            # (reader/writer + up to 512 template dicts) for as long
            # as the client lives.
            client.on_push(
                "ring_completion",
                lambda msg, wid=wid: self._worker_ring_push_reply(
                    wid, msg))
            loop.add_reader(reader.doorbell_fd,
                            self._drain_worker_ring, st)
            registered_fd = reader.doorbell_fd
            await client.call(
                "attach_task_ring", sub_name=sub_name,
                sub_fifo=sub_fifo, comp_name=comp_name,
                comp_fifo=comp_fifo, timeout=10.0)
            st["backstop"] = asyncio.ensure_future(
                self._worker_ring_backstop(st))
            self._worker_rings[wid] = st
            # The raylet pins ring-attached workers against idle
            # recycling until detach: a returned worker must never
            # carry a stale ring into another lease.
            try:
                await self._raylet.notify("worker_ring_attached",
                                          worker_id=wid)
            except Exception:
                pass
        except Exception:
            logger.warning("worker ring setup for %s failed; staying on "
                           "the RPC push path", wid[:8], exc_info=True)
            # Tear down everything this attempt created: the segments
            # were deliberately untracked from the resource_tracker, so
            # nothing else will ever unlink them.
            if registered_fd is not None:
                try:
                    loop.remove_reader(registered_fd)
                except Exception:
                    pass
            for end in (writer, reader):
                if end is not None:
                    try:
                        end.close()
                    except Exception:
                        pass
            for name, fifo in files:
                ringmod.destroy_ring(name, fifo)
            self._worker_rings[wid] = False

    async def _worker_ring_enqueue(self, spec: dict, tmpl: SpecTemplate,
                                   worker: dict,
                                   sched_key: Optional[str] = None
                                   ) -> Optional[Any]:
        """Publish one template-spec delta on the leased worker's own
        ring; returns the reply future, or None when the entry cannot
        ride the ring (caller falls back to the RPC push)."""
        st = await self._ensure_worker_ring(worker)
        if st is None:
            return None
        # One-time template registration per (fn, options, env) shape
        # PER RING. Entries hold (id, registered-future, STRONG
        # template ref): the future gates concurrent first-users (a
        # delta must never hit the ring before its template landed at
        # the worker), the strong ref pins the object so a recycled
        # id() can never alias a stale entry onto the wrong template.
        entry = st["templates"].get(id(tmpl))
        if entry is None:
            if len(st["templates"]) >= 512:
                st["templates"].clear()   # bounded; re-registers
                st["caller_tmpls"].clear()
            tmpl_id = st["next_tmpl"]
            st["next_tmpl"] += 1
            reg = asyncio.get_running_loop().create_future()
            st["templates"][id(tmpl)] = (tmpl_id, reg, tmpl)
            try:
                await st["client"].call("register_task_template",
                                        template_id=tmpl_id,
                                        base=tmpl._base, timeout=10.0)
                reg.set_result(True)
                # Registration CONFIRMED: the caller tier may now ship
                # deltas against this id (strong ref doubles as the
                # id()-aliasing pin for the caller-side map).
                st["caller_tmpls"][id(tmpl)] = (tmpl_id, tmpl)
            except Exception:
                st["templates"].pop(id(tmpl), None)
                reg.set_result(False)
                return None
        else:
            tmpl_id, reg = entry[0], entry[1]
            if not await reg:
                return None
        if not st.get("live"):
            return None   # died while we awaited the registration
        delta = {"t": tmpl_id, "task_id": spec["task_id"],
                 "args": spec["args"],
                 "arg_oids": spec.get("arg_oids") or [],
                 "trace_ctx": spec.get("trace_ctx")}
        payload = msgpack.packb(delta, use_bin_type=True)
        fut = asyncio.get_running_loop().create_future()
        st["waiters"][spec["task_id"]] = fut
        # Caller dispatch on: this push contends the producer latch
        # (the loop reclaims ring ownership for the fallback path).
        # Flag off: no latch anywhere near the hot path — today's
        # behavior, byte-identical.
        latch = st["latch"] if self._caller_dispatch else None
        if latch is not None:
            latch.acquire("loop")
        try:
            pushed = st["writer"].push(payload)
        finally:
            if latch is not None:
                latch.release()
        if not pushed:
            # Full ring or oversized delta: not an error, just a miss.
            st["waiters"].pop(spec["task_id"], None)
            if attribution.enabled:
                attribution.count("ring.fallback")
            return None
        if attribution.enabled:
            attribution.count("ring.direct_enq")
        if flight.enabled:
            flight.instant("ring", "direct_enq")
        # A successful loop-path publish proves the whole flow works
        # for this (sched_key, worker, template): advertise the pair
        # to caller threads.
        self._caller_ring_offer(sched_key, worker, st)
        return fut

    def _drain_worker_ring(self, st: dict) -> int:
        from ray_tpu.core.ring import busy_poll

        total = 0
        rounds = 0
        while st.get("live"):
            try:
                drained = st["reader"].drain()
            except (OSError, ValueError):
                break  # ring torn down under the callback
            if drained and attribution.enabled:
                # Counted HERE so ring.reply means exactly "replies
                # that rode the twin ring" — fallback server pushes
                # count under ring.reply_fallback instead (a full/
                # broken reply ring must be visible in the counters).
                attribution.count("ring.reply", len(drained))
            for raw in drained:
                self._worker_ring_complete(
                    st, msgpack.unpackb(raw, raw=False))
            total += len(drained)
            # Busy-poll handoff (round 16, bounded): right after a
            # non-empty drain the worker is mid-burst — spin briefly
            # for the next reply instead of paying an epoll wakeup
            # per batch. Never spins on an idle ring (drained empty).
            if (not drained or self._busy_poll_s <= 0.0
                    or rounds >= 2):
                break
            rounds += 1
            if not busy_poll(st["reader"], self._busy_poll_s):
                break
            if attribution.enabled:
                attribution.count("ring.busy_poll")
        if total:
            # Doorbell-served drains must feed the backstop's pacing
            # too ("activity", read-and-reset each backstop tick):
            # otherwise active traffic served entirely by doorbells
            # looks idle to the poll and it backs off to the idle
            # period exactly when the lost-wakeup race matters.
            st["activity"] = st.get("activity", 0) + total
        return total

    def _spawn_ring_task(self, coro) -> None:
        """ensure_future with a strong reference held until done (must
        run on the loop thread)."""
        t = asyncio.ensure_future(coro)
        self._ring_bg_tasks.add(t)
        t.add_done_callback(self._ring_bg_tasks.discard)

    def _worker_ring_push_reply(self, wid: str, msg: Any) -> None:
        """Server-push reply fallback, routed to whatever ring is
        CURRENTLY live for this worker (no reply can arrive before the
        ring registers: deltas only flow after setup publishes it)."""
        st = self._worker_rings.get(wid)
        if isinstance(st, dict):
            if attribution.enabled:
                attribution.count("ring.reply_fallback")
            self._worker_ring_complete(st, msg)

    def _worker_ring_complete(self, st: dict, msg: Any) -> None:
        if not isinstance(msg, dict):
            return
        fut = st["waiters"].pop(msg.get("task_id"), None)
        if fut is None:
            return
        if isinstance(fut, _CallerTask):
            # Caller-enqueued entry: no parked coroutine to resume —
            # finish the bookkeeping inline on the loop thread (this
            # drain handles a whole batch per wakeup).
            self._caller_task_complete(st, fut, msg)
            return
        if fut.done():
            return
        err = msg.get("error")
        if err is not None:
            if "unknown spec template" in err:
                # The worker no longer knows an id we cached (should
                # be unreachable given its oldest-first eviction
                # bound): drop OUR cache so the retry re-registers
                # instead of re-sending the dead id forever.
                st["templates"].clear()
                st.get("caller_tmpls", {}).clear()
            # Same shape a failed wire push produces: the submit retry
            # loop treats it as a worker/transport fault.
            fut.set_exception(ConnectionLost(
                f"ring dispatch failed: {err}"))
        else:
            fut.set_result(msg.get("reply"))

    # -- caller-thread dispatch tier (round 16) ------------------------
    def _caller_ring_offer(self, sched_key: Optional[str], worker: dict,
                           st: dict) -> None:
        """Advertise a (leased worker, live ring) pair to caller
        threads under its scheduling key. Loop thread only, called
        after a successful loop-path ring publish — by then the lease
        is held, the pair is attached, and the template flow works.
        Torn down in _teardown_worker_ring (single choke point)."""
        if not self._caller_dispatch or sched_key is None:
            return
        with self._caller_lock:
            self._caller_rings.setdefault(sched_key, {})[
                worker["worker_id"]] = (worker, st)

    def _caller_deps_ready(self, arg_oids) -> bool:
        """Caller-thread analogue of _resolve_dependencies' ready
        check: every OWNED top-level arg already has a value. A pending
        dependency falls back to the loop path, whose resolver waits
        properly (the caller thread must never block on upstream
        tasks)."""
        if not arg_oids:
            return True
        with self._owned_lock:
            for oid in arg_oids:
                entry = self._owned.get(oid)
                if entry is not None and not entry.fut.done():
                    return False
        return True

    def _try_caller_dispatch(self, spec: dict, refs: List[ObjectRef],
                             pinned: Optional[List[ObjectID]],
                             sched_key: str, tmpl: SpecTemplate) -> bool:
        """Publish one submit from the caller thread onto a ringed
        worker's forward ring (tier 5). True = published (the reply
        drain finishes the task); False = miss, caller falls through
        to _enqueue_submit with nothing consumed.

        SPSC discipline: the push (and the waiter insert + liveness
        re-check) run under the ring's ProducerLatch — the loop thread
        cedes/reclaims the producer side through the same latch, so at
        any instant the ring has exactly one producer."""
        if self._shutdown:
            return False
        if not self._caller_deps_ready(spec.get("arg_oids") or ()):
            return False
        payload = None
        w = None
        deadline = None
        while True:
            # Pick a live, non-saturated ringed worker under this key.
            # caller_pipeline < ring_slots is the in-flight bound: ring
            # capacity bounds entries the WORKER hasn't dequeued, but
            # only completions free caller_pipeline — without this cap
            # a fast consumer would let the caller overrun the exec
            # queue far past the loop path's pipeline discipline.
            target = None
            saw_ring = False
            with self._caller_lock:
                ringed = self._caller_rings.get(sched_key)
                if ringed:
                    for worker, st in ringed.values():
                        if (not st.get("live") or worker.get("dead")
                                or worker.get("returned")):
                            continue
                        saw_ring = True
                        if (worker.get("caller_pipeline", 0)
                                < self._ring_slots):
                            target = (worker, st)
                            break
            if not saw_ring:
                return False  # cold key: the loop path attaches/offers
            if target is not None:
                worker, st = target
                entry = st.get("caller_tmpls", {}).get(id(tmpl))
                if entry is None:
                    # Template not registered on this ring yet: one
                    # loop-path submission registers it and re-offers.
                    return False
                if payload is None:
                    delta = {"t": entry[0], "task_id": spec["task_id"],
                             "args": spec["args"],
                             "arg_oids": spec.get("arg_oids") or [],
                             "trace_ctx": spec.get("trace_ctx")}
                    payload = msgpack.packb(delta, use_bin_type=True)
                    w = _CallerTask(spec, refs, pinned, sched_key, tmpl,
                                    worker, spec.get("fn_key"),
                                    len(spec["args"]), time.monotonic())
                w.worker = worker
                latch = st["latch"]
                latch.acquire("caller")
                try:
                    if (st.get("live") and not worker.get("dead")
                            and not worker.get("returned")):
                        # Waiter + pipeline count BEFORE push (loop-
                        # path order): the worker can reply before
                        # this thread runs another bytecode — a reply
                        # with no waiter is dropped on the floor, and
                        # a completion decrementing before our
                        # increment would leave a phantom in-flight
                        # count pinning the lease.
                        st["waiters"][spec["task_id"]] = w
                        self._inflight_task_workers[spec["task_id"]] = (
                            worker["worker_address"], False)
                        with self._caller_lock:
                            worker["caller_pipeline"] = (
                                worker.get("caller_pipeline", 0) + 1)
                        if st["writer"].push(payload):
                            break
                        st["waiters"].pop(spec["task_id"], None)
                        self._inflight_task_workers.pop(
                            spec["task_id"], None)
                        with self._caller_lock:
                            worker["caller_pipeline"] = max(
                                0,
                                worker.get("caller_pipeline", 1) - 1)
                finally:
                    latch.release()
            # Saturated pipeline or full ring. Slots and pipeline
            # window free at the worker's service rate, so a bounded
            # wait rides out a burst overrun instead of dumping the
            # overflow onto the loop-hop path (which would put the
            # loop right back on the hot path this tier exists to
            # skip). The sleep yields the GIL, letting the loop
            # thread drain completions meanwhile.
            now = time.monotonic()
            if deadline is None:
                deadline = now + self._caller_push_wait_s
            if now >= deadline:
                if attribution.enabled:
                    attribution.count("submit.caller_fallback")
                if flight.enabled:
                    flight.instant("task", "caller_fallback")
                return False
            time.sleep(0.0002)
        if attribution.enabled:
            attribution.count("submit.caller_enq")
        if flight.enabled:
            flight.instant("task", "caller_enq", arg=spec.get("name"))
        self._note_caller_pressure()
        return True

    def _caller_task_complete(self, st: dict, w: _CallerTask,
                              msg: dict) -> None:
        """Completion bookkeeping for one caller-enqueued task — the
        loop-path epilogue of _run_on_leased_worker, minus the lease
        recirculation (the caller tier never acquired the worker; the
        loop path owns its circulation). Runs on the loop thread,
        batched N per reply-ring drain."""
        with self._caller_lock:
            w.worker["caller_pipeline"] = max(
                0, w.worker.get("caller_pipeline", 1) - 1)
        self._inflight_task_workers.pop(w.spec["task_id"], None)
        err = msg.get("error")
        if err is not None:
            if "unknown spec template" in err:
                st["templates"].clear()
                st.get("caller_tmpls", {}).clear()
            self._caller_task_retry(
                w, ConnectionLost(f"ring dispatch failed: {err}"))
            return
        self._cancel_requested.discard(w.spec["task_id"])
        reply = msg.get("reply")
        rtt = time.monotonic() - w.push_t0
        prev = w.worker.get("svc_ema")
        w.worker["svc_ema"] = (rtt if prev is None
                               else 0.7 * prev + 0.3 * rtt)
        if attribution.enabled:
            attribution.record("submit.caller_rtt", rtt)
        exec_us = (reply.get("exec_us")
                   if isinstance(reply, dict) else None)
        if exec_us is not None and w.fn_key:
            self._update_fn_cost(w.fn_key, exec_us / 1e6, w.args_len)
        self._record_task_reply(w.spec, reply)
        if w.pinned:
            self._unpin_args(w.pinned)

    def _caller_task_retry(self, w: _CallerTask, exc: Exception) -> None:
        """Route a failed caller-enqueued entry onto the SAME typed
        retry path a failed RPC push takes — minus the attempt this
        enqueue consumed. Loop thread only."""
        spec, refs = w.spec, w.refs
        if spec["task_id"] in self._cancel_requested:
            self._fail_task_cancelled(spec, refs)
            if w.pinned:
                self._unpin_args(w.pinned)
            return
        retries = spec.get("max_retries", 0)
        if retries < 1 or self._shutdown:
            self._fail_task(spec, refs,
                            f"worker died ({exc}); retries exhausted")
            if w.pinned:
                self._unpin_args(w.pinned)
            return
        # max_retries is decremented on the RESUBMITTED spec: this
        # enqueue was attempt #1. Workers ignore the field at
        # execution, so the mutation is wire-safe.
        respec = dict(spec, max_retries=retries - 1)
        self._spawn_ring_task(self._submit_async(
            respec, refs, w.pinned, sched_key=w.sched_key, tmpl=w.tmpl))

    def _caller_task_abandon(self, w: _CallerTask, why: str) -> None:
        """Ring died/detached with this caller entry possibly in
        flight: undo the in-flight accounting and send it to the retry
        path (parity with the ConnectionLost future waiters sweep)."""
        with self._caller_lock:
            w.worker["caller_pipeline"] = max(
                0, w.worker.get("caller_pipeline", 1) - 1)
        self._inflight_task_workers.pop(w.spec["task_id"], None)
        self._caller_task_retry(w, ConnectionLost(why))

    async def _worker_ring_backstop(self, st: dict) -> None:
        """Adaptive lost-wakeup backstop (ring.AdaptivePoll: base
        period under traffic, decaying toward the idle period) +
        worker-death failfast — a dead worker can never complete its
        ring entries, so waiters must fail onto the ConnectionLost
        retry path instead of hanging their get() forever."""
        from ray_tpu.core.ring import AdaptivePoll

        poll = AdaptivePoll()
        while st.get("live"):
            await asyncio.sleep(poll.interval)
            self._drain_worker_ring(st)
            # "activity" accumulates doorbell-served drains between
            # ticks (plus this tick's own), so traffic keeps the poll
            # at its base period regardless of which path drained it.
            poll.observe(st.pop("activity", 0))
            if not st["client"].connected:
                self._fail_worker_ring(
                    st, "worker connection lost with ring submissions "
                        "in flight")
                return

    def _fail_worker_ring(self, st: dict, why: str) -> None:
        """The worker died (or its ring broke) with entries possibly
        in flight: fail every waiter with ConnectionLost — the submit
        retry loop treats that exactly like a failed RPC push (lease
        marked dead, task re-leased elsewhere) — and retire the pair,
        pinning this worker_id to the RPC path. Caller-enqueued
        waiters take the same typed path through their own resubmit
        (handoff-reclaim: the teardown owns the producer side from
        here on; a caller that raced us re-checks `live` under the
        latch and misses)."""
        waiters = self._sweep_ring_waiters(st)
        for fut in waiters.values():
            if isinstance(fut, _CallerTask):
                self._caller_task_abandon(fut, why)
            elif not fut.done():
                fut.set_exception(ConnectionLost(why))
        self._teardown_worker_ring(st, latch_failed=True)

    def _sweep_ring_waiters(self, st: dict) -> dict:
        """Swap out the waiter map for a teardown sweep. With caller
        dispatch on, the swap AND the live flip happen under the
        ProducerLatch (as the terminal owner): a caller-thread insert
        is either fully in the swapped-out map or sees live=False and
        falls back — never stranded in the replacement dict."""
        latch = st.get("latch") if self._caller_dispatch else None
        if latch is None:
            waiters, st["waiters"] = st["waiters"], {}
            return waiters
        latch.acquire("teardown")
        try:
            st["live"] = False
            waiters, st["waiters"] = st["waiters"], {}
            return waiters
        finally:
            latch.release()

    async def _detach_worker_ring(self, st: dict) -> None:
        """Lease return detaches and destroys the pair: tell the
        worker to drop its end (best effort — it may already be dead),
        un-pin at the raylet, then close + unlink our segments. Runs
        BEFORE the lease-return RPC so a recycled worker can never
        carry a stale ring into its next lease."""
        wid = st["worker_id"]
        if st.get("live"):
            try:
                await st["client"].call("detach_task_ring", timeout=5.0)
            except Exception:
                pass
        # Any reply that raced the detach is drained now; a waiter
        # still pending after that can only mean lost work — fail it
        # onto the retry path rather than hang its get() forever.
        self._drain_worker_ring(st)
        waiters = self._sweep_ring_waiters(st)
        for fut in waiters.values():
            if isinstance(fut, _CallerTask):
                self._caller_task_abandon(
                    fut, "lease returned with ring submissions in "
                         "flight")
            elif not fut.done():
                fut.set_exception(ConnectionLost(
                    "lease returned with ring submissions in flight"))
        try:
            await self._raylet.notify("worker_ring_detached",
                                      worker_id=wid)
        except Exception:
            pass
        self._teardown_worker_ring(st, latch_failed=False)

    def _teardown_worker_ring(self, st: dict, latch_failed: bool) -> None:
        """Close + destroy one driver-side pair (we own the files).
        latch_failed=True pins the worker_id to the RPC path (dead
        worker); False forgets it, so re-leasing the same live worker
        attaches a fresh pair. Idempotence keys on `torn`, not `live`:
        the caller-dispatch waiter sweep flips live early (under the
        latch) and the teardown must still run once after it."""
        if st.get("torn"):
            return
        st["torn"] = True
        st["live"] = False
        # Single choke point for the caller-dispatch registry: no
        # caller thread may target a ring past its teardown.
        if self._caller_dispatch:
            with self._caller_lock:
                for key in list(self._caller_rings):
                    ringed = self._caller_rings[key]
                    ringed.pop(st["worker_id"], None)
                    if not ringed:
                        del self._caller_rings[key]
        backstop = st.get("backstop")
        if backstop is not None:
            try:
                backstop.cancel()
            except Exception:
                pass
        try:
            self._loop.loop.remove_reader(st["reader"].doorbell_fd)
        except Exception:
            pass
        for end in (st["writer"], st["reader"]):
            try:
                end.close()
            except Exception:
                pass
        from ray_tpu.core.ring import destroy_ring

        for name, fifo in st["files"]:
            destroy_ring(name, fifo)
        if latch_failed:
            self._worker_rings[st["worker_id"]] = False
        else:
            self._worker_rings.pop(st["worker_id"], None)

    def _close_worker_rings(self) -> None:
        """Shutdown sweep: every still-live driver-side pair (waiters
        failed loudly — a silently dropped submission would hang some
        get() forever), plus, in worker mode, any task ring attached
        to this process. Runs the teardown on the RPC loop when it is
        still alive (reader-fd deregistration and backstop cancels are
        loop-owned state); falls back to direct cleanup otherwise."""

        def _sweep() -> None:
            for st in [s for s in self._worker_rings.values()
                       if isinstance(s, dict)]:
                self._fail_worker_ring(st, "runtime shut down with ring "
                                           "submissions in flight")
            self._worker_rings.clear()
            for st in list(self._task_rings):
                self._detach_task_ring_state(st)

        if not (self._worker_rings or self._task_rings):
            return

        async def _on_loop():
            _sweep()

        try:
            self._loop.run(_on_loop(), timeout=5)
        except Exception:
            _sweep()

    def _record_task_reply(self, spec: dict, reply: dict) -> None:
        task_id = spec["task_id"]
        if attribution.enabled:
            attr = reply.get("attr")
            if attr:
                # Worker-side decode/execute timings ride the reply (a
                # couple of ints, only in attribution mode) so the
                # driver's snapshot covers both sides of the wire.
                attribution.fold(attr)
        if logger.isEnabledFor(logging.DEBUG):
            logger.debug("task reply %s (%s): %s", spec.get("name"),
                         task_id[:12],
                         [(r.get("oid", "")[:16], r.get("node"),
                           ("inline" if r.get("inline") is not None
                            else "-")) for r in reply.get("results", [])])
        results = reply.get("results", [])
        for res in results:
            entry = self._owned_entry(res["oid"])
            if res.get("node"):
                if res["node"] not in entry.nodes:
                    entry.nodes.append(res["node"])
                entry.is_stored = True
                if not entry.fut.done():
                    entry.fut.set_result(("node", res["node"]))
            else:
                if not entry.fut.done():
                    entry.fut.set_result(("inline", res["inline"]))
        if results and not any(res.get("node") for res in results):
            # Every result landed inline: the owner futures hold the
            # values and nothing is ever losable — release the lineage
            # record (and its arg pins) now instead of carrying the spec
            # until the refs die. Retention is for STORE-SEALED results.
            rec = self._lineage.get(results[0]["oid"])
            if rec is not None:
                self._unpin_args(self._lineage.drop_record(rec))
        if spec.get("streaming") and reply.get("done"):
            gen = self._generators.pop(task_id, None)
            if gen is not None:
                err = reply.get("error_blob")
                if err is not None:
                    try:
                        self._deserialize_payload(err)
                        exc = None
                    except BaseException as e:  # noqa: BLE001
                        exc = e
                    gen._finish(exc)
                else:
                    gen._finish()

    # -- lease pool ----------------------------------------------------
    async def _acquire_worker(self, key: str, resources: Dict[str, float],
                              pg: Optional[dict] = None) -> dict:
        """Grab a leased worker for this scheduling key: an idle one
        immediately, else queue and keep up to MAX_INFLIGHT lease
        requests pipelined to the raylet. Completed tasks hand their
        worker straight to the next waiter (no raylet round trip) — this
        is what makes a burst of small same-shape tasks run at worker
        speed instead of lease-RPC speed."""
        pool = self._lease_pools.setdefault(key, _LeasePool())
        while pool.idle:
            worker = pool.idle.pop()
            if worker.get("dead"):
                continue  # died while idling (e.g. OOM-killed mid-pipeline)
            worker["avail"] = False
            return worker
        fut = asyncio.get_running_loop().create_future()
        pool.waiters.append(fut)
        # Coalesced pump (same discipline as _drain_submits): a burst
        # of acquires lands as N waiters in THIS loop pass, and the one
        # deferred pump then sees them all — that is what lets a
        # batched lease RPC carry the whole burst instead of want=1
        # per waiter.
        self._schedule_pump(pool, resources, pg)
        return await fut

    def _schedule_pump(self, pool: _LeasePool,
                       resources: Dict[str, float],
                       pg: Optional[dict]) -> None:
        if pool.pump_scheduled:
            return
        pool.pump_scheduled = True

        def _run() -> None:
            pool.pump_scheduled = False
            self._pump_leases(pool, resources, pg)

        asyncio.get_running_loop().call_soon(_run)

    def _pump_leases(self, pool: _LeasePool,
                     resources: Dict[str, float],
                     pg: Optional[dict]) -> None:
        """Keep lease requests pipelined for every queued waiter: RPCs
        are bounded by MAX_INFLIGHT; with batching on, each RPC asks
        for up to lease_batch_max grants (one round trip leases a whole
        burst's workers — the dominant per-task cost PR 5's attribution
        left on the table)."""
        batch_max = (self._lease_batch_max
                     if pg is None and self._lease_batching else 1)
        # Expected grants are bounded by the SAME allowance the
        # unbatched pump used (min(waiters, MAX_INFLIGHT)): batching
        # must collapse the RPC count for a burst, never multiply the
        # raylet's queue churn past what singles would have caused.
        allowance = min(len(pool.waiters), pool.MAX_INFLIGHT)
        while (pool.inflight_rpcs < pool.MAX_INFLIGHT
               and pool.inflight_leases < allowance):
            want = min(allowance - pool.inflight_leases, batch_max)
            pool.inflight_leases += want
            pool.inflight_rpcs += 1
            asyncio.ensure_future(
                self._fetch_lease(pool, resources, pg, want))

    async def _fetch_lease(self, pool: _LeasePool,
                           resources: Dict[str, float],
                           pg: Optional[dict], want: int = 1) -> None:
        try:
            bundle = None
            address = None
            if pg is not None:
                address, idx = await self._pg_location(
                    pg["pg_id"], pg["bundle_index"], demand=resources)
                bundle = (pg["pg_id"], idx)
            workers = await self._request_leases(
                resources, want, bundle=bundle, address=address)
        except Exception as e:  # noqa: BLE001
            pool.inflight_rpcs -= 1
            pool.inflight_leases -= want
            for i, fut in enumerate(pool.waiters):
                if not fut.done():
                    pool.waiters.pop(i)
                    fut.set_exception(e)
                    break
            # Surplus waiters beyond MAX_INFLIGHT still need lease
            # requests of their own — without this re-pump they would
            # wait forever once every inflight request has failed.
            self._pump_leases(pool, resources, pg)
            return
        pool.inflight_rpcs -= 1
        pool.inflight_leases -= want
        if attribution.enabled and want > 1:
            attribution.value("lease.batch_size", len(workers))
        for worker in workers:
            self._hand_worker(pool, worker)
        # Partial grant ONLY (the raylet had fewer immediately-
        # grantable workers than asked): the shortfall's waiters lost
        # their expected grant and need fresh requests. A full grant
        # never re-pumps — surplus waiters beyond the pipelining cap
        # are served by lease REUSE, the contract
        # tests/test_unit_lease_pool pins.
        if len(workers) < want and pool.waiters:
            self._pump_leases(pool, resources, pg)

    async def _request_leases(self, resources: Dict[str, float],
                              n: int,
                              bundle: Optional[Tuple[str, int]] = None,
                              address: Optional[str] = None
                              ) -> List[dict]:
        """Batched lease request: one raylet RPC for up to `n` workers
        (reference name parity: request_worker_leases). PG-bundle
        leases stay single-grant; the reply may be a partial grant —
        the caller re-pumps."""
        if n <= 1 or bundle is not None:
            return [await self._request_lease(resources, bundle=bundle,
                                              address=address)]
        return await self._lease_request_loop(resources, n)


    def _offer_worker(self, key: str, worker: dict) -> None:
        """Put a leased worker (back) into circulation if it is alive,
        not already circulating, and has pipeline window left. Workers
        whose tasks are slow (or of unknown duration beyond the first)
        only circulate when their queue is empty — fresh leases and
        spillback handle the parallelism instead."""
        if worker.get("dead") or worker.get("avail"):
            return
        # Caller-enqueued entries occupy the same execution queue as
        # loop-path pushes; both count against the pipeline window.
        pipeline = (worker.get("pipeline", 0)
                    + worker.get("caller_pipeline", 0))
        if pipeline >= self._pipeline_depth:
            return
        if pipeline > 0:
            ema = worker.get("svc_ema")
            # Deep pipelining (offering a still-executing worker) only
            # pays off for tasks shorter than a lease round trip.
            if ema is None or ema > self._pipeline_svc_threshold:
                return  # don't queue behind an unknown/slow task
        pool = self._lease_pools.setdefault(key, _LeasePool())
        self._hand_worker(pool, worker)

    def _hand_worker(self, pool: _LeasePool, worker: dict) -> None:
        if worker.get("dead"):
            return
        while pool.waiters:
            fut = pool.waiters.pop(0)
            if not fut.done():
                worker["avail"] = False  # exclusively promised
                fut.set_result(worker)
                return
        worker["avail"] = True
        pool.idle.append(worker)
        asyncio.ensure_future(self._linger_then_return(pool, worker))

    async def _linger_then_return(self, pool: _LeasePool,
                                  worker: dict) -> None:
        """An idle lease is kept briefly for reuse, then returned so the
        raylet can reschedule its resources."""
        await asyncio.sleep(ray_config().lease_idle_linger_s)
        lingered = 0.0
        while worker in pool.idle and (
                worker.get("pipeline", 0) > 0
                or worker.get("caller_pipeline", 0) > 0):
            # Pipelined pushes still executing: the lease cannot be
            # returned yet. Ring-published entries hold the same
            # pipeline counter, so a ring-attached lease with in-flight
            # slots is pinned against return (and hence against raylet
            # recycling) exactly like an in-flight RPC push. Bounded
            # wait — a pipeline counter that never
            # drains (accounting bug, wedged push) must not pin the
            # raylet's resources forever; force-return past the cap.
            if lingered > 10.0:
                logger.warning(
                    "lease %s idle with pipeline=%s for %.0fs; "
                    "force-returning it",
                    worker.get("lease_id"), worker.get("pipeline"),
                    lingered)
                break
            await asyncio.sleep(0.25)
            lingered += 0.25
        if worker not in pool.idle:
            return
        pool.idle.remove(worker)
        worker["avail"] = False
        if not worker.get("returned"):
            worker["returned"] = True
            await self._return_worker(worker)

    async def _request_lease(self, resources: Dict[str, float],
                             is_actor: bool = False,
                             bundle: Optional[Tuple[str, int]] = None,
                             address: Optional[str] = None) -> dict:
        grants = await self._lease_request_loop(
            resources, 1, is_actor=is_actor, bundle=bundle,
            address=address)
        return grants[0]

    async def _lease_request_loop(self, resources: Dict[str, float],
                                  n: int, is_actor: bool = False,
                                  bundle: Optional[Tuple[str, int]] = None,
                                  address: Optional[str] = None
                                  ) -> List[dict]:
        """The one lease-request state machine, single or batched
        (n > 1 → request_worker_leases): connect dial policy, spillback
        chain, cancel-on-timeout and grant bookkeeping live HERE so the
        two paths can never drift."""
        address = address or self.raylet_address
        # PG-bundle leases are pinned to their reserved node; everything
        # else reached via a non-local address is a spillback target.
        pinned_address = address != self.raylet_address
        spillbacks = 0
        request_id = uuid.uuid4().hex
        while True:
            try:
                # Spillback targets get a short dial: a freshly-dead node
                # (stale cluster view) must cost ~2s, not a full connect
                # window per retry — fall back to the local raylet, whose
                # view refreshes within the health-check period.
                # Short dial ONLY for spillback targets (possibly dead,
                # stale view); local and PG-pinned addresses keep the
                # full window.
                is_spillback_target = (not pinned_address
                                       and address != self.raylet_address)
                client = await self._raylet_client(
                    address,
                    connect_timeout=2.0 if is_spillback_target else 10.0)
            except (ConnectionLost, OSError):
                if pinned_address or address == self.raylet_address:
                    raise
                address = self.raylet_address
                spillbacks += 1
                continue
            try:
                reply = await client.call(
                    "request_worker_lease" if n == 1
                    else "request_worker_leases",
                    req=to_wire(WireLeaseRequest(
                        resources=resources, is_actor=is_actor,
                        spillback_count=spillbacks,
                        bundle=list(bundle) if bundle else None,
                        request_id=request_id,
                        job_id=self.job_id.hex(), count=n)),
                    timeout=ray_config().worker_lease_timeout_ms / 1000.0)
            except (TimeoutError, asyncio.TimeoutError):
                # Tell the raylet we gave up: drop the queued request, or
                # return the worker(s) if granted concurrently — the
                # raylet records every grant of this request_id, so one
                # cancel covers a whole batch (a timed-out client must
                # not leak N workers).
                try:
                    await client.call("cancel_lease_request",
                                      request_id=request_id, timeout=5.0)
                except Exception:
                    pass
                raise
            grants = reply.get("grants") or (
                [reply["granted"]] if reply.get("granted") else None)
            if grants:
                for info in grants:
                    info["raylet_address"] = address
                    if not is_actor:
                        # Actor leases live as long as the actor; only
                        # task leases are watchdog-swept for orphaning.
                        self._live_leases.append(info)
                return grants
            if reply.get("spillback"):
                address = reply["spillback"]
                spillbacks += 1
                continue
            raise RpcError(f"lease failed: {reply}")

    async def _lease_watchdog(self) -> None:
        """Self-healing for leaked leases: any granted lease that is not
        circulating (not in a pool, no waiter promise), has no in-flight
        push, and has sat that way for 20s is orphaned — some
        acquire/offer path lost track of it — and pins raylet resources
        forever, starving every other scheduling key. Force-return it
        and log loudly so the underlying leak is visible."""
        while True:
            await asyncio.sleep(5.0)
            now = time.monotonic()
            for worker in list(self._live_leases):
                if worker.get("returned"):
                    try:
                        self._live_leases.remove(worker)
                    except ValueError:
                        pass
                    continue
                if (worker.get("pipeline", 0) > 0
                        or worker.get("caller_pipeline", 0) > 0):
                    # Push(es) in flight: healthy — unless one has been
                    # outstanding implausibly long; then report the
                    # connection state so wedges are diagnosable.
                    started = worker.get("push_started", now)
                    if now - started > 30.0:
                        client = (self._worker_clients or {}).get(
                            worker.get("worker_address"))
                        logger.warning(
                            "lease %s: push of %r in flight for %.0fs "
                            "(worker %s, client_connected=%s, "
                            "pipeline=%s)",
                            worker.get("lease_id"),
                            worker.get("push_task_name"),
                            now - started, worker.get("worker_address"),
                            None if client is None else client.connected,
                            worker.get("pipeline"))
                    worker.pop("wd_idle_since", None)
                    continue
                if worker.get("dead") or worker.get("avail"):
                    worker.pop("wd_idle_since", None)
                    continue
                since = worker.get("wd_idle_since")
                if since is None:
                    worker["wd_idle_since"] = now
                    continue
                if now - since < 20.0:
                    continue
                logger.warning(
                    "lease %s orphaned for %.0fs (not circulating, no "
                    "in-flight push); force-returning it",
                    worker.get("lease_id"), now - since)
                worker["dead"] = True  # never recirculate
                worker["returned"] = True
                try:
                    self._live_leases.remove(worker)
                except ValueError:
                    pass
                await self._return_worker(worker)

    async def _return_worker(self, worker: dict, dead: bool = False) -> None:
        # A ring-attached lease detaches and destroys its pair BEFORE
        # the return reaches the raylet (see _detach_worker_ring).
        st = self._worker_rings.get(worker.get("worker_id"))
        if isinstance(st, dict):
            await self._detach_worker_ring(st)
        elif st is False:
            # The failed/dead latch covers only THIS lease: forget it
            # at return so a future lease of the same (live) worker
            # can attach a fresh pair — and retired workers' latches
            # don't accumulate in the map forever.
            self._worker_rings.pop(worker.get("worker_id"), None)
        item = {"lease_id": worker["lease_id"],
                "worker_id": worker["worker_id"],
                "resources": worker.get("resources", {}),
                "dead": dead}
        address = worker["raylet_address"]
        if not self._lease_return_batching:
            if flight.enabled:
                flight.instant("lease", "return", arg=1)
            await self._send_lease_returns(address, [item])
            return
        # Batched lease returns (round 10, ROADMAP 4c): a burst's
        # returns land as N items in THIS loop pass and the one
        # deferred flush sends them as a single return_worker_leases
        # RPC — the mirror of the round-8 grant batch, same
        # deferred-pump discipline as _drain_submits/_schedule_pump.
        batch = self._pending_lease_returns.get(address)
        if batch is None:
            batch = self._pending_lease_returns[address] = {
                "items": [],
                "fut": asyncio.get_running_loop().create_future()}
            asyncio.get_running_loop().call_soon(
                lambda: self._spawn_ring_task(
                    self._flush_lease_returns(address)))
        batch["items"].append(item)
        await batch["fut"]

    async def _flush_lease_returns(self, address: str) -> None:
        batch = self._pending_lease_returns.pop(address, None)
        if batch is None:
            return
        if attribution.enabled and len(batch["items"]) > 1:
            attribution.value("lease.return_batch", len(batch["items"]))
        if flight.enabled:
            flight.instant("lease", "return", arg=len(batch["items"]))
        try:
            await self._send_lease_returns(address, batch["items"])
        finally:
            if not batch["fut"].done():
                batch["fut"].set_result(None)

    async def _send_lease_returns(self, address: str,
                                  items: List[dict]) -> None:
        # A lost return leaks the lease's resources at the raylet FOREVER
        # (observed: returns timing out against a raylet busy with bulk
        # object IO starved a whole module's scheduling). Retry with
        # backoff — both return handlers are idempotent — and log loudly
        # if the lease(s) could not be returned.
        last: Optional[Exception] = None
        for attempt in range(4):
            if attempt:
                await asyncio.sleep(0.5 * attempt)
            try:
                client = await self._raylet_client(address)
                if len(items) == 1:
                    it = items[0]
                    await client.call("return_worker",
                                      lease_id=it["lease_id"],
                                      worker_id=it["worker_id"],
                                      resources=it["resources"],
                                      dead=it["dead"], timeout=10.0)
                else:
                    await client.call("return_worker_leases",
                                      returns=items, timeout=10.0)
                return
            except Exception as e:  # noqa: BLE001
                last = e
        logger.warning("could not return lease(s) %s to %s after retries "
                       "(%s); their resources may be stranded",
                       [it.get("lease_id") for it in items],
                       address, last)

    # -- clients -------------------------------------------------------
    async def _raylet_client(self, address: str,
                             connect_timeout: float = 10.0) -> RpcClient:
        client = self._raylet_clients.get(address)
        if client is None or not client.connected:
            client = RpcClient(address)
            await client.connect(timeout=connect_timeout)
            self._raylet_clients[address] = client
        return client

    _worker_client_cache: Dict[str, RpcClient]

    async def _worker_client(self, address: str) -> RpcClient:
        cache = getattr(self, "_worker_clients", None)
        if cache is None:
            cache = self._worker_clients = {}
        client = cache.get(address)
        if client is None or not client.connected:
            client = RpcClient(address)
            await client.connect(timeout=10.0)
            cache[address] = client
        return client

    # ==================================================================
    # actors (reference: actor lifecycle gcs_actor_manager.h:251, direct
    # actor transport; creation here is owner-led)
    # ==================================================================
    def create_actor(self, actor_class, opts, args, kwargs):
        from ray_tpu.core.actor import ActorHandle
        from ray_tpu.core.options import resource_demand

        actor_id = ActorID.of(self.job_id)
        aid = actor_id.hex()
        cls_key = self._fn.export(actor_class._cls)
        meta = actor_class.method_meta()
        # Placement needs 1 CPU when nothing is specified; the running actor
        # then holds only its explicit demand (reference actor defaults).
        running_demand = resource_demand(opts)
        demand = running_demand or {"CPU": 1.0}
        detached = opts.lifetime == "detached"
        if opts.lifetime not in (None, "detached", "non_detached"):
            raise ValueError(
                f"lifetime must be None, 'detached' or 'non_detached', "
                f"got {opts.lifetime!r}")
        if detached and not opts.name:
            raise ValueError(
                "detached actors must be named: they are reached via "
                "get_actor(name) after their creator exits")
        info = {
            "class_name": actor_class._class_name,
            "name": opts.name,
            "namespace": (self.namespace if opts.namespace is None
                          else opts.namespace),
            "owner": self.address,
            "state": "PENDING",
            "max_restarts": opts.max_restarts,
            "max_task_retries": opts.max_task_retries,
            "job_id": self.job_id.hex(),
            "detached": detached,
            "method_meta": {k: {kk: vv for kk, vv in m.items()}
                            for k, m in meta.items()},
        }
        reply = self._loop.run(self._gcs.register_actor(aid, info))
        if not reply.get("ok"):
            raise ValueError(reply.get("error", "actor registration failed"))

        state = _ActorState(aid)
        state.restarts_remaining = opts.max_restarts
        state.task_retries = opts.max_task_retries
        args_blob, pinned = self._serialize_args(args, kwargs)
        state.creation = {
            "cls_key": cls_key,
            "args": args_blob,
            "detached": detached,
            "demand": demand,
            "release_after_start": {} if running_demand else demand,
            "max_concurrency": opts.max_concurrency,
            "concurrency_groups": opts.concurrency_groups,
            "runtime_env": _prepared_env(self, opts),
            "class_name": actor_class._class_name,
            "pg": ({"pg_id": _pg_id_of(opts.placement_group),
                    "bundle_index": getattr(
                        opts, "placement_group_bundle_index", -1)}
                   if getattr(opts, "placement_group", None) is not None
                   else None),
        }
        self._actors[aid] = state
        # Constructor-arg refs stay pinned for the actor's whole life: a
        # restart replays creation["args"], so they must survive until the
        # actor is terminally DEAD (r2 review finding).
        state.pinned_args = pinned
        self._actor_meta[aid] = (actor_class._class_name, meta)
        try:
            self._loop.run(self._create_actor_async(state))
        except BaseException:
            self._unpin_actor(state)
            raise
        if state.state == "DEAD":
            self._unpin_actor(state)
        return ActorHandle(actor_id, actor_class._class_name, meta,
                           runtime=self)

    def _unpin_actor(self, state: _ActorState) -> None:
        pinned, state.pinned_args = state.pinned_args, []
        self._unpin_args(pinned)

    async def _create_actor_async(self, state: _ActorState) -> None:
        creation = state.creation
        pg = creation.get("pg")
        bundle = None
        address = None
        if pg is not None:
            address, idx = await self._pg_location(
                pg["pg_id"], pg["bundle_index"], demand=creation["demand"])
            bundle = (pg["pg_id"], idx)
        # Lease timeouts are transient (busy/recovering cluster): retry a
        # few times before declaring the creation failed, like task
        # submission does.
        attempt = 0
        while True:
            try:
                worker = await self._request_lease(
                    creation["demand"], is_actor=True, bundle=bundle,
                    address=address)
                break
            except (TimeoutError, asyncio.TimeoutError, OSError,
                    ConnectionLost):
                # RpcError refusals (infeasible demand, missing bundle)
                # are deterministic — retrying them only delays the real
                # error.
                attempt += 1
                if attempt > 3:
                    raise
                await asyncio.sleep(
                    ray_config().task_retry_delay_ms / 1000.0 or 0.2)
        client = await self._worker_client(worker["worker_address"])
        try:
            reply = await client.call(
                "actor_init", actor_id=state.actor_id_hex,
                cls_key=creation["cls_key"], args=creation["args"],
                max_concurrency=creation["max_concurrency"],
                owner=self.address, job_id=self.job_id.hex(),
                visible_chips=worker.get("chip_ids") or None,
                concurrency_groups=creation.get("concurrency_groups"),
                runtime_env=creation.get("runtime_env"),
                timeout=120.0)
        except Exception as e:
            await self._return_worker(worker, dead=True)
            await self._gcs.update_actor(state.actor_id_hex, {
                "state": "DEAD", "death_cause": f"init push failed: {e}"})
            raise
        if reply.get("error_blob") is not None:
            await self._return_worker(worker, dead=False)
            await self._gcs.update_actor(state.actor_id_hex, {
                "state": "DEAD", "death_cause": "exception in __init__"})
            state.state = "DEAD"
            # Surface the constructor error to the caller now.
            self._deserialize_payload(reply["error_blob"])
            return
        raylet_client = await self._raylet_client(worker["raylet_address"])
        await raylet_client.call(
            "mark_actor_worker", worker_id=worker["worker_id"],
            actor_id=state.actor_id_hex,
            release=creation.get("release_after_start") or None,
            job_id=self.job_id.hex(),
            detached=creation.get("detached", False), timeout=5.0)
        state.address = worker["worker_address"]
        state.client = client
        state.state = "ALIVE"
        await self._gcs.update_actor(state.actor_id_hex, {
            "state": "ALIVE", "address": worker["worker_address"],
            "node_id": worker["node_id"], "worker_id": worker["worker_id"],
        })

    def submit_actor_task(self, handle, method_name, opts, args, kwargs):
        _t0 = time.perf_counter() if attribution.enabled else 0.0
        aid = handle._ray_actor_id.hex()
        task_id = TaskID.for_actor_task(handle._ray_actor_id)
        streaming = opts.num_returns in ("streaming", "dynamic")
        num_returns = 1 if streaming else opts.num_returns
        args_blob, pinned = self._serialize_args(args, kwargs)
        with self._actor_seq_lock:
            seq = self._actor_call_seq.get(aid, 0)
            self._actor_call_seq[aid] = seq + 1
        trace_ctx = current_traceparent() if tracing_enabled() else None
        tkey = (aid, method_name, num_returns, streaming)
        tmpl = self._actor_templates.get(tkey)
        if tmpl is None:
            proto = WireActorTaskSpec(
                task_id=task_id.hex(),
                job_id=self.job_id.hex(),
                actor_id=aid,
                method=method_name,
                name=f"{handle._class_name}.{method_name}",
                args=args_blob,
                num_returns=num_returns,
                streaming=streaming,
                owner=self.address,
                seq=seq,
                concurrency_group=(handle._method_meta or {}).get(
                    method_name, {}).get("concurrency_group"),
                trace_ctx=trace_ctx,
            )
            if len(self._actor_templates) >= 1024:
                self._actor_templates.clear()
            tmpl = self._actor_templates[tkey] = SpecTemplate(proto)
        spec = tmpl.encode(task_id=task_id.hex(), args=args_blob,
                           seq=seq, trace_ctx=trace_ctx)
        if attribution.enabled:
            attribution.record("submit.encode", time.perf_counter() - _t0)
        refs = self._make_return_refs(task_id, num_returns)
        self._record_task_event(task_id.hex(), spec["name"], "SUBMITTED",
                                actor_id=aid)
        gen = None
        if streaming:
            gen = ObjectRefGenerator()
            self._generators[task_id.hex()] = gen
        self._enqueue_submit(("actor", spec, refs, pinned))
        if streaming:
            return gen
        if opts.num_returns == 0:
            return None
        return refs[0] if opts.num_returns == 1 else refs

    async def _actor_client(self, aid: str) -> RpcClient:
        state = self._actors.get(aid)
        if state is None or state.address is None or state.state != "ALIVE":
            # Borrowed handle or restarting actor: resolve via GCS, waiting
            # briefly for PENDING/RESTARTING actors to come up.
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                info = await self._gcs.get_actor(actor_id=aid)
                if info is None:
                    raise ActorDiedError(error_msg="unknown actor")
                if info["state"] == "ALIVE":
                    if state is None:
                        state = _ActorState(aid)
                        state.task_retries = info.get(
                            "max_task_retries", 0) or 0
                        self._actors[aid] = state
                    state.address = info["address"]
                    state.state = "ALIVE"
                    break
                if info["state"] == "DEAD":
                    raise ActorDiedError(
                        error_msg=f"actor is dead: "
                                  f"{info.get('death_cause', 'unknown')}")
                await asyncio.sleep(0.1)
            else:
                raise ActorUnavailableError(
                    error_msg="timed out waiting for actor to become ALIVE")
        return await self._worker_client(state.address)

    async def _submit_actor_async(self, spec: dict, refs: List[ObjectRef],
                                  pinned: Optional[List[ObjectID]] = None
                                  ) -> None:
        aid = spec["actor_id"]
        # Per-task retry budget for SYSTEM failures (reference:
        # direct_actor_task_submitter.h — client queues resubmit through
        # an actor restart when max_task_retries allows; -1 = infinite).
        state = self._actors.get(aid)
        retries_left = state.task_retries if state is not None else 0
        try:
            if spec["task_id"] in self._cancel_requested:
                # Cancelled before the push left this process: resolve the
                # refs AND tell the worker to skip this seq so the next
                # call doesn't stall behind the hole.
                self._fail_task_cancelled(spec, refs)
                try:
                    client = await self._actor_client(aid)
                    await client.notify("actor_seq_skip",
                                        owner=self.address,
                                        seq=spec.get("seq"))
                except Exception:
                    pass  # 60s gate timeout is the backstop
                return
            while True:
                pushed_addr = None
                try:
                    client = await self._actor_client(aid)
                    state = self._actors.get(aid)
                    if state is not None and state.address:
                        pushed_addr = state.address
                        self._inflight_task_workers[spec["task_id"]] = (
                            state.address, True)
                    reply = await client.call(
                        "push_actor_task",
                        spec=(to_wire(spec) if hasattr(spec, "_wire_name")
                              else spec),
                        timeout=None)
                    self._record_task_reply(spec, reply)
                    return
                except RayActorError as e:
                    self._fail_actor_task(spec, refs, e)
                    return
                except (ConnectionLost, RpcError) as e:
                    state = self._actors.get(aid)
                    if (state is not None and state.state == "ALIVE"
                            and (pushed_addr is None
                                 or state.address == pushed_addr)):
                        # We are first to observe this death; a concurrent
                        # handler that already restarted the actor (fresh
                        # address) must not be knocked back to RESTARTING.
                        state.state = "RESTARTING"
                        state.address = None
                    if state is None or retries_left == 0:
                        # No retry budget: fail the call, restart (if
                        # allowed) in the background for FUTURE calls.
                        if state is not None:
                            asyncio.ensure_future(
                                self._maybe_restart_actor(state))
                        self._fail_actor_task(
                            spec, refs, ActorDiedError(
                                error_msg=f"actor connection lost: {e}"))
                        return
                    if retries_left > 0:
                        retries_left -= 1
                    if not await self._restart_and_wait(state):
                        self._fail_actor_task(
                            spec, refs, ActorDiedError(
                                error_msg="actor died and could not be "
                                          f"restarted: {e}"))
                        return
                    # Actor is ALIVE again: resubmit this task to the new
                    # incarnation (same seq; the fresh worker adopts the
                    # first seq it sees).
        except Exception as e:  # noqa: BLE001
            self._fail_actor_task(
                spec, refs, RayActorError(error_msg=str(e)))
        finally:
            self._inflight_task_workers.pop(spec["task_id"], None)
            self._cancel_requested.discard(spec["task_id"])
            if pinned:
                self._unpin_args(pinned)

    async def _restart_and_wait(self, state: "_ActorState",
                                timeout: float = 120.0) -> bool:
        """Drive (or wait out a concurrent) actor restart; True when the
        actor is ALIVE again. Runs on the single RPC event loop, so the
        restart_inflight check-then-act below cannot interleave."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if state.state == "ALIVE":
                return True
            if state.state == "DEAD":
                return False
            if not state.restart_inflight:
                return await self._maybe_restart_actor(state)
            await asyncio.sleep(0.05)
        return state.state == "ALIVE"

    async def _maybe_restart_actor(self, state: Optional[_ActorState]
                                   ) -> bool:
        """Owner-led actor restart (reference: GCS restarts up to
        max_restarts, gcs_actor_manager.h RESTARTING). Guarded so concurrent
        triggers (kill + in-flight ConnectionLost) run exactly one attempt."""
        if state is None:
            return False
        if state.restart_inflight or state.state == "ALIVE":
            return state.state == "ALIVE"
        if state.creation is None or state.restarts_remaining == 0:
            if state.creation is not None:
                await self._gcs.update_actor(state.actor_id_hex, {
                    "state": "DEAD", "death_cause": "worker died"})
            state.state = "DEAD"
            self._unpin_actor(state)
            return False
        state.restart_inflight = True
        try:
            if state.restarts_remaining > 0:
                state.restarts_remaining -= 1
            state.state = "RESTARTING"
            await self._gcs.update_actor(state.actor_id_hex,
                                         {"state": "RESTARTING"})
            await asyncio.sleep(
                ray_config().actor_restart_backoff_ms / 1000.0)
            try:
                await self._create_actor_async(state)
            except Exception:
                state.state = "DEAD"
            if state.state == "DEAD":
                self._unpin_actor(state)
            return state.state == "ALIVE"
        finally:
            state.restart_inflight = False

    def _fail_actor_task(self, spec, refs, exc) -> None:
        blob = serialization.serialize_error(exc).to_bytes()
        for r in refs:
            entry = self._owned_entry(r.hex())
            if not entry.fut.done():
                entry.fut.set_result(("inline", blob))
        gen = self._generators.pop(spec["task_id"], None)
        if gen is not None:
            gen._finish(exc)

    def kill_actor(self, handle, no_restart: bool = True) -> None:
        aid = handle._ray_actor_id.hex()
        state = self._actors.get(aid)
        # ray.kill(no_restart=False) lets a restartable actor come back
        # (reference: gcs_actor_manager destroys vs restarts on KillActor).
        restartable = (not no_restart and state is not None
                       and state.creation is not None
                       and state.restarts_remaining != 0)
        if no_restart and state is not None:
            state.restarts_remaining = 0
            state.creation = None
        if no_restart:
            with self._actor_seq_lock:
                self._actor_call_seq.pop(aid, None)

        async def _kill():
            try:
                info = await self._gcs.get_actor(actor_id=aid)
                if restartable:
                    # Publish RESTARTING before the worker exits so borrowers
                    # never resolve the stale ALIVE address of a dead worker
                    # during the kill->restart window.
                    await self._gcs.update_actor(aid, {
                        "state": "RESTARTING", "address": None})
                else:
                    await self._gcs.update_actor(aid, {
                        "state": "DEAD", "death_cause": "ray.kill"})
                if info and info.get("address"):
                    client = await self._worker_client(info["address"])
                    await client.notify("exit_worker")
            except Exception:
                pass

        self._loop.run(_kill(), timeout=10)
        if state is None:
            return
        if restartable:
            state.state = "RESTARTING"
            state.address = None
            self._loop.spawn(self._maybe_restart_actor(state))
        else:
            state.state = "DEAD"
            self._unpin_actor(state)

    def get_actor(self, name: str, namespace: Optional[str] = None):
        from ray_tpu.core.actor import ActorHandle

        info = self._loop.run(self._gcs.get_actor(
            name=name, namespace=namespace or self.namespace))
        if info is None or info.get("state") == "DEAD":
            raise ValueError(f"Failed to look up actor with name '{name}'")
        actor_id = ActorID(bytes.fromhex(info["actor_id"]))
        return ActorHandle(actor_id, info.get("class_name", "Actor"),
                           info.get("method_meta", {}), runtime=self)

    def cancel(self, ref: ObjectRef, force: bool = False,
               recursive: bool = True) -> None:
        """Cancel the task that produces `ref` (reference:
        core_worker cancellation: queued tasks are dropped; running
        tasks get TaskCancelledError raised in their thread; force=True
        kills the executing worker process)."""
        task_hex = ref.id().task_id().hex()
        with self._owned_lock:
            entry = self._owned.get(ref.hex())
        if entry is not None and entry.fut.done():
            # Already finished: cancel is a no-op (reference semantics) —
            # and must not leave a flag that would poison a later lineage
            # re-execution of this same task id.
            return
        inflight = self._inflight_task_workers.get(task_hex)
        if inflight is not None and inflight[1] and force:
            # Reference parity: force-killing an actor task would kill
            # the whole actor (collateral damage to every other caller).
            raise ValueError(
                "force=True is not supported for actor tasks; use "
                "ray_tpu.kill on the actor instead")
        self._cancel_requested.add(task_hex)
        if inflight is None:
            return  # queued (or already done): handled at push time
        address = inflight[0]

        async def _cancel():
            try:
                client = await self._worker_client(address)
                await client.call("cancel_task", task_id=task_hex,
                                  force=force, timeout=10.0)
            except Exception:
                pass  # worker already gone

        self._loop.run(_cancel(), timeout=15)

    async def handle_cancel_task(self, conn: ServerConnection, *,
                                 task_id: str,
                                 force: bool = False) -> dict:
        """Worker-side: interrupt the execution of `task_id` — cancel its
        coroutine (async actor methods), async-raise in its thread (sync
        code), or mark it cancelled-before-start."""
        thread_id = self._running_task_threads.get(task_id)
        if thread_id is None:
            # Not started yet (queued behind the actor's concurrency or
            # seq gate): poison it so execution aborts immediately.
            self._cancelled_pending.add(task_id)
            return {"found": False}
        if force:
            # Reference force-cancel kills the worker process; the raylet
            # monitor reaps it and the owner sees ConnectionLost.
            os._exit(137)
        cfut = self._running_task_cfuts.get(task_id)
        if cfut is not None:
            # Async method: the executor thread is parked in
            # cfut.result() where an async-raise cannot land — cancel
            # the coroutine instead.
            cfut.cancel()
            return {"found": True}
        import ctypes

        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(thread_id),
            ctypes.py_object(TaskCancelledError))
        return {"found": True}

    # ==================================================================
    # placement groups (reference: python/ray/util/placement_group.py:41 +
    # gcs_placement_group_scheduler.h 2PC; owner-led here, like actors)
    # ==================================================================
    def create_placement_group(self, bundles: List[Dict[str, float]],
                               strategy: str = "PACK", name: str = "",
                               target_node_ids: Optional[List[str]] = None
                               ) -> str:
        from ray_tpu.core.ids import PlacementGroupID
        from ray_tpu.core.pg_scheduler import validate_pg_args

        validate_pg_args(bundles, strategy)
        pg_id = PlacementGroupID.of(self.job_id).hex()
        info = {
            "bundles": [dict(b) for b in bundles],
            "strategy": strategy,
            "name": name,
            "state": "PENDING",
            "owner": self.address,
            "target_node_ids": target_node_ids,
        }
        self._loop.run(self._gcs.register_placement_group(pg_id, info))
        self._loop.spawn(self._schedule_pg_async(pg_id, info))
        return pg_id

    async def _schedule_pg_async(self, pg_id: str, info: dict) -> None:
        # The 2PC itself is the module-level schedule_placement_group —
        # one protocol definition shared with the simcluster harness.
        await schedule_placement_group(self._gcs, self._raylet_client,
                                       pg_id, info)

    def placement_group_wait(self, pg_id: str,
                             timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            info = self._loop.run(self._gcs.get_placement_group(pg_id))
            state = (info or {}).get("state")
            if state == "CREATED":
                return True
            if state in ("INFEASIBLE", "REMOVED", None):
                return False
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(0.05)

    def remove_placement_group(self, pg_id: str) -> None:
        info = self._loop.run(self._gcs.get_placement_group(pg_id))
        if info is None or info.get("state") == "REMOVED":
            return

        async def _remove():
            # Record REMOVED FIRST, then return the bundles: any return
            # that fails (dead raylet, dropped message, owner crash
            # mid-loop) is mopped up by raylet-side reconciliation
            # against the terminal state (_maybe_reconcile_bundles).
            # The reverse order strands committed bundles behind a
            # forever-CREATED record nobody will ever reclaim.
            await self._gcs.update_placement_group(
                pg_id, {"state": "REMOVED"})
            for idx, loc in enumerate(info.get("bundle_locations") or []):
                try:
                    client = await self._raylet_client(loc["address"])
                    await client.call("return_bundle", pg_id=pg_id,
                                      bundle_index=idx, timeout=10.0)
                except Exception:
                    pass

        self._loop.run(_remove(), timeout=30)
        self._pg_cache.pop(pg_id, None)

    def placement_group_table(self, pg_id: Optional[str] = None):
        if pg_id is not None:
            return self._loop.run(self._gcs.get_placement_group(pg_id))
        return {p["pg_id"]: p
                for p in self._loop.run(self._gcs.list_placement_groups())}

    async def _pg_location(self, pg_id: str, bundle_index: int,
                           demand: Optional[Dict[str, float]] = None
                           ) -> Tuple[str, int]:
        """Resolve (raylet_address, bundle_index) for a lease against a PG,
        waiting for a still-scheduling group. bundle_index -1 → round-robin
        over the bundles whose spec can hold `demand` (reference:
        any-feasible-bundle semantics)."""

        info = self._pg_cache.get(pg_id)
        if info is None or info.get("state") != "CREATED":
            # Generous deadline: the owner-side scheduler terminates in
            # CREATED or INFEASIBLE after bounded attempts — but if the
            # owner process died mid-scheduling the record stays PENDING
            # forever, so don't spin unbounded on someone else's PG.
            deadline = time.monotonic() + 300.0
            while True:
                info = await self._gcs.get_placement_group(pg_id)
                state = (info or {}).get("state")
                if state == "CREATED":
                    self._pg_cache[pg_id] = info
                    break
                if state in ("REMOVED", "INFEASIBLE", None):
                    raise ValueError(
                        f"placement group {pg_id} is unusable "
                        f"(state={state}: "
                        f"{(info or {}).get('detail', '')})")
                if time.monotonic() >= deadline:
                    raise ValueError(
                        f"placement group {pg_id} stuck PENDING for 300s "
                        "(owner died mid-scheduling?)")
                await asyncio.sleep(0.1)
        locs = info["bundle_locations"]
        if bundle_index is None or bundle_index < 0:
            specs = info.get("bundles", [])
            feasible = [i for i in range(len(locs))
                        if not demand or not specs
                        or all(specs[i].get(k, 0.0) + 1e-9 >= v
                               for k, v in demand.items())]
            if not feasible:
                raise ValueError(
                    f"no bundle of placement group {pg_id} can hold "
                    f"{demand}; bundles: {specs}")
            self._pg_rr[pg_id] = self._pg_rr.get(pg_id, -1) + 1
            bundle_index = feasible[self._pg_rr[pg_id] % len(feasible)]
        if bundle_index >= len(locs):
            raise ValueError(
                f"bundle index {bundle_index} out of range for placement "
                f"group with {len(locs)} bundles")
        return locs[bundle_index]["address"], bundle_index

    # ==================================================================
    # owner-side RPC service (reference: CoreWorkerService pubsub/locations)
    # ==================================================================
    async def handle_get_object_locations(self, conn: ServerConnection, *,
                                          oid: str) -> Optional[dict]:
        with self._owned_lock:
            entry = self._owned.get(oid)
        if entry is None:
            return None
        if not entry.fut.done():
            return {"pending": True, "nodes": []}
        kind, payload = entry.fut.result()
        if kind == "inline":
            return {"inline": payload}
        return {"nodes": list(entry.nodes)}

    async def handle_get_object_locations_batch(
            self, conn: ServerConnection, *,
            oids: List[str]) -> Dict[str, Optional[dict]]:
        """Batched location query: one RPC resolves every ref this caller
        is waiting on (reference: batched WaitRequest — kills the
        per-ref-per-tick polling storm)."""
        out: Dict[str, Optional[dict]] = {}
        for oid in oids:
            out[oid] = await self.handle_get_object_locations(conn,
                                                              oid=oid)
        return out

    async def handle_generator_item(self, conn: ServerConnection, *,
                                    task_id: str, oid: str,
                                    inline: Optional[bytes] = None,
                                    node: Optional[str] = None) -> bool:
        entry = self._owned_entry(oid)
        if node:
            if node not in entry.nodes:
                entry.nodes.append(node)
            entry.is_stored = True
            if not entry.fut.done():
                entry.fut.set_result(("node", node))
        elif not entry.fut.done():
            entry.fut.set_result(("inline", inline))
        gen = self._generators.get(task_id)
        if gen is not None:
            gen._push(ObjectRef(ObjectID(bytes.fromhex(oid)),
                                owner=self.address, runtime=self))
        return True

    async def handle_prune_object_location(self, conn: ServerConnection, *,
                                           oid: str, node: str) -> bool:
        """A raylet discovered `node` no longer holds `oid` (evicted or
        died): drop the stale location; when the LAST copy is gone,
        re-execute the producing task if its lineage is retained
        (reference: object_recovery_manager.h:41)."""
        lost = False
        with self._owned_lock:
            entry = self._owned.get(oid)
            if entry is not None and node in entry.nodes:
                entry.nodes = [n for n in entry.nodes if n != node]
                lost = not entry.nodes and entry.is_stored
        if lost:
            self._trigger_reconstruction(oid)
        return True

    def _trigger_reconstruction(self, oid: str) -> bool:
        """Re-execute the task that produced `oid` (owner-side; runs on the
        RPC loop). Pullers observing `pending` keep waiting meanwhile.
        Returns True when a re-execution is running (started now or
        already inflight); False means the loss is final (unretained
        lineage or exhausted budget) and the typed error stands."""
        verdict, rec = self._lineage.begin_reexec(oid)
        if verdict == lineage_mod.INFLIGHT:
            return True
        if verdict != lineage_mod.STARTED:
            if verdict == lineage_mod.EXHAUSTED:
                logger.warning("object %s lost and reconstruction budget "
                               "exhausted", oid[:16])
            return False
        refs = []
        with self._owned_lock:
            for roid in rec["ref_oids"]:
                entry = self._owned.get(roid)
                if entry is None:
                    continue
                if entry.is_stored and entry.nodes:
                    continue  # sibling return with healthy copies: keep it
                # Reset to pending: directory answers "pending" until the
                # re-executed task stores fresh copies.
                entry.fut = concurrent.futures.Future()
                entry.nodes = []
                entry.is_stored = False
        for roid in rec["ref_oids"]:
            refs.append(ObjectRef(ObjectID(bytes.fromhex(roid)),
                                  owner=self.address, runtime=self))
        logger.info("reconstructing %s via re-execution of %s (%d budget "
                    "left)", oid[:16], rec["spec"].get("name"), rec["left"])

        async def _resubmit():
            try:
                await self._submit_async(rec["spec"], refs, None)
            except BaseException as e:  # noqa: BLE001
                logger.warning("reconstruction resubmit for %s aborted: "
                               "%r", oid[:16], e)
                raise
            finally:
                self._lineage.end_reexec(rec)
                if logger.isEnabledFor(logging.DEBUG):
                    with self._owned_lock:
                        e = self._owned.get(oid)
                        logger.debug(
                            "reconstruction resubmit finished for %s: "
                            "done=%s nodes=%s stored=%s", oid[:16],
                            e is not None and e.fut.done(),
                            e.nodes if e else None,
                            e.is_stored if e else None)

        self._loop.spawn(_resubmit())
        return True

    async def handle_reconstruct_object(self, conn: ServerConnection, *,
                                        oid: str) -> Dict[str, Any]:
        """A raylet's pull found no reachable copy of an object we own:
        decide recovery. `recovering=True` tells the puller to keep
        polling (a value is pending, copies reappeared, or a lineage
        re-execution just started); False means the loss is final and
        the borrower's get must fail with the typed error. This closes
        the notify race where a prune was still in flight when the
        puller's next locations query saw an empty directory."""
        with self._owned_lock:
            entry = self._owned.get(oid)
            if entry is None:
                return {"recovering": False, "known": False}
            if not entry.fut.done():
                return {"recovering": True}
            if entry.nodes:
                # Copies (re)appeared since the puller looked — or the
                # puller's view raced a fresh seal. Re-resolve.
                return {"recovering": True}
            kind, _ = entry.fut.result()
            if kind == "inline":
                # Inline values live in the owner future; the next
                # locations query returns the payload itself.
                return {"recovering": True}
        return {"recovering": self._trigger_reconstruction(oid)}

    async def handle_ping(self, conn: ServerConnection) -> str:
        return "pong"

    async def handle_dump_flight_record(
            self, conn: ServerConnection, *,
            window_s: Optional[float] = None,
            include_events: bool = True) -> dict:
        """This process's flight-recorder ring + stall episodes (the
        raylet's fan-out handler of the same name collects these from
        every worker on its node; the dashboard merges nodes)."""
        return flight.dump(window_s=window_s,
                           include_events=include_events)

    # ==================================================================
    # worker-mode execution (reference: core_worker.cc:2596 ExecuteTask +
    # _raylet.pyx task_execution_handler)
    # ==================================================================
    def _ensure_job_env(self, job_id: Optional[str]) -> None:
        """Extend sys.path with the driver's entries so driver-local modules
        (test files, scripts) resolve when unpickling by reference."""
        if not job_id:
            return
        if self.mode == "worker" and len(job_id) == len(self.job_id.hex()):
            # Adopt the job we execute for — on EVERY push, since a reused
            # worker can serve different jobs across leases: tasks/actors
            # submitted FROM this worker (e.g. a Tune trial spawning its
            # training gang) must carry the original driver's job so their
            # workers resolve driver-local modules too (reference: job_id
            # rides the TaskSpec end-to-end).
            self.job_id = JobID(bytes.fromhex(job_id))
        if job_id in self._job_envs_applied:
            return
        try:
            info = self._loop.run(self._gcs.get_job(job_id), timeout=10)
        except Exception:
            return  # transient GCS error: leave unmarked so we retry
        import sys
        with self._job_env_lock:
            if job_id in self._job_envs_applied:
                return
            for p in (info or {}).get("sys_path", []):
                if p not in sys.path:
                    sys.path.append(p)
            # A falsy record is memoized too: the job is simply gone from
            # the GCS table and won't come back, so don't re-query per task.
            self._job_envs_applied.add(job_id)

    def _resolve_task_args(self, args_blob: bytes):
        """Returns (args, kwargs, arg_refs) where arg_refs is the list of
        (oid, owner) pairs for every ref deserialized from the payload —
        the input for _commit_arg_borrows at task completion."""
        if args_blob is ClusterRuntime._empty_args_blob:
            # Inline fast path: the shared zero-arg blob (identity, not
            # equality — a wire copy never matches) decodes to a known
            # constant; skip the unpickle.
            return (), {}, []
        _deser_ctx.suppress_borrow = True
        _deser_ctx.arg_refs = []
        try:
            args, kwargs = self._deserialize_payload(args_blob)
        finally:
            _deser_ctx.suppress_borrow = False
            arg_refs = _deser_ctx.arg_refs
            _deser_ctx.arg_refs = None
        args = [self.get(a) if isinstance(a, ObjectRef) else a for a in args]
        kwargs = {k: self.get(v) if isinstance(v, ObjectRef) else v
                  for k, v in kwargs.items()}
        return args, kwargs, arg_refs

    def _dump_task_profile(self, profiler, task_id: str,
                           name: str) -> None:
        """Per-task cProfile dump (off unless the call site opted in
        with `.options(_metadata={"profile": True})`). The pstats text
        lands in two places: a file next to this worker's log (same
        directory the raylet tails for `/api/logs`), and — top lines
        only — on stdout, i.e. IN the worker log itself, so the
        existing log surfaces point at the full dump. Profiling output
        must never fail the task."""
        try:
            import io
            import pstats

            buf = io.StringIO()
            stats = pstats.Stats(profiler, stream=buf)
            stats.sort_stats("cumulative").print_stats(30)
            text = buf.getvalue()
            # Same resolution as the stall reports: RAY_TPU_LOG_DIR
            # when inherited (the raylet's log dir — where /api/logs
            # reads), created if missing.
            log_dir = flight.report_dir()
            wid = (self._raylet_worker_id or self.worker_id.hex())[:8]
            path = os.path.join(
                log_dir, f"worker-{wid}-profile-{task_id[:8]}.pstats.txt")
            with open(path, "w") as f:
                f.write(f"# task {name} ({task_id})\n")
                f.write(text)
            head = "\n".join(text.splitlines()[:12])
            print(f"[profile] task {name} ({task_id[:8]}) -> {path}\n"
                  f"{head}", flush=True)
        except Exception:
            logger.debug("task profile dump failed", exc_info=True)

    def _commit_arg_borrows(self, arg_refs) -> None:
        """Upgrade still-held arg-ref pins to owner-registered borrows.

        Called after task completion with args/kwargs/value dropped: any
        arg oid whose local pin count survived is retained (actor state,
        a live generator, result escrow) and the owner must count the
        borrow BEFORE our reply lets the submitter's pin lapse, or the
        owner may free the object while we still hold it (reference:
        reference_count.h — borrowed refs are reported in the task
        reply). Synchronous on purpose; costs RPCs only for tasks that
        actually retain arg refs.
        """
        pending = []  # (oid, owner, rec) needing an owner round-trip
        seen = set()
        for oid, owner in arg_refs:
            if oid in seen:
                continue
            seen.add(oid)
            with self._borrowed_lock:
                rec = self._borrowed.get(oid)
                if rec is None or rec[2]:
                    continue  # fully released during the task / registered
            pending.append((oid, owner, rec))
        if not pending:
            return

        async def _register(oid, owner):
            client = await self._worker_client(owner)
            return bool(await client.call("register_borrow", oid=oid,
                                          timeout=30.0))

        async def _register_all():
            # Concurrent: the RPCs are independent, and a dead owner must
            # cost one timeout total, not one per retained oid.
            return await asyncio.gather(
                *(_register(oid, owner) for oid, owner, _ in pending),
                return_exceptions=True)

        try:
            results = self._loop.run(
                _register_all(),
                timeout=ray_config().borrow_commit_timeout_s)
        except Exception:
            results = [False] * len(pending)
        for (oid, owner, rec), res in zip(pending, results):
            ok = res is True
            if not ok:
                # The retained ref is now unprotected: once the
                # submitter's pin lapses the owner may free the object
                # and a later get on it will fail. Leave a trail.
                logger.warning(
                    "could not register retained arg borrow for %s with "
                    "owner %s (%s); object may be freed while still held",
                    oid[:16], owner,
                    res if isinstance(res, Exception) else "refused")
            with self._borrowed_lock:
                cur = self._borrowed.get(oid)
                if cur is rec:
                    if ok:
                        rec[2] = True
                    continue
            if ok:
                # Pin released while our registration was in flight: the
                # owner counted us, so compensate.
                async def _release(oid=oid, owner=owner):
                    try:
                        client = await self._worker_client(owner)
                        await client.call("release_borrow", oid=oid,
                                          timeout=30.0)
                    except Exception:
                        pass

                self._loop.spawn(_release())

    def _escrow_pin(self, ref) -> None:
        """Pin a ref embedded in an outgoing result until consumers had
        ample time to register their borrow (window: config
        borrow_escrow_s; reference: the borrowing protocol of
        reference_count.h, here time-bounded rather than tracked per
        containing object)."""
        oid = ref.hex()
        with self._owned_lock:
            known = oid in self._owned
        if not known:
            with self._borrowed_lock:
                known = oid in self._borrowed
        if known:
            self.add_local_reference(ref.id())
        else:
            # A pass-through ref (arrived as a task arg under
            # suppress_borrow, now re-exported in our result): register
            # a real borrow with its owner so the pin actually holds.
            self.on_ref_deserialized(ref)

        async def _release_later(object_id=ref.id()):
            await asyncio.sleep(ray_config().borrow_escrow_s)
            self.remove_local_reference(object_id)

        self._loop.spawn(_release_later())

    def _package_result(self, oid: str, value: Any,
                        is_error: bool = False) -> dict:
        so = (serialization.serialize_error(value) if is_error
              else serialization.serialize(
                  value, ref_serializer=self._escrow_pin))
        size = so.total_size()
        if size <= ray_config().max_direct_call_object_size:
            return {"oid": oid, "inline": so.to_bytes()}
        shm_name = self._loop.run(
            self._raylet.call("create_object", oid=oid, size=size))
        self._shm.write_chunks(shm_name, so.chunks())
        # See _store_serialized: seal needs no round trip.
        self._loop.run(self._raylet.notify("seal_object", oid=oid))
        return {"oid": oid, "node": self.raylet_address}

    def _execute_task(self, spec: dict) -> dict:
        from ray_tpu.runtime_context import (_reset_task_context,
                                             _set_task_context)

        task_id = spec["task_id"]
        num_returns = spec["num_returns"]
        name = spec.get("name", "task")
        results: List[dict] = []
        token = _set_task_context(
            task_id=TaskID(bytes.fromhex(task_id)))
        self._record_task_event(task_id, name, "RUNNING",
                                job_id=spec.get("job_id"))
        self._running_task_threads[task_id] = threading.get_ident()
        ok = False
        arg_refs: List[tuple] = []
        args = kwargs = value = None
        # Worker-side attribution split: arg-resolution vs exec vs
        # result-packaging, so a copy regression in either data-plane
        # half (arg fetch, return store) is attributable separately
        # from user compute (rides the reply as attr_exec).
        attr_on = attribution.enabled
        split = {"arg_resolve": 0, "exec": 0, "result_pack": 0}
        _tmark = time.perf_counter() if attr_on else 0.0
        # exec_us rides EVERY successful reply (one int, two clock
        # reads): it feeds the owner's per-fn cost EMA that gates the
        # inline fast path (_inline_eligible).
        exec_us: Optional[int] = None
        try:
            if task_id in self._cancelled_pending:
                raise TaskCancelledError(task_id)
            self._apply_visible_chips(spec.get("visible_chips"))
            self._ensure_job_env(spec.get("job_id"))
            if spec.get("runtime_env"):
                from ray_tpu.core.runtime_env import apply_runtime_env

                apply_runtime_env(self, spec["runtime_env"])
            fn = self._fn.fetch(spec["fn_key"])
            args, kwargs, arg_refs = self._resolve_task_args(spec["args"])
            if attr_on:
                now = time.perf_counter()
                split["arg_resolve"] = int((now - _tmark) * 1e6)
                _tmark = now
            # Per-task cProfile opt-in (.options(_metadata={"profile":
            # True})): wraps ONLY the user-code call; the pstats text
            # dumps next to the worker log so /api/logs surfaces it.
            profiler = None
            if spec.get("profile"):
                import cProfile

                profiler = cProfile.Profile()
            _e0 = time.perf_counter()
            if tracing_enabled() or spec.get("trace_ctx"):
                # Execution span parents to the CALLER's span via the
                # propagated traceparent (reference: tracing_helper's
                # _function_span on the worker side).
                with span(f"task.run {name}",
                          parent=spec.get("trace_ctx"),
                          attributes={"task_id": task_id,
                                      "component": "worker"}):
                    value = (profiler.runcall(fn, *args, **kwargs)
                             if profiler is not None
                             else fn(*args, **kwargs))
            else:
                value = (profiler.runcall(fn, *args, **kwargs)
                         if profiler is not None else fn(*args, **kwargs))
            exec_us = int((time.perf_counter() - _e0) * 1e6)
            if profiler is not None:
                self._dump_task_profile(profiler, task_id, name)
            if flight.enabled:
                flight.record("task", f"exec:{name}", dur_us=exec_us,
                              arg=task_id[:8],
                              t=time.monotonic() - exec_us / 1e6)
            if attr_on:
                now = time.perf_counter()
                split["exec"] = int((now - _tmark) * 1e6)
                _tmark = now
            args = kwargs = None
            results = self._package_returns(task_id, num_returns, name,
                                            value)
            if attr_on:
                split["result_pack"] = int(
                    (time.perf_counter() - _tmark) * 1e6)
            ok = True
        except BaseException as e:  # noqa: BLE001
            self._die_if_orphaned()
            results = self._package_error(task_id, num_returns, name, e)
        finally:
            # Drop frame refs to args/value so only genuinely retained
            # arg refs still hold pins, then upgrade those to real
            # borrows before the reply releases the submitter's pin.
            args = kwargs = value = None
            self._commit_arg_borrows(arg_refs)
            self._running_task_threads.pop(task_id, None)
            self._cancelled_pending.discard(task_id)
            self._record_task_event(
                task_id, name, "FINISHED" if ok else "FAILED",
                job_id=spec.get("job_id"))
            _reset_task_context(token)
        reply: Dict[str, Any] = {"results": results}
        if exec_us is not None:
            reply["exec_us"] = exec_us
        if attr_on:
            reply["attr_exec"] = split
        return reply

    def _package_returns(self, task_id: str, num_returns: int, name: str,
                         value: Any) -> List[dict]:
        def oid_for(i):
            return ObjectID.for_return(
                TaskID(bytes.fromhex(task_id)), i + 1).hex()

        if num_returns == 1:
            return [self._package_result(oid_for(0), value)]
        if num_returns == 0:
            return []
        if not isinstance(value, (tuple, list)) or len(value) != num_returns:
            err = ValueError(
                f"Task declared num_returns={num_returns} but returned "
                f"{type(value).__name__}")
            return self._package_error(task_id, num_returns, name, err)
        return [self._package_result(oid_for(i), v)
                for i, v in enumerate(value)]

    def _die_if_orphaned(self) -> None:
        """A worker whose raylet died is a zombie: its object store, lease
        and chip bookkeeping are gone. Reporting the resulting plumbing
        errors (ConnectionLost on arg fetch / result store) to the owner
        would surface them as USER task failures, which don't retry.
        Exit instead — the owner observes worker death as a SYSTEM
        failure and retries/reconstructs (reference: workers exit on
        raylet socket EOF, node_manager.cc disconnect handling)."""
        if self.mode == "worker" and not self._raylet.connected:
            logging.getLogger(__name__).warning(
                "raylet connection lost mid-task; exiting so the owner "
                "retries elsewhere")
            os._exit(1)

    def _package_error(self, task_id: str, num_returns: int, name: str,
                       exc: BaseException) -> List[dict]:
        wrapped = (exc if isinstance(exc, (RayTaskError, RayActorError,
                                           TaskCancelledError))
                   else RayTaskError.from_exception(name, exc))
        out = []
        for i in range(max(num_returns, 1)):
            oid = ObjectID.for_return(
                TaskID(bytes.fromhex(task_id)), i + 1).hex()
            out.append(self._package_result(oid, wrapped, is_error=True))
        return out

    def _decode_spec(self, conn: ServerConnection, spec: dict,
                     expect: str):
        """Task-spec decode boundary. Post-handshake connections (the
        peer's schema digest verified ours — conn.metadata['wire_fast'])
        take the no-validate fast path; anything short of a perfect
        envelope falls back inside from_wire_fast to the validated
        decode, whose typed WireDecodeError names the offending field
        instead of a KeyError inside the executor."""
        if conn.metadata.get("wire_fast"):
            return from_wire_fast(spec, expect)
        return from_wire(spec, expect=expect)

    async def handle_push_task(self, conn: ServerConnection, *,
                               spec: dict) -> dict:
        attr_on = attribution.enabled
        _t0 = time.perf_counter() if attr_on else 0.0
        if isinstance(spec, dict) and "_t" in spec:
            spec = self._decode_spec(conn, spec, "TaskSpec")
            if attr_on:
                attribution.record("wire.decode_task",
                                   time.perf_counter() - _t0)
        # Refuse work the moment our raylet is gone (don't wait to fail
        # on the result store): the pusher holds a stale lease on a dead
        # node; exiting here converts it to a clean worker-death retry
        # without a wasted duplicate execution.
        self._die_if_orphaned()
        if spec.get("streaming"):
            return await self._execute_streaming(spec, actor=False)
        loop = asyncio.get_running_loop()
        _t1 = time.perf_counter() if attr_on else 0.0
        reply = await loop.run_in_executor(
            self._exec_pool, self._execute_task, spec)
        if attr_on:
            # decode measured here; the arg-resolve/exec/result-pack
            # split rides out of _execute_task (attr_exec).
            attr = {"decode": int((_t1 - _t0) * 1e6)}
            attr.update(reply.pop("attr_exec", None) or {})
            reply["attr"] = attr
        return reply

    # -- worker-direct dispatch ring: worker side (round 10) -----------
    async def handle_attach_task_ring(self, conn: ServerConnection, *,
                                      sub_name: str, sub_fifo: str,
                                      comp_name: str, comp_fifo: str
                                      ) -> bool:
        """The driver that leased this worker created a ring pair (it
        owns the segments and FIFOs): attach the submit side as
        consumer, the reply side as producer, and wake on the submit
        doorbell. Deltas dequeued here execute through the SAME
        `_execute_task` an RPC push runs — task_events, typed errors,
        cancellation, exec_us, the attribution split, all identical —
        and the reply rides the twin ring (a full reply ring or an
        oversized reply falls back to a server push on this
        connection, so a reply is never dropped)."""
        from ray_tpu.core.ring import RingReader, RingWriter

        self._detach_task_ring(conn)
        reader = writer = None
        state = None
        try:
            reader = RingReader(sub_name, sub_fifo)
            writer = RingWriter(comp_name, comp_fifo)
            state = {
                "reader": reader,
                "writer": writer,
                "templates": {},
                "conn": conn,
                "live": True,
            }
            conn.metadata["task_ring"] = state
            self._task_rings.append(state)
            loop = asyncio.get_running_loop()
            loop.add_reader(state["reader"].doorbell_fd,
                            self._on_task_ring_doorbell, state)
            state["poller"] = asyncio.ensure_future(
                self._task_ring_backstop(state))
        except BaseException:
            # Partial attach must not leak our end's fds/mappings in a
            # long-lived worker (the driver latches False and unlinks
            # the files when this RPC errors).
            if state is not None:
                self._detach_task_ring(conn)
            else:
                for end in (reader, writer):
                    if end is not None:
                        try:
                            end.close()
                        except Exception:
                            pass
            raise
        return True

    async def handle_detach_task_ring(self, conn: ServerConnection
                                      ) -> bool:
        """Lease return: drop our end of the pair (the driver unlinks
        the files once we have answered)."""
        self._detach_task_ring(conn)
        return True

    async def handle_register_task_template(self, conn: ServerConnection,
                                            *, template_id: int,
                                            base: dict) -> bool:
        """Invariant wire dict of a spec template, registered once per
        (fn, options, env) shape per ring; deltas reference it by id so
        the steady-state ring entry carries only per-call fields."""
        state = conn.metadata.get("task_ring")
        if state is None:
            raise RpcError("no task ring attached on this connection")
        while len(state["templates"]) >= 1024:
            # Evict OLDEST-first (insertion order), never wholesale:
            # the driver's own map clears at 512 and re-registers under
            # fresh monotonic ids, so any id it still holds is among
            # the newest <=512 registrations — old-end eviction can
            # never invalidate a live id.
            state["templates"].pop(next(iter(state["templates"])))
        state["templates"][int(template_id)] = base
        return True

    def _on_task_ring_doorbell(self, state: dict) -> int:
        from ray_tpu.core.ring import busy_poll

        total = 0
        rounds = 0
        while True:
            try:
                drained = state["reader"].drain()
            except (OSError, ValueError):
                return total  # ring torn down under the callback
            for raw in drained:
                try:
                    self._submit_ring_task(state, raw)
                except Exception:
                    # One malformed entry must not drop the REST of
                    # the drained batch on the floor (their waiters
                    # would hang with the worker still connected).
                    logger.warning("malformed ring entry dropped",
                                   exc_info=True)
            total += len(drained)
            # Busy-poll handoff (round 16, ROADMAP 3c): mid-burst the
            # driver's next delta lands within the spin budget — take
            # it now instead of sleeping into an epoll wakeup. Gated
            # on traffic (this drain found entries) so an idle worker
            # core never spins.
            if (not drained or self._busy_poll_s <= 0.0
                    or rounds >= 2):
                break
            rounds += 1
            if not busy_poll(state["reader"], self._busy_poll_s):
                break
            if attribution.enabled:
                attribution.count("worker.busy_poll")
            if flight.enabled:
                flight.instant("ring", "busy_poll")
        if total:
            # Feed the backstop's pacing (see _drain_worker_ring).
            state["activity"] = state.get("activity", 0) + total
        return total

    async def _task_ring_backstop(self, state: dict) -> None:
        """Lost-wakeup backstop, adaptively paced (ring.AdaptivePoll):
        base period while tasks flow, decaying to the idle period on a
        quiet ring."""
        from ray_tpu.core.ring import AdaptivePoll

        poll = AdaptivePoll()
        while state.get("live") and not state["reader"].closed:
            await asyncio.sleep(poll.interval)
            try:
                self._on_task_ring_doorbell(state)
                # Doorbell-served drains between ticks count as
                # traffic too (same accounting as the driver side).
                poll.observe(state.pop("activity", 0))
            except Exception:
                return  # ring torn down under us

    def _submit_ring_task(self, state: dict, raw: bytes) -> None:
        """Decode one delta on the loop thread (dict merge + fast
        decode), then hand execution AND the reply to the single exec
        thread: the reply rides the twin ring straight from that
        thread (it is the reply ring's only producer, so SPSC holds).
        A steady-state ring task therefore costs this worker zero
        event-loop round trips — the run_in_executor reply hop of the
        RPC push path (one call_soon_threadsafe self-pipe write per
        task) never happens."""
        attr_on = attribution.enabled
        _t0 = time.perf_counter() if attr_on else 0.0
        task_id = None
        try:
            delta = msgpack.unpackb(raw, raw=False)
            task_id = delta.get("task_id")
            base = state["templates"].get(delta.pop("t", None))
            if base is None:
                raise RpcError("unknown spec template")
            merged = dict(base)
            merged.update(delta)
            # Ring deltas skip the per-connection handshake gate: the
            # template base arrived over a validated registration and
            # the delta fields are producer-controlled; any envelope
            # shortfall still falls back to the validated decode
            # inside from_wire_fast.
            spec = from_wire_fast(merged, "TaskSpec")
            if attr_on:
                attribution.count("ring.worker_deq")
            if flight.enabled:
                flight.instant("ring", "worker_deq")
        except Exception as e:  # noqa: BLE001
            # A typed ring-level failure (user exceptions ride inside
            # reply["results"]): the driver maps it onto the same
            # ConnectionLost/retry path a failed RPC push takes. The
            # reply still goes through the exec pool so the reply
            # ring keeps its single producer. An entry so corrupt its
            # task_id is unreadable cannot be error-replied — drop it
            # loudly (the caller's per-entry guard keeps the rest of
            # the batch flowing).
            if task_id is None:
                logger.warning("undecodable ring entry dropped: %s", e)
                return
            err = f"{type(e).__name__}: {e}"
            self._submit_to_exec_pool(
                self._task_ring_complete, state,
                {"task_id": task_id, "error": err})
            return
        decode_us = int((time.perf_counter() - _t0) * 1e6) if attr_on \
            else 0

        def run_and_reply():
            try:
                # Refuse work the moment our raylet is gone, exactly
                # like handle_push_task: exiting converts the stale
                # lease into a clean worker-death retry at the owner.
                self._die_if_orphaned()
                reply = self._execute_task(spec)
                if attr_on:
                    attr = {"decode": decode_us}
                    attr.update(reply.pop("attr_exec", None) or {})
                    reply["attr"] = attr
                else:
                    reply.pop("attr_exec", None)
                msg = {"task_id": task_id, "reply": reply}
            except BaseException as e:  # noqa: BLE001
                msg = {"task_id": task_id,
                       "error": f"{type(e).__name__}: {e}"}
            self._task_ring_complete(state, msg)

        self._submit_to_exec_pool(run_and_reply)

    def _submit_to_exec_pool(self, fn, *args) -> None:
        try:
            self._exec_pool.submit(fn, *args)
        except RuntimeError:
            pass  # pool shut down: the driver's failfast covers us

    def _task_ring_complete(self, state: dict, msg: dict) -> None:
        """Reply producer — runs on the exec thread (see
        _submit_ring_task)."""
        if not state.get("live"):
            return
        try:
            payload = msgpack.packb(msg, use_bin_type=True)
            pushed = state["writer"].push(payload)
        except (OSError, ValueError):
            return  # ring torn down mid-reply: driver failfast covers
        if not pushed:
            # Reply ring full or the reply exceeds a slot: deliver over
            # the attach connection instead (server push) — a reply
            # must never be dropped. The push coroutine needs the loop;
            # strong-ref'd so the task can't be GC'd mid-push.
            try:
                self._loop.call_soon(
                    lambda: self._spawn_ring_task(
                        state["conn"].push("ring_completion", msg)))
            except Exception:
                pass

    def _detach_task_ring(self, conn: ServerConnection) -> None:
        state = conn.metadata.pop("task_ring", None)
        if state is not None:
            self._detach_task_ring_state(state)

    def _detach_task_ring_state(self, state: dict) -> None:
        if not state.get("live"):
            return
        state["live"] = False
        try:
            self._task_rings.remove(state)
        except ValueError:
            pass
        poller = state.get("poller")
        if poller is not None:
            poller.cancel()
        try:
            self._loop.loop.remove_reader(state["reader"].doorbell_fd)
        except Exception:
            pass
        state["reader"].close()
        state["writer"].close()

    async def on_client_disconnect(self, conn: ServerConnection) -> None:
        """The driver that attached a task ring vanished: its segments
        may be unlinked any moment — drop our end so the consumer never
        touches a dead mapping. (In-flight executions still complete;
        their replies fall back to the dead conn's push and vanish with
        it, which is correct: the owner is gone.)"""
        self._detach_task_ring(conn)

    async def _execute_streaming(self, spec: dict, actor: bool) -> dict:

        loop = asyncio.get_running_loop()
        owner_addr = spec["owner"]
        task_id = spec["task_id"]

        def run() -> Optional[bytes]:
            arg_refs: List[tuple] = []
            args = kwargs = it = None
            try:
                self._ensure_job_env(spec.get("job_id"))
                if actor:
                    method = getattr(self._actor_instance, spec["method"])
                    args, kwargs, arg_refs = self._resolve_task_args(
                        spec["args"])
                    it = method(*args, **kwargs)
                else:
                    fn = self._fn.fetch(spec["fn_key"])
                    args, kwargs, arg_refs = self._resolve_task_args(
                        spec["args"])
                    it = fn(*args, **kwargs)
                args = kwargs = None
                idx = 0
                for item in it:
                    idx += 1
                    oid = ObjectID.for_return(
                        TaskID(bytes.fromhex(task_id)), idx).hex()
                    res = self._package_result(oid, item)
                    fut = asyncio.run_coroutine_threadsafe(
                        self._push_generator_item(owner_addr, task_id, res),
                        loop)
                    fut.result()
                return None
            except BaseException as e:  # noqa: BLE001
                self._die_if_orphaned()
                wrapped = (e if isinstance(e, RayTaskError)
                           else RayTaskError.from_exception(
                               spec.get("name", "task"), e))
                return serialization.serialize_error(wrapped).to_bytes()
            finally:
                args = kwargs = it = None
                self._commit_arg_borrows(arg_refs)

        pool = (self._actor_executor if actor and self._actor_executor
                else self._exec_pool)
        error_blob = await loop.run_in_executor(pool, run)
        return {"results": [], "done": True, "error_blob": error_blob}

    async def _push_generator_item(self, owner_addr: str, task_id: str,
                                   res: dict) -> None:
        client = await self._worker_client(owner_addr)
        await client.call("generator_item", task_id=task_id,
                          oid=res["oid"], inline=res.get("inline"),
                          node=res.get("node"), timeout=30.0)

    # -- actor execution -----------------------------------------------
    def _apply_visible_chips(self, chips) -> None:
        """Isolate this worker process to its granted TPU chips (reference:
        accelerators/tpu.py:214). Must run before user code imports jax."""
        if chips:
            from ray_tpu.core.jax_platform import enable_host_platform
            from ray_tpu.parallel.tpu import visible_chip_env

            os.environ.update(visible_chip_env(chips))
            # Undo the worker-default CPU pin: this worker owns chips now.
            enable_host_platform()

    async def handle_actor_init(self, conn: ServerConnection, *,
                                actor_id: str, cls_key: str, args: bytes,
                                max_concurrency: Optional[int],
                                owner: str,
                                job_id: Optional[str] = None,
                                visible_chips=None,
                                concurrency_groups: Optional[dict] = None,
                                runtime_env: Optional[dict] = None
                                ) -> dict:
        import inspect as _inspect

        loop = asyncio.get_running_loop()

        def init() -> Optional[bytes]:
            try:
                self._apply_visible_chips(visible_chips)
                self._ensure_job_env(job_id)
                if runtime_env:
                    from ray_tpu.core.runtime_env import apply_runtime_env

                    apply_runtime_env(self, runtime_env)
                cls = self._fn.fetch(cls_key)
                rargs, rkwargs, arg_refs = self._resolve_task_args(args)
                self._actor_instance = cls(*rargs, **rkwargs)
                rargs = rkwargs = None
                # Constructor args stored on the instance are the classic
                # retained-arg case: commit before the creation reply.
                self._commit_arg_borrows(arg_refs)
                is_async = any(
                    _inspect.iscoroutinefunction(m)
                    or _inspect.isasyncgenfunction(m)
                    for _, m in _inspect.getmembers(cls, callable))
                conc = max_concurrency or (100 if is_async else 1)
                self._actor_executor = (
                    concurrent.futures.ThreadPoolExecutor(
                        max_workers=conc, thread_name_prefix="actor-exec"))
                # Concurrency groups: each group gets its own bounded
                # executor; ungrouped methods share the default one
                # (reference: concurrency_group_manager.h).
                self._actor_group_executors = {
                    name: concurrent.futures.ThreadPoolExecutor(
                        max_workers=limit,
                        thread_name_prefix=f"actor-{name}")
                    for name, limit in (concurrency_groups or {}).items()
                }
                if is_async:
                    import asyncio as aio
                    self._actor_loop = aio.new_event_loop()
                    threading.Thread(target=self._actor_loop.run_forever,
                                     daemon=True).start()
                self._actor_id_hex = actor_id
                return None
            except BaseException as e:  # noqa: BLE001
                wrapped = (e if isinstance(e, RayTaskError)
                           else RayTaskError.from_exception(
                               f"{cls_key}.__init__", e))
                return serialization.serialize_error(wrapped).to_bytes()

        error_blob = await loop.run_in_executor(self._exec_pool, init)
        return {"error_blob": error_blob}

    def _execute_actor_method(self, spec: dict) -> dict:
        from ray_tpu.runtime_context import (_reset_task_context,
                                             _set_task_context)
        import inspect as _inspect

        task_id = spec["task_id"]
        num_returns = spec["num_returns"]
        name = spec.get("name", "method")
        token = _set_task_context(
            task_id=TaskID(bytes.fromhex(task_id)),
            actor_id=ActorID(bytes.fromhex(spec["actor_id"])))
        self._record_task_event(task_id, name, "RUNNING",
                                job_id=spec.get("job_id"),
                                actor_id=spec.get("actor_id"))
        self._running_task_threads[task_id] = threading.get_ident()
        ok = False
        arg_refs: List[tuple] = []
        args = kwargs = value = None
        # Same worker-side split as _execute_task (see there).
        attr_on = attribution.enabled
        split = {"arg_resolve": 0, "exec": 0, "result_pack": 0}
        _tmark = time.perf_counter() if attr_on else 0.0
        try:
            if task_id in self._cancelled_pending:
                raise TaskCancelledError(task_id)
            self._ensure_job_env(spec.get("job_id"))
            args, kwargs, arg_refs = self._resolve_task_args(spec["args"])
            if attr_on:
                now = time.perf_counter()
                split["arg_resolve"] = int((now - _tmark) * 1e6)
                _tmark = now
            traced = tracing_enabled() or spec.get("trace_ctx")
            ctx = (span(f"actor.run {name}",
                        parent=spec.get("trace_ctx"),
                        attributes={"task_id": task_id,
                                    "actor_id": spec.get("actor_id"),
                                    "component": "worker"})
                   if traced else contextlib.nullcontext())
            with ctx:
                if spec["method"] == "__ray_call__":
                    # fn(actor_instance, *args): the system method for
                    # running arbitrary code against a live actor
                    # (reference: __ray_call__ in python/ray/actor.py).
                    fn, args = args[0], args[1:]
                    value = fn(self._actor_instance, *args, **kwargs)
                else:
                    method = getattr(self._actor_instance, spec["method"])
                    value = method(*args, **kwargs)
            if _inspect.iscoroutine(value):
                cfut = asyncio.run_coroutine_threadsafe(
                    value, self._actor_loop)
                self._running_task_cfuts[task_id] = cfut
                try:
                    value = cfut.result()
                except concurrent.futures.CancelledError:
                    raise TaskCancelledError(task_id)
                finally:
                    self._running_task_cfuts.pop(task_id, None)
            if attr_on:
                now = time.perf_counter()
                split["exec"] = int((now - _tmark) * 1e6)
                _tmark = now
            args = kwargs = None
            results = self._package_returns(task_id, num_returns, name,
                                            value)
            if attr_on:
                split["result_pack"] = int(
                    (time.perf_counter() - _tmark) * 1e6)
            ok = True
        except BaseException as e:  # noqa: BLE001
            self._die_if_orphaned()
            results = self._package_error(task_id, num_returns, name, e)
        finally:
            # See _execute_task: only genuinely retained arg refs (here
            # usually actor state) must survive as registered borrows.
            args = kwargs = value = None
            self._commit_arg_borrows(arg_refs)
            self._running_task_threads.pop(task_id, None)
            self._cancelled_pending.discard(task_id)
            self._record_task_event(
                task_id, name, "FINISHED" if ok else "FAILED",
                job_id=spec.get("job_id"),
                actor_id=spec.get("actor_id"))
            _reset_task_context(token)
        if attr_on:
            return {"results": results, "attr_exec": split}
        return {"results": results}

    async def handle_push_actor_task(self, conn: ServerConnection, *,
                                     spec: dict) -> dict:
        attr_on = attribution.enabled
        _t0 = time.perf_counter() if attr_on else 0.0
        if isinstance(spec, dict) and "_t" in spec:
            spec = self._decode_spec(conn, spec, "ActorTaskSpec")
        # Decode measured BEFORE the per-caller ordering gate: a task
        # waiting its turn behind a slow predecessor is actor
        # contention, and must not be booked as wire-decode cost.
        decode_us = int((time.perf_counter() - _t0) * 1e6) if attr_on else 0
        if self._actor_instance is None:
            raise RpcError("no actor instance on this worker")
        if spec.get("streaming"):
            await self._await_actor_turn(spec)
            self._advance_actor_turn(spec)
            return await self._execute_streaming(spec, actor=True)
        loop = asyncio.get_running_loop()
        await self._await_actor_turn(spec)
        executor = (getattr(self, "_actor_group_executors", {}) or {}).get(
            spec.get("concurrency_group"))
        fut = loop.run_in_executor(
            executor or self._actor_executor or self._exec_pool,
            self._execute_actor_method, spec)
        self._advance_actor_turn(spec)
        reply = await fut
        if attr_on:
            attr = {"decode": decode_us}
            attr.update(reply.pop("attr_exec", None) or {})
            reply["attr"] = attr
        return reply

    # Explicit per-caller sequencing (reference:
    # sequential_actor_submit_queue.h): the caller stamps each actor task
    # with a monotonically increasing seq; dispatch here is gated so a
    # task never STARTS before its predecessors from the same caller,
    # regardless of any future awaits added earlier in this handler.
    def _actor_seq_entry(self, caller: str) -> dict:
        entry = self._actor_seq.get(caller)
        if entry is None:
            if len(self._actor_seq) >= 256:
                # Bound per-caller state: drop idle entries (no waiters —
                # long-gone callers); adopt-first-seen re-seeds any that
                # come back.
                for key, e in list(self._actor_seq.items()):
                    if not e["cond"]._waiters and not e["waiting"]:
                        del self._actor_seq[key]
            entry = {"next": None, "cond": asyncio.Condition(),
                     "skipped": set(), "waiting": 0}
            self._actor_seq[caller] = entry
        return entry

    async def handle_actor_seq_skip(self, conn: ServerConnection, *,
                                    owner: str,
                                    seq: Optional[int] = None) -> bool:
        """A seq consumed caller-side will never be pushed (cancelled
        pre-push): release successors immediately."""
        if seq is None:
            return True
        entry = self._actor_seq_entry(owner)
        async with entry["cond"]:
            entry["skipped"].add(seq)
            entry["cond"].notify_all()
        return True

    async def _await_actor_turn(self, spec: dict) -> None:
        seq = spec.get("seq")
        if seq is None:
            return
        entry = self._actor_seq_entry(spec.get("owner", ""))
        # Fast path: everything here runs on the one worker event loop,
        # so plain dict reads/writes are race-free between awaits — the
        # Condition is only needed when this task actually has to wait
        # (out-of-order arrival, which TCP ordering makes rare).
        while entry["next"] is not None and entry["next"] < seq:
            if entry["next"] in entry["skipped"]:
                # Explicitly-skipped hole (cancelled pre-push).
                entry["skipped"].discard(entry["next"])
                entry["next"] += 1
                continue
            # Announce intent-to-wait synchronously (single-threaded
            # loop: no await between here and _advance's check), so the
            # advancer can't miss us while cond.wait() is still
            # registering its waiter.
            entry["waiting"] += 1
            try:
                async with entry["cond"]:
                    # Full re-check under the lock, INCLUDING skip holes:
                    # a skip notification can land while we were queued
                    # on the lock, and missing it here would stall 60s.
                    while (entry["next"] is not None
                           and entry["next"] < seq
                           and entry["next"] in entry["skipped"]):
                        entry["skipped"].discard(entry["next"])
                        entry["next"] += 1
                    if entry["next"] is not None and entry["next"] >= seq:
                        break
                    try:
                        await asyncio.wait_for(entry["cond"].wait(),
                                               timeout=60.0)
                    except asyncio.TimeoutError:
                        # A predecessor seq was consumed caller-side but
                        # its push never arrived (failed before send):
                        # liveness over strictness — adopt this seq.
                        entry["next"] = seq
            finally:
                entry["waiting"] -= 1
        if entry["next"] is None:
            # First task seen from this caller (fresh worker, or the
            # caller reconnected after a restart): adopt its seq.
            entry["next"] = seq

    def _advance_actor_turn(self, spec: dict) -> None:
        seq = spec.get("seq")
        if seq is None:
            return
        entry = self._actor_seq_entry(spec.get("owner", ""))
        if entry["next"] is not None and entry["next"] == seq:
            entry["next"] = seq + 1
        if not entry["waiting"]:
            return  # nobody waiting (or registering): skip the notify

        async def notify():
            async with entry["cond"]:
                entry["cond"].notify_all()

        asyncio.ensure_future(notify())

    async def handle_cgraph_push(self, conn: ServerConnection, *,
                                 channel: str, data: bytes, seq: int = 0,
                                 capacity: int = 8, kind: str = "obj",
                                 ordered: bool = True) -> bool:
        """Compiled-graph channel deposit (reference: the shared-memory
        channel write in ray/experimental/channel/). The reader process
        hosts the slot buffer; this handler admits one pushed frame in
        writer order. The deposit blocks while the slot is full — the
        delayed reply IS the writer's backpressure — so it runs on an
        executor thread, never on the RPC loop."""
        from ray_tpu.cgraph.channel import deposit_nowait, deposit_remote

        if deposit_nowait(kind, channel, capacity, data, seq,
                          ordered=ordered):
            return True   # free slot, in-order frame: no thread hop
        # Dedicated pool: a full channel parks its deposit thread for up
        # to the push timeout — on the shared default executor that would
        # head-of-line-block unrelated work (generator pushes, to_thread).
        pool = getattr(self, "_cgraph_deposit_pool", None)
        if pool is None:
            pool = self._cgraph_deposit_pool = (
                concurrent.futures.ThreadPoolExecutor(
                    max_workers=32, thread_name_prefix="cgraph-deposit"))
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            pool,
            lambda: deposit_remote(kind, channel, capacity, data, seq,
                                   ordered=ordered))

    async def handle_collective_ranks(self, conn: ServerConnection) -> dict:
        """{group: rank} of this process's p2p-capable collective groups
        — the device-channel writer's route discovery (cgraph/channel.py
        DeviceChannel._ensure_route)."""
        from ray_tpu.util.collective import local_ranks

        return local_ranks()

    async def handle_exit_worker(self, conn: ServerConnection) -> bool:

        async def _die():
            await asyncio.sleep(0.05)
            os._exit(0)

        asyncio.ensure_future(_die())
        return True

    # ==================================================================
    # cluster introspection
    # ==================================================================
    def nodes(self) -> List[dict]:
        raw = self._loop.run(self._gcs.get_nodes())
        return [{
            "NodeID": n["node_id"],
            "Alive": n["alive"],
            "Resources": n.get("resources_total", {}),
            "Available": n.get("resources_available", {}),
            "NodeManagerAddress": n.get("address"),
            "IsHeadNode": n.get("is_head", False),
            "Labels": n.get("labels", {}),
        } for n in raw]

    def object_store_stats(self) -> List[dict]:
        """Every alive raylet's plasma inventory (state API
        list_objects / `ray_tpu memory`)."""

        async def collect():
            out = []
            for n in await self._gcs.get_nodes():
                if not n.get("alive"):
                    continue
                try:
                    client = await self._raylet_client(n["address"])
                    stats = await client.call("object_store_stats",
                                              timeout=10.0)
                    for obj in stats["objects"]:
                        out.append(dict(obj, node_id=stats["node_id"],
                                        address=n["address"]))
                except Exception:
                    continue
            return out

        return self._loop.run(collect(), timeout=60)

    def cluster_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self._loop.run(self._gcs.get_nodes()):
            if not n.get("alive"):
                continue
            for k, v in n.get("resources_total", {}).items():
                out[k] = out.get(k, 0.0) + v
        return out

    def available_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self._loop.run(self._gcs.get_nodes()):
            if not n.get("alive"):
                continue
            for k, v in n.get("resources_available", {}).items():
                out[k] = out.get(k, 0.0) + v
        return out

    # -- internal kv ----------------------------------------------------
    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True):
        k = key.decode() if isinstance(key, bytes) else key
        return self._loop.run(self._gcs.kv_put(k, value, overwrite))

    def kv_get(self, key: bytes) -> Optional[bytes]:
        k = key.decode() if isinstance(key, bytes) else key
        return self._loop.run(self._gcs.kv_get(k))

    def kv_del(self, key: bytes) -> None:
        k = key.decode() if isinstance(key, bytes) else key
        self._loop.run(self._gcs.kv_del(k))

    def kv_keys(self, prefix: bytes) -> List[bytes]:
        p = prefix.decode() if isinstance(prefix, bytes) else prefix
        return [k.encode() for k in self._loop.run(self._gcs.kv_keys(p))]
