"""Value serialization for the object store.

Reference equivalent: `python/ray/_private/serialization.py` (cloudpickle +
Arrow, zero-copy numpy). Design here: cloudpickle protocol-5 with out-of-band
pickle buffers so large numpy / jax host arrays are written into the object
store without an extra copy, and reads return views over shared memory.

Wire format of a stored object:
    [u32 metadata_len][metadata bytes (msgpack)] [pickled payload] [buffers...]
metadata = {"nbuf": n, "buf_offsets": [...], "buf_lens": [...], "err": bool}

Array-native format (the zero-copy data plane): a bare contiguous
ndarray skips pickle entirely — the metadata carries dtype/shape
(`"nd": {"d": dtype_str, "s": shape}`), the payload is empty, and the
single buffer IS the array. `deserialize` of such an object returns an
np view over the store segment without ever invoking a pickler, so a
`get` of a 10 MB tensor costs a header unpack and nothing else.
"""

from __future__ import annotations

import pickle
import struct
from dataclasses import dataclass
from typing import Any, Callable, List, Optional

import cloudpickle
import msgpack

_HEADER = struct.Struct("<I")


@dataclass
class SerializedObject:
    """A serialized value: an inline payload plus zero-copy buffer chunks."""

    payload: bytes
    buffers: List[memoryview]
    is_error: bool = False
    nd: Optional[dict] = None   # array-native: {"d": dtype_str, "s": shape}

    def total_size(self) -> int:
        return (
            _HEADER.size
            + len(self._metadata())
            + len(self.payload)
            + sum(b.nbytes if isinstance(b, memoryview) else len(b)
                  for b in self.buffers)
        )

    def _metadata(self) -> bytes:
        lens = [b.nbytes if isinstance(b, memoryview) else len(b)
                for b in self.buffers]
        meta = {"nbuf": len(self.buffers), "buf_lens": lens,
                "payload_len": len(self.payload), "err": self.is_error}
        if self.nd is not None:
            meta["nd"] = self.nd
        return msgpack.packb(meta)

    def to_bytes(self) -> bytes:
        out = bytearray()
        self.write_into(out)
        return bytes(out)

    def write_into(self, buf) -> None:
        """Append the wire format into `buf` (bytearray or shm memoryview wrapper)."""
        for chunk in self.chunks():
            buf += chunk

    def chunks(self) -> List:
        """The wire format as a chunk list (for scatter-gather writes)."""
        meta = self._metadata()
        return [_HEADER.pack(len(meta)) + meta, self.payload, *self.buffers]


def is_plain_ndarray(value: Any) -> bool:
    """True for arrays the array-native format can carry: exactly
    np.ndarray (subclasses may carry state pickle must capture),
    contiguous, and a fixed-size non-object dtype."""
    import numpy as np

    return (type(value) is np.ndarray and value.dtype.kind not in "OV"
            and value.flags.c_contiguous)


def serialize_array(value) -> SerializedObject:
    """Array-native serialization: a dtype/shape header plus the raw
    buffer — no pickler on either side, and the buffer is handed to the
    store writer as a view (the single shm write is the only copy)."""
    view = memoryview(value)
    return SerializedObject(
        payload=b"",
        buffers=[view.cast("B")] if value.size else [],
        nd={"d": value.dtype.str, "s": list(value.shape)})


def serialize(value: Any, *,
              ref_serializer: Optional[Callable[[Any], None]] = None
              ) -> SerializedObject:
    """Serialize `value`; large contiguous buffers are captured out-of-band.

    Bare contiguous ndarrays take the array-native path (no pickle at
    all — see serialize_array); everything else goes through cloudpickle
    with out-of-band buffers.

    `ref_serializer` is called on every ObjectRef contained in the value so the
    owner can run the borrowing protocol (reference:
    `reference_count.h` borrowed-refs / `serialization.py` object-ref hooks).
    """
    if is_plain_ndarray(value):
        return serialize_array(value)
    buffers: List[pickle.PickleBuffer] = []

    def buffer_callback(pb: pickle.PickleBuffer) -> bool:
        raw = pb.raw()
        if raw.nbytes >= 4096 and raw.contiguous:
            buffers.append(pb)
            return False  # keep out-of-band
        return True  # in-band

    from ray_tpu.core.object_ref import ObjectRef, _serialization_context

    with _serialization_context(ref_serializer):
        payload = cloudpickle.dumps(
            value, protocol=5, buffer_callback=buffer_callback)
    views = [pb.raw() for pb in buffers]
    return SerializedObject(payload=payload, buffers=views)


def serialize_error(exc: BaseException) -> SerializedObject:
    try:
        so = serialize(exc)
    except Exception:
        from ray_tpu.exceptions import RaySystemError
        so = serialize(RaySystemError(f"Unserializable exception: {exc!r}"))
    so.is_error = True
    return so


# ---------------------------------------------------------------------------
# Fast path (compiled-graph channels; reference: the serialization
# shortcut Ray's Compiled Graphs take for channel payloads). Common leaf
# types skip cloudpickle entirely: a 1-byte tag + raw payload. ndarrays
# are written header + buffer and read back as a zero-copy view over the
# frame. Everything else falls back to the full path above (tag b"P").
# ---------------------------------------------------------------------------
def serialize_fast_into(value: Any, buf: bytearray) -> None:
    """Append the fast wire form of `value` into `buf` (reused across
    calls by channel writers — no per-call allocation)."""
    import numpy as np

    t = type(value)
    if value is None:
        buf += b"N"
    elif t is bytes:
        buf += b"B"
        buf += value
    elif t is str:
        buf += b"S"
        buf += value.encode()
    elif t in (bool, int, float):
        try:
            buf += b"M"
            buf += msgpack.packb(value)
        except (OverflowError, ValueError):   # int out of msgpack range
            del buf[-1:]
            buf += b"P"
            serialize(value).write_into(buf)
    elif (t is np.ndarray and value.dtype.kind not in "OV"
          and value.flags.c_contiguous):
        for chunk in pack_array_chunks(value):
            buf += chunk
    else:
        buf += b"P"
        serialize(value).write_into(buf)


def pack_array_chunks(value) -> list:
    """THE byte-level "A" wire form of a plain contiguous ndarray, as a
    chunk list: `[b"A" + u32 head_len + msgpack{d,s}, raw buffer view]`.
    Single source of truth — `serialize_fast_into` embeds these chunks
    inline and `ArrayChannel._encode_chunks` ships them out of band as
    a blob frame; `deserialize_fast`'s "A" branch decodes both. The
    buffer chunk is a VIEW of `value` (zero-copy): callers that cannot
    guarantee the array stays unmutated until the transport consumes it
    must copy first."""
    head = msgpack.packb({"d": value.dtype.str, "s": list(value.shape)})
    chunks = [b"A" + _HEADER.pack(len(head)) + head]
    if value.size:   # cast("B") rejects zeros in shape/strides
        chunks.append(memoryview(value).cast("B"))
    return chunks


def serialize_fast(value: Any) -> bytes:
    buf = bytearray()
    serialize_fast_into(value, buf)
    return bytes(buf)


def deserialize_fast(blob) -> Any:
    view = memoryview(blob)
    tag = view[:1].tobytes()
    body = view[1:]
    if tag == b"N":
        return None
    if tag == b"B":
        return bytes(body)
    if tag == b"S":
        return bytes(body).decode()
    if tag == b"M":
        return msgpack.unpackb(bytes(body))
    if tag == b"A":
        import numpy as np

        (head_len,) = _HEADER.unpack_from(body, 0)
        head = msgpack.unpackb(bytes(body[_HEADER.size:
                                          _HEADER.size + head_len]))
        arr = np.frombuffer(body[_HEADER.size + head_len:],
                            dtype=np.dtype(head["d"]))
        return arr.reshape(head["s"])
    if tag == b"P":
        return deserialize(body)
    raise ValueError(f"bad fast-serialization tag {tag!r}")


def deserialize(data, *,
                ref_deserializer: Optional[Callable[[Any], None]] = None,
                raise_errors: bool = True) -> Any:
    """Deserialize from a bytes-like (possibly a zero-copy shm memoryview)."""
    view = memoryview(data)
    (meta_len,) = _HEADER.unpack_from(view, 0)
    off = _HEADER.size
    meta = msgpack.unpackb(bytes(view[off:off + meta_len]))
    off += meta_len
    nd = meta.get("nd")
    if nd is not None:
        # Array-native object: reconstruct a zero-copy view straight
        # over the (possibly shm-backed) buffer — no pickler runs.
        import numpy as np

        from ray_tpu.core import attribution

        if attribution.enabled:
            attribution.count("get.nd_view")
        blen = meta["buf_lens"][0] if meta["buf_lens"] else 0
        arr = np.frombuffer(view[off:off + blen], dtype=np.dtype(nd["d"]))
        arr = arr.reshape(nd["s"])
        # The view aliases the LIVE store segment (mapped O_RDWR), which
        # other readers — and the writer's kept mapping — share. A
        # writable array here would let `get(ref)[0] = x` silently
        # corrupt the stored object for everyone (plasma maps client
        # reads read-only for the same reason).
        if arr.flags.writeable:
            arr.flags.writeable = False
        return arr
    payload = view[off:off + meta["payload_len"]]
    off += meta["payload_len"]
    buffers = []
    for blen in meta["buf_lens"]:
        buffers.append(view[off:off + blen])
        off += blen

    from ray_tpu.core.object_ref import _serialization_context

    with _serialization_context(ref_deserializer):
        value = pickle.loads(payload, buffers=buffers)
    if meta.get("err") and raise_errors:
        from ray_tpu.exceptions import RayTaskError
        if isinstance(value, RayTaskError):
            raise value.as_instanceof_cause()
        raise value
    return value
