"""Simulated-raylet scale harness: 100+-node control-plane scenarios in
one pytest process, in seconds.

The scheduler and GCS had only ever run on a handful of OS processes;
"survives at 100 nodes" was an untested claim. This module scales the
loopback-fake approach of `core/rpc_testing.py` into a whole cluster:

- ONE real `GcsServer` (storage, WAL, health loop, every handler) runs
  with `serve_rpc=False` — no TCP listener, but the full control plane;
- N `SimRaylet`s inherit the real raylet's `NodeLedger` (resource
  accounting, placement-group 2PC handlers, spillback policy) and speak
  to the GCS through the real `GcsClient` accessors over in-process
  loopback `ServerConnection` dispatch — production wire typing,
  production handlers, zero sockets;
- a `SimDriver` creates placement groups through the SAME
  `schedule_placement_group` coroutine the real runtime uses, and
  submits simulated task leases with the real retry discipline
  (ConnectionLost -> jittered backoff -> other node);
- every message crosses `FaultPlan.apply` (core/faults.py): seeded
  drops, delays, duplicates, one-way partitions and crash-on-nth are a
  replayable property of the seed, so "the cluster leaked a bundle
  under seed 17" is a failing test, not an anecdote.

Used by tests/test_unit_simcluster.py (`-m unit`) and
`python -m ray_tpu.perf --simcluster`.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import random
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import lineage as lineage_mod
from ray_tpu.core.cluster_runtime import schedule_placement_group
from ray_tpu.core.config import ray_config
from ray_tpu.core.faults import FaultPlan
from ray_tpu.core.gcs.client import GcsClient, backoff_delay
from ray_tpu.core.gcs.server import GcsServer
from ray_tpu.core.lineage import LineageTable
from ray_tpu.core.raylet import NodeLedger, _Bundle  # noqa: F401 (re-export)
from ray_tpu.core.rpc import ConnectionLost
from ray_tpu.core.rpc_testing import LoopbackClient
from ray_tpu.exceptions import (GetTimeoutError, ObjectLostError,
                                OwnerDiedError)

logger = logging.getLogger(__name__)

# Control-plane timings compressed ~10x so a restart+grace+reconcile
# cycle fits in a unit-test second; every value is the REAL config knob,
# just smaller — the code paths cannot tell the difference.
SIM_CONFIG = {
    "health_check_period_ms": 100,
    "health_check_failure_threshold": 3,
    "raylet_heartbeat_period_ms": 50,
    "gcs_rpc_timeout_s": 8.0,
    "gcs_reconnect_backoff_base_ms": 10.0,
    "gcs_reconnect_backoff_max_ms": 250.0,
    "pg_reconcile_interval_s": 0.25,
    "pg_stuck_commit_s": 2.0,
    "object_timeout_ms": 20,
    "cluster_view_refresh_ms": 100,
    # HA GCS (round 18): compressed lease/election timings so a leader
    # kill -9 + election + client failover cycle fits in a unit test.
    "gcs_ha_lease_ms": 300.0,
    "gcs_ha_renew_ms": 100.0,
    "gcs_ha_replicate_timeout_ms": 500.0,
}


class _SimChannel:
    """The client half of one simulated connection (src -> dst), with
    `_ReconnectingRpc` semantics: a ConnectionLost call retries with the
    SAME capped-exponential-jitter backoff the real GCS client uses,
    within the same `gcs_rpc_timeout_s` window. Satisfies the interface
    `GcsClient` needs from its rpc.

    HA (round 18): when the cluster boots multiple GCS replicas, a dst
    of "gcs" re-resolves per attempt exactly like the real
    `_ReconnectingRpc._resolve_target` — follow the NOT_LEADER hint if a
    follower redirected us, otherwise rotate the replica set — so sim
    raylets/drivers ride the same jittered-backoff path onto the new
    leader that production clients do."""

    def __init__(self, cluster: "SimCluster", src: str, dst: str,
                 retry_window: bool = True):
        self._cluster = cluster
        self.src = src
        self.dst = dst
        self._retry_window = retry_window
        self._gcs_target: Optional[str] = None  # leader hint (replica id)
        self.connected = True

    async def connect(self, timeout: float = 10.0) -> None:
        return None

    async def close(self) -> None:
        self.connected = False

    def on_push(self, channel: str, handler) -> None:
        pass  # sim components don't subscribe

    def mark_subscribed(self, channel: str) -> None:
        pass

    def _resolve(self, attempt: int) -> str:
        if self.dst != "gcs":
            return self.dst
        ids = self._cluster.gcs_ids
        if len(ids) == 1:
            return ids[0]
        if self._gcs_target is not None:
            return self._gcs_target
        return ids[attempt % len(ids)]

    def _note_redirect(self, err: Exception) -> bool:
        """True if `err` was a follower's NOT_LEADER redirect; records
        the leader hint (a replica id in the sim) for the next attempt.
        QuorumLostError is retryable too: rotate off the stuck replica."""
        from ray_tpu.core.gcs.replication import parse_not_leader

        if "QuorumLostError" in str(err):
            self._gcs_target = None
            return True
        hint = parse_not_leader(str(err))
        if hint is None:
            return False
        self._gcs_target = hint.get("leader")  # None = election running
        return True

    async def call(self, method: str, timeout: Optional[float] = 60.0,
                   **kwargs: Any) -> Any:
        from ray_tpu.core.rpc import RpcError

        try:
            return await self._cluster.dispatch(
                self.src, self._resolve(0), method, kwargs)
        except ConnectionLost:
            self._gcs_target = None
            if not self._retry_window:
                raise
        except RpcError as e:
            if not self._retry_window or not self._note_redirect(e):
                raise
        # Reconnect-retry (mirrors _ReconnectingRpc.call + _reconnect):
        # keep trying with jittered backoff until the window closes,
        # re-resolving the target replica each attempt.
        loop = asyncio.get_running_loop()
        deadline = loop.time() + ray_config().gcs_rpc_timeout_s
        attempt = 0
        while True:
            await asyncio.sleep(backoff_delay(attempt))
            attempt += 1
            try:
                return await self._cluster.dispatch(
                    self.src, self._resolve(attempt), method, kwargs)
            except ConnectionLost:
                self._gcs_target = None
                if loop.time() >= deadline:
                    raise
            except RpcError as e:
                if not self._note_redirect(e) or loop.time() >= deadline:
                    raise


class _RayletCaller:
    """What `schedule_placement_group` sees as a raylet client: `.call`
    routed through the fault plan to the sim raylet that owns the
    address. No retry window — the 2PC's own failure handling must see
    raw ConnectionLost, exactly as over TCP."""

    def __init__(self, cluster: "SimCluster", src: str, address: str):
        self._cluster = cluster
        self._src = src
        self._address = address

    async def call(self, method: str, timeout: Optional[float] = 60.0,
                   **kwargs: Any) -> Any:
        dst = self._cluster.node_by_address(self._address)
        if dst is None:
            raise ConnectionLost(f"no sim node at {self._address}")
        return await self._cluster.dispatch(self._src, dst, method, kwargs)


class SimRaylet(NodeLedger):
    """A raylet reduced to its control-plane brain: the real NodeLedger
    (2PC bundle handlers, resource accounting, spillback policy) plus
    the real heartbeat/re-register/reconcile contract — no worker
    processes, no object store, no sockets."""

    def __init__(self, cluster: "SimCluster", node_id: str,
                 resources: Dict[str, float]):
        self.cluster = cluster
        self.node_id = node_id
        self.address = f"sim:{node_id}"
        self.resources_total = dict(resources)
        self.resources_available = dict(resources)
        self._bundles: Dict[str, _Bundle] = {}
        self._chips_free: List[int] = list(
            range(int(resources.get("TPU", 0))))
        self._cluster_view: Dict[str, Dict[str, Any]] = {}
        # Simulated object store: oid -> value. One dict stands in for
        # the plasma store; the PROTOCOL around it (owner location
        # directory, holder-death pruning, reconstruct-or-fail) mirrors
        # raylet.handle_pull_object step for step.
        self._objects: Dict[str, Any] = {}
        self.alive = True
        self.registered = False
        self.lease_grants = 0
        self._next_lease = 0
        self._leases: Dict[str, Tuple[Dict[str, float], Optional[str]]] = {}
        # At-least-once protection: a duplicated/retried lease request
        # must not acquire twice (mirrors the real raylet's
        # _recent_grants reclaim machinery, simplified to a reply cache).
        self._granted_by_request: Dict[str, Dict[str, Any]] = {}
        self._gcs = GcsClient(self.address,
                              rpc=_SimChannel(cluster, node_id, "gcs"))
        self._hb_task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        await self._register_with_gcs()
        self._hb_task = asyncio.ensure_future(self._heartbeat_loop())

    async def _register_with_gcs(self) -> None:
        await self._gcs.register_node(
            node_id=self.node_id, address=self.address,
            object_store_address=self.address,
            resources=self.resources_total, labels={}, is_head=False)
        self.registered = True

    async def _heartbeat_loop(self) -> None:
        """The real raylet's heartbeat contract (raylet.py
        _heartbeat_loop): report resources, re-register on a False
        reply, refresh the cluster view, reap/reconcile bundles. GCS
        outages back off with the shared jittered delay."""
        period = ray_config().raylet_heartbeat_period_ms / 1000.0
        attempt = 0
        last_view = 0.0
        while self.alive:
            try:
                ok = await self._gcs.heartbeat(
                    self.node_id, self.resources_available,
                    load={"pending": 0})
                if ok is False:
                    await self._register_with_gcs()
                # View refresh throttled separately from liveness —
                # the same contract as the real raylet (PROFILE round
                # 11: per-beat get_nodes was the 1000-node GCS wall).
                now = time.monotonic()
                if (now - last_view
                        >= ray_config().cluster_view_refresh_ms
                        / 1000.0):
                    self._cluster_view = {
                        n["node_id"]: n
                        for n in await self._gcs.get_nodes()}
                    last_view = now
                attempt = 0
            except Exception:
                await asyncio.sleep(backoff_delay(attempt))
                attempt += 1
            self._reap_stale_prepares()
            try:
                await self._maybe_reconcile_bundles()
            except Exception:
                logger.debug("sim reconcile failed", exc_info=True)
            await asyncio.sleep(period)

    def crash(self) -> None:
        """kill -9 equivalent: the ledger dies with the process; every
        in-flight call to this node sees ConnectionLost."""
        self.alive = False
        self.registered = False
        if self._hb_task is not None:
            self._hb_task.cancel()

    async def stop(self) -> None:
        self.alive = False
        if self._hb_task is not None:
            self._hb_task.cancel()
            try:
                await self._hb_task
            except (asyncio.CancelledError, Exception):
                pass

    # -- simulated task leases ------------------------------------------
    async def handle_request_sim_lease(
            self, conn, *, resources: Dict[str, float],
            request_id: Optional[str] = None,
            spillback_count: int = 0,
            bundle: Optional[List[Any]] = None) -> Dict[str, Any]:
        """Chip-less worker lease against the ledger — grant, spillback
        (via the REAL `_maybe_spillback` hybrid policy), or reject.
        Idempotent per request_id: at-least-once delivery (retries,
        duplicate injection) must never double-acquire."""
        if request_id is not None:
            cached = self._granted_by_request.get(request_id)
            if cached is not None:
                return cached
        demand = {k: float(v) for k, v in resources.items() if v}
        reply: Dict[str, Any]
        if bundle is not None:
            key = f"{bundle[0]}:{bundle[1]}"
            b = self._bundles.get(key)
            if b is None or b.removed:
                return {"error": "bundle_missing"}
            if not self._fits(b.available, demand):
                return {"error": "infeasible"}
            for k, v in demand.items():
                b.available[k] = b.available.get(k, 0.0) - v
            bundle_key: Optional[str] = key
        else:
            remote = self._maybe_spillback(demand, spillback_count)
            if remote is not None:
                return {"spillback": remote}
            if not self._fits(self.resources_available, demand):
                # The sim keeps no pending queue: the driver's retry
                # loop is the queue (bounded, jittered).
                return {"error": "infeasible"}
            self._acquire(demand)
            bundle_key = None
        self._next_lease += 1
        lease_id = f"{self.node_id}#{self._next_lease}"
        self._leases[lease_id] = (demand, bundle_key)
        self.lease_grants += 1
        reply = {"lease_id": lease_id, "node_id": self.node_id}
        if request_id is not None:
            self._granted_by_request[request_id] = reply
            if len(self._granted_by_request) > 4096:
                for k in itertools.islice(
                        iter(list(self._granted_by_request)), 2048):
                    self._granted_by_request.pop(k, None)
        return reply

    async def handle_return_sim_lease(self, conn, *,
                                      lease_id: str) -> bool:
        rec = self._leases.pop(lease_id, None)
        if rec is None:
            return True  # duplicate return: already released
        demand, bundle_key = rec
        if bundle_key is not None:
            b = self._bundles.get(bundle_key)
            if b is not None and not b.removed:
                for k, v in demand.items():
                    b.available[k] = min(b.available.get(k, 0.0) + v,
                                         b.total.get(k, v))
            else:
                self._release(demand)
        else:
            self._release(demand)
        return True

    async def handle_node_stats(self, conn) -> Dict[str, Any]:
        return {
            "node_id": self.node_id,
            "resources_total": self.resources_total,
            "resources_available": self.resources_available,
            "bundles": {k: {"total": b.total, "available": b.available,
                            "committed": b.committed}
                        for k, b in self._bundles.items() if not b.removed},
            "leases": len(self._leases),
            "objects": len(self._objects),
        }

    # -- simulated object plane (round 15: data-plane recovery) ---------
    async def handle_store_sim_object(self, conn, *, oid: str,
                                      value: Any) -> bool:
        self._objects[oid] = value
        return True

    async def handle_read_sim_object(self, conn, *,
                                     oid: str) -> Dict[str, Any]:
        """Remote holder read (the sim's read_object): found=False means
        'no longer a holder' and the puller prunes this location."""
        if oid in self._objects:
            return {"found": True, "value": self._objects[oid]}
        return {"found": False}

    async def handle_pull_sim_object(self, conn, *, oid: str, owner: str,
                                     pull_timeout: float = 15.0
                                     ) -> Dict[str, Any]:
        """The borrower-side pull loop — raylet.handle_pull_object's
        protocol over the sim message plane: local store -> owner's
        location directory -> holder fetch; a dead holder is pruned at
        the owner; empty-directory-and-not-pending asks the owner to
        RECONSTRUCT (lineage re-execution) and keeps polling while it
        recovers; only an authoritative 'no recovery' fails the get."""
        cfg = ray_config()
        poll = cfg.object_timeout_ms / 1000.0
        deadline = time.monotonic() + pull_timeout
        owner_unreachable_since: Optional[float] = None
        while time.monotonic() < deadline:
            if oid in self._objects:
                return {"value": self._objects[oid]}
            try:
                loc = await self.cluster.dispatch(
                    self.node_id, owner, "get_sim_object_locations",
                    {"oid": oid})
            except ConnectionLost as e:
                now = time.monotonic()
                if owner_unreachable_since is None:
                    owner_unreachable_since = now
                if (now - owner_unreachable_since
                        >= cfg.owner_unreachable_grace_s):
                    return {"error": f"owner unreachable: {e}",
                            "owner_dead": True}
                await asyncio.sleep(poll)
                continue
            owner_unreachable_since = None
            if loc is None:
                return {"error": "owner does not know this object"}
            if loc.get("pending"):
                await asyncio.sleep(poll)
                continue
            for node in list(loc.get("nodes", ())):
                if node == self.node_id:
                    # Stale self-location (evicted): prune it so the
                    # owner can recover instead of us spinning.
                    await self._prune_at_owner(owner, oid, node)
                    continue
                try:
                    r = await self.cluster.dispatch(
                        self.node_id, node, "read_sim_object",
                        {"oid": oid})
                except ConnectionLost:
                    if not self.cluster.is_alive(node):
                        # Cluster says the holder is DEAD: prune so the
                        # owner can start lineage reconstruction.
                        await self._prune_at_owner(owner, oid, node)
                    continue
                if r.get("found"):
                    self._objects[oid] = r["value"]
                    return {"value": r["value"]}
                await self._prune_at_owner(owner, oid, node)
            if not loc.get("nodes"):
                try:
                    r = await self.cluster.dispatch(
                        self.node_id, owner, "reconstruct_sim_object",
                        {"oid": oid})
                except ConnectionLost:
                    await asyncio.sleep(poll)
                    continue
                if r and r.get("recovering"):
                    await asyncio.sleep(poll)
                    continue
                return {"error": "no reachable copy"}
            await asyncio.sleep(poll)
        return {"error": "timeout"}

    async def _prune_at_owner(self, owner: str, oid: str,
                              node: str) -> None:
        try:
            await self.cluster.dispatch(
                self.node_id, owner, "prune_sim_object_location",
                {"oid": oid, "node": node})
        except ConnectionLost:
            pass


class SimDriver:
    """The owner side: creates placement groups through the runtime's
    `schedule_placement_group` and submits simulated tasks with the
    production retry discipline. Tracks completion so the acceptance
    invariant ("zero lost tasks") is a list comparison."""

    def __init__(self, cluster: "SimCluster", name: str = "driver"):
        self.cluster = cluster
        self.name = name
        self.alive = True
        self._gcs = GcsClient(f"sim:{name}",
                              rpc=_SimChannel(cluster, name, "gcs"))
        self._rng = random.Random(cluster.seed ^ 0x5eed)
        self._next_task = 0
        self._next_pg = 0
        self.completed: List[str] = []
        self.lost: List[str] = []
        # -- owned simulated objects (round 15) -------------------------
        # oid -> {"pending": bool, "nodes": [node_id]} — the owner's
        # location directory, the exact record handle_get_object_
        # locations serves in production.
        self._objects: Dict[str, Dict[str, Any]] = {}
        # THE shared policy object: production's ClusterRuntime and this
        # sim driver run the same retention/budget/inflight state
        # machine (core/lineage.py).
        self.lineage = LineageTable()
        # producer tag -> executions (re-executions visible to tests)
        self.exec_counts: Dict[str, int] = {}
        # The driver's LOCAL raylet: every pull goes through it (its
        # store caches pulled copies, exactly like a real worker's node
        # store). Re-homed deterministically when it dies.
        self.node: Optional[str] = None

    async def raylet_client_for(self, address: str) -> _RayletCaller:
        return _RayletCaller(self.cluster, self.name, address)

    # -- placement groups ----------------------------------------------
    async def create_placement_group(self, bundles: List[Dict[str, float]],
                                     strategy: str = "PACK",
                                     attempts: int = 8
                                     ) -> Tuple[str, str]:
        self._next_pg += 1
        pg_id = f"simpg{self.cluster.seed:x}n{self._next_pg:05d}"
        info = {"bundles": [dict(b) for b in bundles],
                "strategy": strategy, "name": "", "state": "PENDING",
                "owner": self.name, "target_node_ids": None}
        await self._gcs.register_placement_group(pg_id, info)
        state = await schedule_placement_group(
            self._gcs, self.raylet_client_for, pg_id, info,
            attempts=attempts)
        return pg_id, state

    async def remove_placement_group(self, pg_id: str) -> None:
        """REMOVED is recorded FIRST, then bundles are returned: any
        return that fails (drop, dead node) is mopped up by raylet-side
        reconciliation against the terminal state — the reverse order
        can strand committed bundles behind a forever-CREATED record."""
        info = await self._gcs.get_placement_group(pg_id)
        if info is None or info.get("state") == "REMOVED":
            return
        await self._gcs.update_placement_group(pg_id, {"state": "REMOVED"})
        for idx, loc in enumerate(info.get("bundle_locations") or []):
            try:
                client = await self.raylet_client_for(loc["address"])
                await client.call("return_bundle", pg_id=pg_id,
                                  bundle_index=idx)
            except ConnectionLost:
                pass  # reconciler returns it against the REMOVED state

    # -- simulated objects: put/get with lineage recovery (round 15) ----
    async def create_object(self, tag: str, deps: Optional[List[str]]
                            = None, max_retries: int = 3) -> str:
        """Run one simulated producer task: lease a node, 'execute' (a
        deterministic function of tag + resolved dep values, counted in
        exec_counts), store the result on the leased node, and retain
        the producing spec in the SHARED LineageTable so a lost copy
        re-executes — recursively re-resolving deps that were lost
        with their own nodes. Returns the oid."""
        deps = list(deps or ())
        self._next_task += 1
        oid = f"simobj-{tag}"
        self._objects[oid] = {"pending": True, "nodes": []}
        self.lineage.retain([oid], {"name": tag, "tag": tag, "deps": deps},
                            [], max_retries)
        await self._exec_producer(oid, tag, deps)
        return oid

    async def _exec_producer(self, oid: str, tag: str,
                             deps: List[str]) -> None:
        """One (re-)execution of a producer: dep resolution (which may
        itself reconstruct), lease, compute, store, publish location.
        Mirrors _submit_async's retry discipline for transport loss."""
        entry = self._objects[oid]
        entry["pending"] = True
        entry["nodes"] = []
        dep_vals = [await self.get_object(d) for d in deps]
        self.exec_counts[tag] = self.exec_counts.get(tag, 0) + 1
        value = (f"{tag}({','.join(str(v) for v in dep_vals)})"
                 if deps else f"{tag}()")
        self._next_task += 1
        rid = f"{oid}-x{self._next_task}"
        for attempt in range(60):
            node = self._pick_node()
            if node is None:
                await asyncio.sleep(backoff_delay(attempt, self._rng))
                continue
            try:
                reply = await self._lease_chain(node, {"CPU": 1.0}, rid)
                if reply is None or "lease_id" not in reply:
                    await asyncio.sleep(backoff_delay(attempt, self._rng))
                    continue
                target = reply["node_id"]
                await self.cluster.dispatch(self.name, target,
                                            "store_sim_object",
                                            {"oid": oid, "value": value})
                await self._return_lease(target, reply["lease_id"])
            except ConnectionLost:
                await asyncio.sleep(backoff_delay(attempt, self._rng))
                continue
            entry["nodes"] = [target]
            entry["pending"] = False
            return
        entry["pending"] = False  # directory: lost, nothing in flight
        logger.warning("sim producer %s could not store its result", tag)

    async def get_object(self, oid: str, owner: Optional[str] = None,
                         timeout: float = 15.0) -> Any:
        """A get() through a live raylet's pull loop (borrowers pass the
        owner driver's name). Block-and-retries through reconstruction;
        degrades to the production-typed errors when recovery is
        impossible."""
        owner = owner or self.name
        deadline = time.monotonic() + timeout
        attempt = 0
        while True:
            node = self._home_node()
            if node is None:
                if time.monotonic() >= deadline:
                    raise GetTimeoutError(f"no live node to pull {oid}")
                await asyncio.sleep(backoff_delay(attempt, self._rng))
                attempt += 1
                continue
            try:
                r = await self.cluster.dispatch(
                    self.name, node, "pull_sim_object",
                    {"oid": oid, "owner": owner,
                     "pull_timeout": max(0.1, deadline - time.monotonic())})
            except ConnectionLost:
                # The pulling raylet itself died mid-get: re-pull via a
                # survivor (the production client's retry path).
                if time.monotonic() >= deadline:
                    raise GetTimeoutError(f"timed out pulling {oid}")
                await asyncio.sleep(backoff_delay(attempt, self._rng))
                attempt += 1
                continue
            if "value" in r:
                return r["value"]
            err = r.get("error", "")
            if r.get("owner_dead"):
                raise OwnerDiedError(oid)
            if "timeout" in err:
                raise GetTimeoutError(f"timed out pulling {oid}: {err}")
            raise ObjectLostError(oid)

    # owner-side directory handlers (the sim's CoreWorkerService) ------
    async def handle_get_sim_object_locations(
            self, conn, *, oid: str) -> Optional[Dict[str, Any]]:
        e = self._objects.get(oid)
        if e is None:
            return None
        return {"pending": bool(e["pending"]), "nodes": list(e["nodes"])}

    async def handle_prune_sim_object_location(self, conn, *, oid: str,
                                               node: str) -> bool:
        e = self._objects.get(oid)
        if e is None:
            return True
        if node in e["nodes"]:
            e["nodes"] = [n for n in e["nodes"] if n != node]
            if not e["nodes"] and not e["pending"]:
                self._trigger_sim_reconstruction(oid)
        return True

    async def handle_reconstruct_sim_object(self, conn, *,
                                            oid: str) -> Dict[str, Any]:
        e = self._objects.get(oid)
        if e is None:
            return {"recovering": False, "known": False}
        if e["pending"]:
            return {"recovering": True}
        if e["nodes"]:
            return {"recovering": True}
        return {"recovering": self._trigger_sim_reconstruction(oid)}

    def _trigger_sim_reconstruction(self, oid: str) -> bool:
        """The owner's recovery decision — the SAME LineageTable verdict
        machine production's _trigger_reconstruction consults, driving
        the sim's re-execution path."""
        verdict, rec = self.lineage.begin_reexec(oid)
        if verdict == lineage_mod.INFLIGHT:
            return True
        if verdict != lineage_mod.STARTED:
            if verdict == lineage_mod.EXHAUSTED:
                logger.warning("sim object %s lost; budget exhausted", oid)
            return False
        spec = rec["spec"]

        async def _re():
            try:
                await self._exec_producer(oid, spec["tag"], spec["deps"])
            finally:
                self.lineage.end_reexec(rec)

        asyncio.ensure_future(_re())
        return True

    # -- simulated tasks -----------------------------------------------
    async def submit_task(self, resources: Optional[Dict[str, float]]
                          = None, hold_s: float = 0.0,
                          max_attempts: int = 60) -> bool:
        """One simulated task: lease -> hold -> return, surviving
        ConnectionLost/spillback/infeasible with the jittered-backoff
        retry discipline of the real submit path. Returns True when the
        task completed (and records it); False only after the retry
        budget is exhausted (records into .lost)."""
        demand = dict(resources or {"CPU": 1.0})
        self._next_task += 1
        task_id = f"{self.name}-t{self._next_task:06d}"
        for attempt in range(max_attempts):
            node = self._pick_node()
            if node is None:
                await asyncio.sleep(backoff_delay(attempt, self._rng))
                continue
            try:
                reply = await self._lease_chain(node, demand, task_id)
            except ConnectionLost:
                await asyncio.sleep(backoff_delay(attempt, self._rng))
                continue
            if reply is None or "lease_id" not in reply:
                await asyncio.sleep(backoff_delay(attempt, self._rng))
                continue
            if hold_s:
                await asyncio.sleep(hold_s)
            await self._return_lease(reply["node_id"], reply["lease_id"])
            self.completed.append(task_id)
            return True
        self.lost.append(task_id)
        return False

    async def _lease_chain(self, node: str, demand: Dict[str, float],
                           task_id: str) -> Optional[Dict[str, Any]]:
        """Follow spillback redirects like the real lease client (bounded
        chain, same request_id so at-least-once stays single-grant
        per target)."""
        spill = 0
        while True:
            reply = await self.cluster.dispatch(
                self.name, node, "request_sim_lease",
                {"resources": demand, "request_id": f"{task_id}@{node}",
                 "spillback_count": spill})
            target = reply.get("spillback") if reply else None
            if target is None:
                return reply
            nxt = self.cluster.node_by_address(target)
            if nxt is None:
                return None
            node, spill = nxt, spill + 1

    async def _return_lease(self, node: str, lease_id: str) -> None:
        for attempt in range(20):
            if not self.cluster.is_alive(node):
                return  # lease died with the node; nothing to release
            try:
                await self.cluster.dispatch(self.name, node,
                                            "return_sim_lease",
                                            {"lease_id": lease_id})
                return
            except ConnectionLost:
                await asyncio.sleep(backoff_delay(attempt, self._rng))
        logger.warning("lease %s on %s could not be returned", lease_id,
                       node)

    def _pick_node(self) -> Optional[str]:
        live = [n for n, r in self.cluster.raylets.items() if r.alive]
        if not live:
            return None
        return self._rng.choice(live)

    def _home_node(self) -> Optional[str]:
        """This driver's local raylet (pulls route through it; its
        store caches the copies). Deterministic re-home on death."""
        if self.node is not None and self.cluster.is_alive(self.node):
            return self.node
        live = sorted(n for n, r in self.cluster.raylets.items()
                      if r.alive)
        self.node = live[0] if live else None
        return self.node


class SimCluster:
    """N simulated raylets + one real GcsServer + a fault plan, in one
    event loop."""

    def __init__(self, num_nodes: int = 100, *,
                 resources: Optional[Dict[str, float]] = None,
                 seed: int = 0,
                 storage_path: Optional[str] = None,
                 plan: Optional[FaultPlan] = None,
                 config: Optional[Dict[str, Any]] = None,
                 num_gcs: int = 1):
        self.num_nodes = num_nodes
        self.seed = seed
        self.node_resources = dict(resources or {"CPU": 4.0})
        self.storage_path = storage_path
        self.plan = plan if plan is not None else FaultPlan(seed)
        self._config_overrides = {**SIM_CONFIG, **(config or {})}
        self._saved_config: Optional[Dict[str, Any]] = None
        # HA (round 18): num_gcs > 1 boots a replica set ("gcs0"...)
        # running the Raft-lite replicated WAL of gcs/replication.py.
        # num_gcs == 1 keeps the historic single instance addressed as
        # "gcs" — same dispatch keys, same fault-plan edges, so every
        # pre-HA seed replays byte-identically.
        if num_gcs > 1 and not storage_path:
            raise ValueError("multi-replica GCS needs storage_path "
                             "(the replicated WAL lives there)")
        self.gcs_ids: List[str] = (
            ["gcs"] if num_gcs == 1
            else [f"gcs{i}" for i in range(num_gcs)])
        self.gcs_replicas: Dict[str, Optional[GcsServer]] = {
            rid: None for rid in self.gcs_ids}
        self.gcs_epochs: Dict[str, int] = {rid: 0 for rid in self.gcs_ids}
        self.raylets: Dict[str, SimRaylet] = {}
        self._by_address: Dict[str, str] = {}
        # (src, dst, epoch) -> LoopbackClient bound to the live target
        self._conns: Dict[Tuple[str, str, int], LoopbackClient] = {}
        self.driver = SimDriver(self)
        # Dispatch-addressable drivers (the OWNER side of the object
        # plane: raylets pull locations / prune / reconstruct against
        # them). Borrower drivers register here too via add_driver.
        self.drivers: Dict[str, SimDriver] = {self.driver.name: self.driver}

    @property
    def gcs(self) -> Optional[GcsServer]:
        """The serving GCS instance: the sole replica (single mode) or
        the current leader (HA mode; None while an election runs).
        Invariant checks and tests read tables through this, exactly as
        before HA existed."""
        if len(self.gcs_ids) == 1:
            return self.gcs_replicas[self.gcs_ids[0]]
        for g in self.gcs_replicas.values():
            if (g is not None and g.replication is not None
                    and g.replication.is_leader()):
                return g
        return None

    @property
    def gcs_epoch(self) -> int:
        return self.gcs_epochs[self.gcs_ids[0]]

    def leader_id(self) -> Optional[str]:
        for rid, g in self.gcs_replicas.items():
            if (g is not None and g.replication is not None
                    and g.replication.is_leader()):
                return rid
        return None

    def add_driver(self, name: str) -> SimDriver:
        """A second owner/borrower process (e.g. the borrower of the
        data-plane acceptance scenario)."""
        drv = SimDriver(self, name=name)
        self.drivers[name] = drv
        return drv

    def _storage_for(self, rid: str) -> Optional[str]:
        if self.storage_path is None or len(self.gcs_ids) == 1:
            return self.storage_path
        return f"{self.storage_path}.{rid}"

    def _new_gcs(self, rid: Optional[str] = None) -> GcsServer:
        """A GcsServer whose outbound raylet clients (PG reschedule 2PC)
        ride the fault-injected sim dispatch, set BEFORE start() so
        crash-resumed reschedules of recovered RESCHEDULING groups go
        through the plan too. In HA mode each replica additionally gets
        a Replication whose peer RPCs (vote, replicate_wal, snapshot)
        cross the SAME fault plan — elections under partitions are
        seeded scenarios, not luck."""
        rid = rid or self.gcs_ids[0]
        gcs = GcsServer(storage_path=self._storage_for(rid))
        gcs.raylet_client_factory = (
            lambda addr: _RayletCaller(self, rid, addr))
        if len(self.gcs_ids) > 1:
            from ray_tpu.core.gcs.replication import Replication

            def peer_call(peer, method, _rid=rid, **kw):
                return self.dispatch(_rid, peer, method, kw)

            gcs.replication = Replication(
                gcs, rid, [p for p in self.gcs_ids if p != rid],
                peer_call=peer_call,
                address_of=lambda pid: pid,
                rng=random.Random(f"{self.seed}:{rid}"))
        return gcs

    # -- lifecycle ------------------------------------------------------
    async def start(self) -> None:
        cfg = ray_config()
        self._saved_config = dict(cfg._values)
        cfg.apply_system_config(self._config_overrides)
        self._wire_crashes()
        for rid in self.gcs_ids:
            self.gcs_replicas[rid] = self._new_gcs(rid)
        await asyncio.gather(
            *(g.start(serve_rpc=False)
              for g in self.gcs_replicas.values()))
        if len(self.gcs_ids) > 1:
            # Let the first election settle before the raylet fleet
            # registers: a 100-node register storm against a leaderless
            # replica set is all redirect noise.
            await self.wait_until(lambda: self.gcs is not None,
                                  timeout=15.0)
        for i in range(self.num_nodes):
            node_id = f"simnode{i:04d}"
            raylet = SimRaylet(self, node_id, self.node_resources)
            self.raylets[node_id] = raylet
            self._by_address[raylet.address] = node_id
        await asyncio.gather(*(r.start() for r in self.raylets.values()))

    async def stop(self) -> None:
        # Mass-cancel first, then reap: awaiting each heartbeat task's
        # cancellation one by one costs a full scheduler pass through
        # every still-runnable loop per node — 135 s at N=1000.
        for r in self.raylets.values():
            r.alive = False
            if r._hb_task is not None:
                r._hb_task.cancel()
        await asyncio.gather(*(r.stop() for r in self.raylets.values()),
                             return_exceptions=True)
        for rid, g in self.gcs_replicas.items():
            if g is not None:
                await g.stop()
                self.gcs_replicas[rid] = None
        if self._saved_config is not None:
            cfg = ray_config()
            cfg._values.clear()
            cfg._values.update(self._saved_config)
            self._saved_config = None

    def _wire_crashes(self) -> None:
        """Give crash rules without a callback the cluster's kill switch
        (dst 'gcs' -> kill_gcs; a node id -> crash_raylet)."""
        for rule in self.plan.rules:
            if rule.kind == "crash" and rule.on_crash is None:
                rule.on_crash = self.crash_target

    def crash_target(self, dst: str) -> None:
        if dst == "gcs" or dst in self.gcs_replicas:
            self.kill_gcs(dst if dst in self.gcs_replicas else None)
        elif dst in self.raylets:
            self.crash_raylet(dst)

    # -- fault-injected message plane -----------------------------------
    def node_by_address(self, address: str) -> Optional[str]:
        return self._by_address.get(address)

    def is_alive(self, dst: str) -> bool:
        if dst in self.gcs_replicas:
            return self.gcs_replicas[dst] is not None
        r = self.raylets.get(dst)
        if r is not None:
            return r.alive
        d = self.drivers.get(dst)
        return d is not None and d.alive

    def _target(self, dst: str) -> Optional[Any]:
        if dst in self.gcs_replicas:
            return self.gcs_replicas[dst]
        r = self.raylets.get(dst)
        if r is not None:
            return r if r.alive else None
        d = self.drivers.get(dst)
        return d if (d is not None and d.alive) else None

    async def _client(self, src: str, dst: str,
                      target: Any) -> LoopbackClient:
        key = (src, dst, self.gcs_epochs.get(dst, 0))
        client = self._conns.get(key)
        if client is None or client.handlers is not target:
            client = LoopbackClient(target)
            # Handshake through the real __schema__ dispatch once per
            # (src, dst, epoch) — connect-time traffic is not
            # fault-injected, matching TCP (faults sit on calls).
            await client.connect()
            self._conns[key] = client
        return client

    async def dispatch(self, src: str, dst: str, method: str,
                       kwargs: Dict[str, Any]) -> Any:
        """One message src -> dst through the fault plan, then the real
        ServerConnection dispatch of the target. A target that dies
        while the handler runs loses the REPLY too (kill -9 semantics):
        the caller sees ConnectionLost even though the zombie handler
        finished against the dead instance's discarded state."""
        duplicate = await self.plan.apply(src, dst, method)
        target = self._target(dst)
        if target is None:
            raise ConnectionLost(f"sim target {dst} is down")
        epoch = self.gcs_epochs.get(dst)
        client = await self._client(src, dst, target)
        if duplicate:
            async def _dup():
                try:
                    await client.call(method, **kwargs)
                except Exception:
                    pass  # the duplicate's outcome is invisible

            asyncio.ensure_future(_dup())
        result = await client.call(method, **kwargs)
        if epoch is not None:
            if self.gcs_epochs[dst] != epoch:
                raise ConnectionLost(f"{dst} died before replying")
        elif not self.is_alive(dst):
            raise ConnectionLost(f"sim target {dst} died before replying")
        return result

    # -- chaos controls -------------------------------------------------
    def kill_gcs(self, replica_id: Optional[str] = None) -> None:
        """kill -9: no final flush, loops die mid-flight; only
        WAL-acked state survives to the next epoch. In-flight handler
        coroutines of the killed instance cannot be preempted in-process
        — so their replies are discarded by the epoch check in
        dispatch(), and storage is severed HERE so a zombie flush can't
        append to the WAL the next epoch replays. In HA mode the
        default victim is the current leader."""
        rid = replica_id or (self.gcs_ids[0] if len(self.gcs_ids) == 1
                             else self.leader_id())
        if rid is None:
            return
        gcs = self.gcs_replicas.get(rid)
        if gcs is None:
            return
        if gcs.replication is not None:
            # The ticker dies with the process: a zombie leader must not
            # keep renewing the lease it no longer holds.
            gcs.replication.stop()
        if gcs._health_task is not None:
            gcs._health_task.cancel()
        if gcs._snapshot_task is not None:
            gcs._snapshot_task.cancel()
        for task in gcs._reschedule_tasks.values():
            # Reschedule passes die with the process; the restarted
            # instance resumes them from the written-through
            # RESCHEDULING records.
            task.cancel()
        gcs._reschedule_tasks.clear()
        gcs._storage_path = None
        self.gcs_replicas[rid] = None
        self.gcs_epochs[rid] += 1

    def kill_leader(self) -> Optional[str]:
        """kill -9 the replica currently holding the lease. Returns its
        id (restart it later with restart_gcs(rid)) or None if no
        leader is up."""
        rid = self.leader_id() if len(self.gcs_ids) > 1 \
            else self.gcs_ids[0]
        if rid is None or self.gcs_replicas.get(rid) is None:
            return None
        self.kill_gcs(rid)
        return rid

    async def restart_gcs(self, replica_id: Optional[str] = None) -> None:
        assert self.storage_path, "restart needs persistent storage"
        rid = replica_id or self.gcs_ids[0]
        self.gcs_replicas[rid] = self._new_gcs(rid)
        await self.gcs_replicas[rid].start(serve_rpc=False)
        self.gcs_epochs[rid] += 1

    def crash_raylet(self, node_id: str) -> None:
        raylet = self.raylets.get(node_id)
        if raylet is not None:
            raylet.crash()

    def evict_sim_object(self, oid: str) -> int:
        """Drop every live raylet's copy of a sim object (the LRU/
        delete eviction stand-in): the next pull must recover through
        the owner's directory — prune, then lineage re-execution."""
        n = 0
        for r in self.alive_raylets():
            if r._objects.pop(oid, None) is not None:
                n += 1
        return n

    # -- invariants -----------------------------------------------------
    def alive_raylets(self) -> List[SimRaylet]:
        return [r for r in self.raylets.values() if r.alive]

    def leaked_reservations(self) -> List[Tuple[str, str, Any]]:
        """Bundles held by live raylets that the control plane does not
        stand behind: every entry is a capacity leak."""
        assert self.gcs is not None
        out = []
        for r in self.alive_raylets():
            for key, b in r._bundles.items():
                if b.removed:
                    continue
                pg_id = key.split(":", 1)[0]
                state = (self.gcs.placement_groups.get(pg_id)
                         or {}).get("state")
                if state != "CREATED":
                    out.append((r.node_id, key, state))
        return out

    def resource_violations(self) -> List[Tuple[str, Dict, Dict]]:
        """Live raylets with no leases and no bundles must be back at
        full capacity — anything else leaked through a retry path."""
        out = []
        for r in self.alive_raylets():
            if r._leases or any(not b.removed
                                for b in r._bundles.values()):
                continue
            if any(abs(r.resources_available.get(k, 0.0) - v) > 1e-6
                   for k, v in r.resources_total.items()):
                out.append((r.node_id, dict(r.resources_available),
                            dict(r.resources_total)))
        return out

    def registered_count(self) -> int:
        assert self.gcs is not None
        return sum(1 for n in self.gcs.nodes.values() if n.get("alive"))

    async def wait_until(self, predicate, timeout: float = 10.0,
                         interval: float = 0.05) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if predicate():
                return True
            await asyncio.sleep(interval)
        return predicate()
