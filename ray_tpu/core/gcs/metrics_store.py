"""GCS-side metrics retention store + SLO burn-rate tracker (round 17).

Receives the per-node coalesced batches piggybacked on raylet
heartbeats (see `core/metrics_ts.py` for the wire format) and keeps,
per series:

  * **metadata** — name, type, labels, help, histogram boundaries.
    Registered once per series and persisted through the GCS WAL
    (`metric_series` table), so identity survives a kill -9.
  * **cumulative state** — exact running totals folded at ingest
    (counters sum their increments, histograms their bucket
    increments), so the Prometheus exposition at `GET /metrics` is a
    true monotone counter view regardless of ring eviction.
  * **a retention ring** — the most recent N delta points, feeding the
    windowed query engine (`rate()`, quantile-over-time on pushed
    histogram buckets, label aggregation).  Ring data is deliberately
    in-memory only: after a restart the recovered metadata makes
    re-pushed series land on their old identity (no duplicates) while
    history restarts empty — the cheap half of durability that
    actually matters for alerting.

The SLO layer evaluates declarative objectives against the store with
the multi-window burn-rate recipe (error budget consumed per unit time,
checked over a long and a short window so a page needs both sustained
and current burn).  State transitions surface as `slo.burn` flight
events, landing on the merged `/api/timeline` next to the stalls that
caused them.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.core.metrics_ts import series_key


class _Series:
    __slots__ = ("meta", "ring", "counter_total", "gauge_last",
                 "hist_buckets", "hist_sum", "hist_count")

    def __init__(self, meta: Dict[str, Any], points: int) -> None:
        self.meta = meta
        self.ring: deque = deque(maxlen=max(2, points))
        self.counter_total = 0.0
        self.gauge_last = 0.0
        self.hist_buckets: List[float] = []
        self.hist_sum = 0.0
        self.hist_count = 0


class MetricsStore:
    """Retention rings + query engine over pushed delta batches."""

    def __init__(self, max_series: int = 2000, points: int = 512,
                 on_register: Optional[Callable[[str, Dict], None]] = None,
                 ) -> None:
        self.max_series = max_series
        self.points = points
        self.on_register = on_register
        self.series: Dict[str, _Series] = {}
        self.dropped_series = 0
        self.points_ingested = 0
        self.batches_ingested = 0

    # -- ingest ----------------------------------------------------------

    def adopt_metadata(self, metadata: Dict[str, Dict]) -> None:
        """Recreate (empty-ring) series for WAL-recovered metadata, so a
        re-pushed series after restart reuses its identity."""
        for key, meta in metadata.items():
            if key not in self.series:
                self.series[key] = _Series(dict(meta), self.points)

    def ingest(self, batch: List[Dict[str, Any]],
               extra_labels: Optional[Dict[str, str]] = None) -> None:
        """Fold one node's pushed batch (a list of delta entries)."""
        for entry in batch:
            t = float(entry.get("t") or time.time())
            for item in entry.get("series", ()):
                name, mtype, labels, payload = item[0], item[1], \
                    dict(item[2]), item[3]
                help_text = item[4] if len(item) > 4 else None
                if extra_labels:
                    for k, v in extra_labels.items():
                        labels.setdefault(k, v)
                key = series_key(name, labels)
                s = self.series.get(key)
                if s is None:
                    if len(self.series) >= self.max_series:
                        self.dropped_series += 1
                        continue
                    meta = {"name": name, "type": mtype, "labels": labels,
                            "help": help_text or ""}
                    if mtype == "histogram":
                        meta["boundaries"] = list(payload[3])
                    s = self.series[key] = _Series(meta, self.points)
                    if self.on_register is not None:
                        self.on_register(key, meta)
                elif help_text and not s.meta.get("help"):
                    s.meta["help"] = help_text
                if mtype == "histogram":
                    b_delta, s_delta, c_delta = \
                        payload[0], payload[1], payload[2]
                    if len(s.hist_buckets) != len(b_delta):
                        s.hist_buckets = [0.0] * len(b_delta)
                        s.meta["boundaries"] = list(payload[3])
                    for i, d in enumerate(b_delta):
                        s.hist_buckets[i] += d
                    s.hist_sum += s_delta
                    s.hist_count += int(c_delta)
                    s.ring.append((t, (b_delta, s_delta, int(c_delta))))
                elif mtype == "counter":
                    s.counter_total += payload
                    s.ring.append((t, payload))
                else:
                    s.gauge_last = payload
                    s.ring.append((t, payload))
                self.points_ingested += 1
            self.batches_ingested += 1

    # -- reads -----------------------------------------------------------

    def latest_fold(self) -> List[Dict[str, Any]]:
        """The cluster-wide fold, shaped like a registry snapshot (the
        shape `util.metrics.render_prometheus` consumes)."""
        by_name: Dict[str, Dict[str, Any]] = {}
        for s in self.series.values():
            meta = s.meta
            out = by_name.setdefault(meta["name"], {
                "name": meta["name"], "type": meta["type"],
                "help": meta.get("help", ""), "samples": []})
            if meta["type"] == "histogram":
                if not s.hist_buckets:
                    continue  # metadata-only (recovered, nothing pushed)
                out["samples"].append({
                    "tags": dict(meta["labels"]),
                    "buckets": list(s.hist_buckets),
                    "boundaries": list(meta.get("boundaries", ())),
                    "sum": s.hist_sum, "count": s.hist_count})
            elif meta["type"] == "counter":
                if not s.ring:
                    continue
                out["samples"].append({"tags": dict(meta["labels"]),
                                       "value": s.counter_total})
            else:
                if not s.ring:
                    continue
                out["samples"].append({"tags": dict(meta["labels"]),
                                       "value": s.gauge_last})
        return [m for m in by_name.values() if m["samples"]]

    def _select(self, name: str,
                labels: Optional[Dict[str, str]]) -> List[_Series]:
        out = []
        for s in self.series.values():
            if s.meta["name"] != name:
                continue
            if labels and any(s.meta["labels"].get(k) != v
                              for k, v in labels.items()):
                continue
            out.append(s)
        return out

    @staticmethod
    def _window_points(s: _Series, since: float) -> List[Tuple[float, Any]]:
        return [(t, p) for t, p in s.ring if t >= since]

    def window_histogram(self, name: str, window_s: float,
                         labels: Optional[Dict[str, str]] = None,
                         now: Optional[float] = None,
                         ) -> Tuple[List[float], List[float], float, int]:
        """Summed bucket increments over the window across matching
        series → (boundaries, bucket_counts, sum, count)."""
        now = time.time() if now is None else now
        since = now - window_s
        boundaries: List[float] = []
        buckets: List[float] = []
        total_sum, total_count = 0.0, 0
        for s in self._select(name, labels):
            if s.meta["type"] != "histogram":
                continue
            sb = list(s.meta.get("boundaries", ()))
            for t, (b_delta, s_delta, c_delta) in \
                    self._window_points(s, since):
                if not boundaries:
                    boundaries = sb
                    buckets = [0.0] * len(b_delta)
                if len(b_delta) != len(buckets):
                    continue  # incompatible boundaries; skip
                for i, d in enumerate(b_delta):
                    buckets[i] += d
                total_sum += s_delta
                total_count += c_delta
        return boundaries, buckets, total_sum, total_count

    @staticmethod
    def bucket_quantile(boundaries: List[float], buckets: List[float],
                        q: float) -> Optional[float]:
        total = sum(buckets)
        if total <= 0:
            return None
        target = q * total
        acc = 0.0
        for i, c in enumerate(buckets):
            acc += c
            if acc >= target:
                return (boundaries[i] if i < len(boundaries)
                        else boundaries[-1] if boundaries else float("inf"))
        return boundaries[-1] if boundaries else None

    def query(self, name: str, window_s: float = 60.0, agg: str = "raw",
              labels: Optional[Dict[str, str]] = None,
              group_by: Optional[List[str]] = None,
              now: Optional[float] = None) -> Dict[str, Any]:
        """Windowed read.  agg: raw | rate | sum | avg | max | min | pNN
        (e.g. p99 — quantile-over-time on pushed histogram buckets)."""
        now = time.time() if now is None else now
        since = now - window_s
        matched = self._select(name, labels)
        out: Dict[str, Any] = {"series": name, "window_s": window_s,
                               "agg": agg, "matched": len(matched)}

        if agg.startswith("p") and agg[1:].replace(".", "").isdigit():
            q = float(agg[1:]) / 100.0
            boundaries, buckets, hsum, hcount = self.window_histogram(
                name, window_s, labels, now=now)
            out["value"] = self.bucket_quantile(boundaries, buckets, q)
            out["count"] = hcount
            out["sum"] = hsum
            return out

        if agg == "raw":
            rows = []
            for s in matched:
                pts = []
                for t, p in self._window_points(s, since):
                    if s.meta["type"] == "histogram":
                        pts.append([round(t, 3), p[2]])
                    else:
                        pts.append([round(t, 3), p])
                rows.append({"labels": s.meta["labels"], "points": pts})
            out["results"] = rows
            return out

        # Scalar-per-group aggregations.
        groups: Dict[Tuple, Dict[str, Any]] = {}
        for s in matched:
            gkey = tuple((k, s.meta["labels"].get(k, ""))
                         for k in (group_by or ()))
            g = groups.setdefault(gkey, {"labels": dict(gkey), "values": []})
            pts = self._window_points(s, since)
            if not pts:
                continue
            if agg == "rate":
                if s.meta["type"] == "histogram":
                    inc = sum(p[2] for _, p in pts)
                elif s.meta["type"] == "counter":
                    inc = sum(p for _, p in pts)
                else:  # gauge: net change over the window
                    inc = pts[-1][1] - pts[0][1]
                g["values"].append(inc / max(window_s, 1e-9))
            else:  # gauge-style: latest value per series
                p = pts[-1][1]
                g["values"].append(p[2] if s.meta["type"] == "histogram"
                                   else p)
        rows = []
        for g in groups.values():
            vals = g["values"]
            if agg in ("rate", "sum"):
                v = sum(vals)
            elif agg == "avg":
                v = sum(vals) / len(vals) if vals else None
            elif agg == "max":
                v = max(vals) if vals else None
            elif agg == "min":
                v = min(vals) if vals else None
            else:
                raise ValueError(f"unknown agg {agg!r}")
            rows.append({"labels": g["labels"], "value": v})
        out["results"] = rows
        return out

    def stats(self) -> Dict[str, Any]:
        return {"series": len(self.series),
                "dropped_series": self.dropped_series,
                "points_ingested": self.points_ingested,
                "batches_ingested": self.batches_ingested}


# -- SLO burn-rate tracking ----------------------------------------------

_DEFAULT_PAGE_BURN = 10.0
_DEFAULT_WARN_BURN = 2.0


class SloTracker:
    """Declarative objectives evaluated against the retention store.

    Two objective kinds:

      * ``latency_quantile`` — ``<series> p<q*100> < threshold_s over
        window_s``.  Error fraction = fraction of histogram
        observations above the threshold in the window; error budget =
        1 - q.
      * ``error_ratio`` — ``<bad_series> / <total_series> < max_ratio
        over window_s``.  Error fraction = bad rate / total rate;
        budget = max_ratio.

    Burn rate = error fraction / budget.  The state machine is the
    standard multi-window recipe: **page** when both the long window
    (the objective's own) and the short window (long/12) burn at >=
    page_burn, **warning** at >= warn_burn, else **ok** — so a page
    needs burn that is both sustained and still happening.
    """

    def __init__(self, on_transition: Optional[
            Callable[[str, str, str, float], None]] = None) -> None:
        self.slos: Dict[str, Dict[str, Any]] = {}
        self.state: Dict[str, Dict[str, Any]] = {}
        self.on_transition = on_transition

    def register(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        name = spec.get("name")
        if not name:
            raise ValueError("SLO spec needs a 'name'")
        kind = spec.get("objective", "latency_quantile")
        if kind not in ("latency_quantile", "error_ratio"):
            raise ValueError(f"unknown SLO objective {kind!r}")
        if kind == "latency_quantile":
            if not spec.get("series"):
                raise ValueError("latency_quantile SLO needs 'series'")
            spec.setdefault("q", 0.99)
            if "threshold_s" not in spec:
                raise ValueError("latency_quantile SLO needs 'threshold_s'")
        else:
            if not spec.get("bad_series") or not spec.get("total_series"):
                raise ValueError(
                    "error_ratio SLO needs 'bad_series' and 'total_series'")
            spec.setdefault("max_ratio", 0.01)
        spec.setdefault("window_s", 300.0)
        spec.setdefault("page_burn", _DEFAULT_PAGE_BURN)
        spec.setdefault("warn_burn", _DEFAULT_WARN_BURN)
        self.slos[name] = spec
        self.state.setdefault(name, {
            "state": "ok", "burn_long": 0.0, "burn_short": 0.0,
            "since": time.time(), "transitions": 0})
        return spec

    def remove(self, name: str) -> bool:
        self.state.pop(name, None)
        return self.slos.pop(name, None) is not None

    def _error_fraction(self, store: MetricsStore, spec: Dict[str, Any],
                        window_s: float, now: float) -> Tuple[float, float]:
        """→ (error_fraction, event_count) over `window_s`."""
        labels = spec.get("labels")
        if spec.get("objective", "latency_quantile") == "latency_quantile":
            boundaries, buckets, _, count = store.window_histogram(
                spec["series"], window_s, labels, now=now)
            if count <= 0:
                return 0.0, 0.0
            threshold = float(spec["threshold_s"])
            good = 0.0
            for i, c in enumerate(buckets):
                ub = boundaries[i] if i < len(boundaries) else float("inf")
                if ub <= threshold:
                    good += c
            return max(0.0, (count - good) / count), float(count)
        bad = store.query(spec["bad_series"], window_s, "rate",
                          labels=spec.get("bad_labels") or labels, now=now)
        total = store.query(spec["total_series"], window_s, "rate",
                            labels=spec.get("total_labels") or labels,
                            now=now)
        bad_v = sum(r["value"] or 0.0 for r in bad["results"])
        tot_v = sum(r["value"] or 0.0 for r in total["results"])
        if tot_v <= 0:
            return 0.0, 0.0
        return max(0.0, bad_v / tot_v), tot_v * window_s

    def evaluate(self, store: MetricsStore,
                 now: Optional[float] = None) -> List[Tuple[str, str, str]]:
        """Re-evaluate every SLO; returns [(name, old, new)] transitions."""
        now = time.time() if now is None else now
        transitions = []
        for name, spec in self.slos.items():
            long_w = float(spec["window_s"])
            short_w = max(1.0, long_w / 12.0)
            if spec.get("objective",
                        "latency_quantile") == "latency_quantile":
                budget = max(1e-9, 1.0 - float(spec["q"]))
            else:
                budget = max(1e-9, float(spec["max_ratio"]))
            frac_long, n_long = self._error_fraction(
                store, spec, long_w, now)
            frac_short, _ = self._error_fraction(store, spec, short_w, now)
            burn_long = frac_long / budget
            burn_short = frac_short / budget
            if burn_long >= spec["page_burn"] and \
                    burn_short >= spec["page_burn"]:
                new_state = "page"
            elif burn_long >= spec["warn_burn"] and \
                    burn_short >= spec["warn_burn"]:
                new_state = "warning"
            else:
                new_state = "ok"
            st = self.state[name]
            st["burn_long"] = round(burn_long, 4)
            st["burn_short"] = round(burn_short, 4)
            st["events_long"] = n_long
            if new_state != st["state"]:
                old = st["state"]
                st["state"] = new_state
                st["since"] = now
                st["transitions"] += 1
                transitions.append((name, old, new_state))
                if self.on_transition is not None:
                    self.on_transition(name, old, new_state, burn_long)
        return transitions

    def status(self, store: MetricsStore) -> List[Dict[str, Any]]:
        """The `GET /api/slo` payload."""
        out = []
        now = time.time()
        for name, spec in self.slos.items():
            st = self.state.get(name, {})
            row = {"name": name, "spec": spec, "state": st.get("state", "ok"),
                   "burn_long": st.get("burn_long", 0.0),
                   "burn_short": st.get("burn_short", 0.0),
                   "since": st.get("since"),
                   "transitions": st.get("transitions", 0)}
            if spec.get("objective",
                        "latency_quantile") == "latency_quantile":
                boundaries, buckets, _, count = store.window_histogram(
                    spec["series"], float(spec["window_s"]),
                    spec.get("labels"), now=now)
                row["current_quantile_s"] = MetricsStore.bucket_quantile(
                    boundaries, buckets, float(spec["q"]))
                row["window_events"] = count
            out.append(row)
        return out
