"""HA GCS: Raft-lite replication of the GCS write-ahead log.

Reference shape: the upstream GCS delegates fault tolerance to an
external Redis (PAPER.md layer 2). Here replication is built natively on
the WAL the GCS already writes (gcs/server.py `flush_now`): N replicas
each run the full `GcsServer` store, the leader appends every
write-through frame to a quorum of followers before acking, and
leadership is a term-numbered lease renewed over the same RPC plane.

Raft-lite, deliberately smaller than Raft:

- The replicated log IS the existing WAL frame stream. Records are
  absolute `(table, key, present, value)` cells, so re-applying a frame
  is idempotent and followers never need log truncation/rollback — a
  frame that reached a quorum is never reordered because exactly one
  leader per term produces frames (vote safety), and a frame that missed
  quorum is simply re-sent (possibly with a superset of cells) at the
  same index.
- Elections fire on lease expiry; the vote criterion is log completeness
  (`(last_term, last_index)` at least as new as the voter's), so a
  follower missing an acked write can never win — "no acked write
  forgotten" across failover.
- Catch-up is a full-state snapshot install (the persisted tables are
  small — control-plane metadata, not data plane), not incremental log
  shipping.

Followers redirect every non-replication RPC with a typed
`NotLeaderError` carrying a leader hint; `gcs/client.py` and the
simcluster channel parse it out of the standard error string and
re-resolve, so clients ride the existing jittered-backoff reconnect path
onto the new leader with no new wire machinery.
"""

from __future__ import annotations

import asyncio
import logging
import random
import re
import time
from typing import Any, Awaitable, Callable, Dict, List, Optional

from ray_tpu.core.config import ray_config

logger = logging.getLogger(__name__)


class NotLeaderError(RuntimeError):
    """Raised by a follower replica for any RPC only the leader may
    serve. Crosses the wire as the standard handler-error string
    ("NotLeaderError: leader=gcs1 term=3"); `parse_not_leader` recovers
    the redirect hint on the client side."""

    def __init__(self, leader_hint: Optional[str] = None, term: int = 0):
        self.leader_hint = leader_hint
        self.term = term
        super().__init__(f"leader={leader_hint or '?'} term={term}")


_NOT_LEADER_RE = re.compile(
    r"NotLeaderError\b.*?leader=(\S+)\s+term=(\d+)")


def parse_not_leader(text: Any) -> Optional[Dict[str, Any]]:
    """Recover the redirect hint from an RpcError string. Returns
    {"leader": addr-or-None, "term": int} or None if the error is not a
    NOT_LEADER redirect."""
    m = _NOT_LEADER_RE.search(str(text or ""))
    if not m:
        return None
    leader = m.group(1)
    return {"leader": None if leader == "?" else leader,
            "term": int(m.group(2))}


class QuorumLostError(RuntimeError):
    """A write-through frame could not reach a majority: the mutation
    fails (and is retried by the client against whoever leads next)
    rather than acking a write only this replica remembers."""


class Replication:
    """Per-replica consensus state, owned by a `GcsServer`.

    `peer_call(peer_id, method, **kwargs)` is the outbound RPC: the
    simcluster injects its fault-planned dispatch; production dials
    RpcClients from the `peers` id->address map. `address_of(peer_id)`
    renders the redirect hint clients dial (replica ids in the sim,
    host:port in production).
    """

    def __init__(self, server: Any, self_id: str, peers: List[str], *,
                 peer_call: Optional[Callable[..., Awaitable[Any]]] = None,
                 peer_addrs: Optional[Dict[str, str]] = None,
                 address_of: Optional[Callable[[str], str]] = None,
                 rng: Optional[random.Random] = None):
        self.server = server
        self.self_id = self_id
        self.peers = [p for p in peers if p != self_id]
        self.cluster_size = len(self.peers) + 1
        self.quorum = self.cluster_size // 2 + 1
        # -- consensus state ------------------------------------------
        self.term = 0
        self.role = "follower"           # follower | candidate | leader
        self.leader_id: Optional[str] = None
        self.voted_for: Dict[int, str] = {}
        self.last_index = 0              # last quorum-committed frame
        self.last_term = 0               # term that produced it
        # Observed leader per term, merged across replicas by the HA
        # bench to assert the one-leader-per-term invariant.
        self.leaders_by_term: Dict[int, str] = {}
        self.elections = 0               # elections this replica started
        self.frames_replicated = 0
        self.match_index: Dict[str, int] = {}  # peer -> confirmed index
        # -- wiring ---------------------------------------------------
        self._peer_addrs = dict(peer_addrs or {})
        self._peer_call = peer_call or self._dial_peer
        self._address_of = address_of or (
            lambda pid: self._peer_addrs.get(pid, pid))
        self._rng = rng or random.Random()
        self._peer_clients: Dict[str, Any] = {}
        self._syncing: set = set()  # peers with a catch-up in flight
        self._renew_tasks: set = set()  # in-flight lease renewals
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        now = time.monotonic()
        self._election_deadline = now + self._election_timeout()
        self._last_quorum_at = now

    # -- lifecycle ----------------------------------------------------
    @property
    def active(self) -> bool:
        return self.cluster_size > 1

    def is_leader(self) -> bool:
        return self.role == "leader"

    def leader_address(self) -> Optional[str]:
        if self.leader_id is None:
            return None
        if self.leader_id == self.self_id:
            return self._address_of(self.self_id)
        return self._address_of(self.leader_id)

    def recover(self) -> None:
        """Seed (term, index) from the persisted `replication_meta`
        record the WAL replay restored — a rejoining replica must not
        vote as if its log were empty — and the Raft hard state
        (currentTerm, votedFor) the `vote` record persisted: a replica
        that granted a vote in term N and was kill -9'd must come back
        remembering it, or it could vote twice in term N and mint two
        leaders for one term."""
        st = self.server.replication_meta.get("state") or {}
        self.last_index = int(st.get("index", 0))
        self.last_term = int(st.get("term", 0))
        vote = self.server.replication_meta.get("vote") or {}
        vterm = int(vote.get("term", 0))
        if vterm and vote.get("voted_for"):
            self.voted_for[vterm] = vote["voted_for"]
        self.term = max(self.term, self.last_term, vterm)

    async def _persist_hard_state(self) -> bool:
        """Durably record (currentTerm, votedFor) BEFORE acting on them.
        The `vote` record is per-replica LOCAL state: it rides our own
        WAL (so `recover` sees it after a crash) but is never shipped in
        replicated frames or snapshots — a leader's vote must not
        overwrite a follower's. Returns False when the write failed; the
        caller must then refuse to vote / stand."""
        import pickle
        import struct

        server = self.server
        record = {"term": self.term,
                  "voted_for": self.voted_for.get(self.term)}
        server.replication_meta["vote"] = record
        if not getattr(server, "_storage_path", None):
            return True  # storage-less replica (unit rigs): in-memory only
        payload = pickle.dumps(
            [("replication_meta", "vote", True, record)], protocol=5)
        frame = struct.pack("<I", len(payload)) + payload
        try:
            async with server._flush_lock:
                await asyncio.to_thread(server._append_wal, frame)
            return True
        except Exception:
            logger.warning("GCS %s could not persist vote state",
                           self.self_id, exc_info=True)
            return False

    def start(self) -> None:
        if self._task is None and self.active:
            self._task = asyncio.ensure_future(self._ticker())

    def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            self._task = None
        # kill -9 semantics: an in-flight renewal must die with the
        # process, not keep asserting a lease the holder no longer runs.
        for t in list(self._renew_tasks):
            t.cancel()
        self._renew_tasks.clear()

    def status(self) -> Dict[str, Any]:
        lag = 0
        if self.is_leader() and self.peers:
            lag = self.last_index - min(
                self.match_index.get(p, 0) for p in self.peers)
        return {
            "replica_id": self.self_id,
            "role": self.role,
            "term": self.term,
            "leader": self.leader_id,
            "leader_address": self.leader_address(),
            "last_index": self.last_index,
            "replication_lag": lag,
            "elections": self.elections,
            "replicas": self.cluster_size,
            "quorum": self.quorum,
        }

    # -- timers -------------------------------------------------------
    def _cfg_s(self, name: str) -> float:
        return getattr(ray_config(), name) / 1000.0

    def _election_timeout(self) -> float:
        # Randomized per-attempt spread breaks split votes; seeding the
        # rng (simcluster does) keeps fault scenarios replayable.
        return self._cfg_s("gcs_ha_lease_ms") * (1.0 + self._rng.random())

    def _reset_election_deadline(self) -> None:
        self._election_deadline = time.monotonic() + self._election_timeout()

    async def _ticker(self) -> None:
        renew_s = self._cfg_s("gcs_ha_renew_ms")
        while not self._stopped:
            await asyncio.sleep(renew_s)
            try:
                if self.is_leader():
                    # Fire-and-collect: a partitioned peer's reply
                    # timeout must not stretch the heartbeat cadence the
                    # HEALTHY follower observes, or its election
                    # deadline fires against a live leader and the set
                    # churns through terms for the partition's lifetime.
                    t = asyncio.ensure_future(self._renew_guard())
                    self._renew_tasks.add(t)
                    t.add_done_callback(self._renew_tasks.discard)
                elif time.monotonic() >= self._election_deadline:
                    await self._run_election()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.warning("replication tick failed", exc_info=True)

    async def _renew_guard(self) -> None:
        try:
            await self._renew_lease()
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.warning("lease renewal failed", exc_info=True)

    # -- role transitions ---------------------------------------------
    def _become_follower(self, leader: Optional[str] = None) -> None:
        was_leader = self.role == "leader"
        self.role = "follower"
        self.leader_id = leader
        self._reset_election_deadline()
        if was_leader:
            logger.warning("GCS %s stepping down (term %d, new leader %s)",
                           self.self_id, self.term, leader or "?")

    async def _become_leader(self, term: int) -> None:
        self.role = "leader"
        self.leader_id = self.self_id
        self.leaders_by_term[term] = self.self_id
        self.match_index = {p: 0 for p in self.peers}
        self._last_quorum_at = time.monotonic()
        logger.info("GCS %s elected leader for term %d (log index %d)",
                    self.self_id, term, self.last_index)
        from ray_tpu.core import flight

        if flight.enabled:
            flight.instant("gcs", "gcs.failover",
                           arg=f"{self.self_id}:term={term}")
        # Promotion mirrors restart recovery: soft state (heartbeats,
        # metric identities, SLO watchers, stuck reschedules) rebuilds
        # through the same contracts a restarted GCS uses.
        await self.server._on_promoted(term)
        # Assert the lease immediately so lagging followers stop
        # standing for election against us.
        await self._renew_lease()

    # -- leader: lease renewal + replication --------------------------
    async def _renew_lease(self) -> None:
        term = self.term
        replies = await self._broadcast(
            "replicate_wal", term=term, leader=self.self_id,
            index=self.last_index, prev_term=self.last_term, frame=None)
        acked = 1
        for peer, r in replies:
            if r is None:
                continue
            if r.get("term", 0) > self.term:
                self.term = r["term"]
                self._become_follower()
                return
            if r.get("ok"):
                acked += 1
                idx = int(r.get("index", 0))
                self.match_index[peer] = max(
                    self.match_index.get(peer, 0),
                    min(idx, self.last_index))
                rlt = r.get("log_term")
                if idx < self.last_index or (
                        rlt is not None
                        and (idx, rlt) != (self.last_index,
                                           self.last_term)):
                    # Restarted/lagging follower: catch it up from the
                    # heartbeat, not only on the next write (a quiet
                    # cluster would otherwise leave it behind forever).
                    # A log head that MISMATCHES ours (rather than
                    # trailing it) is a diverged tail — a crash-replayed
                    # frame no quorum ever acked — and the snapshot
                    # install is what rolls it back.
                    self._sync_peer_bg(peer)
            elif "need" in r:
                self._sync_peer_bg(peer)
        now = time.monotonic()
        if acked >= self.quorum:
            self._last_quorum_at = now
        elif now - self._last_quorum_at > self._cfg_s("gcs_ha_lease_ms"):
            # A leader partitioned from every quorum must stop serving:
            # its lease is not renewable, so a majority-side leader may
            # already exist — step down rather than serve stale reads
            # forever.
            logger.warning("GCS %s lost quorum contact; stepping down",
                           self.self_id)
            self._become_follower()

    async def commit(self, frame: bytes) -> None:
        """Replicate one WAL frame (already stamped with the next index
        via `stamp_record`) to a quorum. Called by the leader's
        `flush_now` after the local append; raises QuorumLostError if a
        majority cannot confirm — the mutation then fails upward and the
        client retries against whoever leads next."""
        term = self.term
        index = self.last_index + 1
        replies = await self._broadcast(
            "replicate_wal", term=term, leader=self.self_id,
            index=index, prev_term=self.last_term, frame=frame)
        acked = 1  # the local append already happened
        for peer, r in replies:
            if r is None:
                continue
            if r.get("term", 0) > self.term:
                self.term = r["term"]
                self._become_follower()
                break
            if r.get("ok"):
                acked += 1
                self.match_index[peer] = max(
                    self.match_index.get(peer, 0), int(r.get("index", 0)))
            elif "need" in r:
                # Lagging or rejoined follower: install a full snapshot
                # then retry this frame once, inline — it may be the ack
                # that completes the quorum.
                if await self._sync_peer(peer):
                    retry = await self._call_peer(
                        peer, "replicate_wal", term=term,
                        leader=self.self_id, index=index,
                        prev_term=self.last_term, frame=frame)
                    if retry is not None and retry.get("ok"):
                        acked += 1
                        self.match_index[peer] = index
        if not self.is_leader() or acked < self.quorum:
            raise QuorumLostError(
                f"frame {index}: {acked}/{self.cluster_size} acks "
                f"(quorum {self.quorum})")
        self.last_index = index
        self.last_term = term
        self.frames_replicated += 1
        self._last_quorum_at = time.monotonic()
        self.server.replication_meta["state"] = {"term": term,
                                                 "index": index}
        from ray_tpu.core import flight

        if flight.enabled:
            flight.instant("gcs", "wal.replicate",
                           arg=f"idx={index}:acks={acked}")

    def stamp_record(self) -> tuple:
        """The replication-meta cell embedded in every replicated frame:
        WAL replay restores (term, index) through the ordinary record
        path, so a rejoining replica recovers its log position for free."""
        return ("replication_meta", "state", True,
                {"term": self.term, "index": self.last_index + 1})

    def _sync_peer_bg(self, peer: str) -> None:
        """At most one in-flight snapshot install per peer."""
        if peer in self._syncing:
            return
        self._syncing.add(peer)

        async def _bg() -> None:
            try:
                await self._sync_peer(peer)
            except Exception:
                logger.debug("peer sync failed", exc_info=True)
            finally:
                self._syncing.discard(peer)

        asyncio.ensure_future(_bg())

    async def _sync_peer(self, peer: str) -> bool:
        """Full-state catch-up: ship the persisted tables at our current
        commit point. Small by construction (control-plane metadata)."""
        import pickle

        tables = {}
        for t in self.server._PERSISTED_TABLES:
            tbl = dict(getattr(self.server, t))
            if t == "replication_meta":
                tbl.pop("vote", None)  # our vote is not the peer's vote
            tables[t] = tbl
        blob = pickle.dumps(tables, protocol=5)
        r = await self._call_peer(
            peer, "install_snapshot", term=self.term, leader=self.self_id,
            index=self.last_index, log_term=self.last_term, snapshot=blob)
        ok = bool(r and r.get("ok"))
        if ok:
            self.match_index[peer] = max(
                self.match_index.get(peer, 0), self.last_index)
        return ok

    # -- elections ----------------------------------------------------
    async def _run_election(self) -> None:
        self.term += 1
        term = self.term
        self.voted_for[term] = self.self_id
        self.role = "candidate"
        self.leader_id = None
        self.elections += 1
        self._reset_election_deadline()
        if not await self._persist_hard_state():
            # Candidacy we can't durably record is candidacy we must not
            # announce: a crash would forget the self-vote and free this
            # replica to vote for someone else in the same term.
            self.role = "follower"
            return
        from ray_tpu.core import flight

        if flight.enabled:
            flight.instant("gcs", "gcs.election",
                           arg=f"{self.self_id}:term={term}")
        logger.info("GCS %s standing for election (term %d, log %d.%d)",
                    self.self_id, term, self.last_term, self.last_index)
        replies = await self._broadcast(
            "request_vote", term=term, candidate=self.self_id,
            last_index=self.last_index, last_term=self.last_term)
        votes = 1
        for _peer, r in replies:
            if r is None:
                continue
            if r.get("term", 0) > self.term:
                self.term = r["term"]
                self._become_follower()
                return
            if r.get("granted"):
                votes += 1
        if self.term != term or self.role != "candidate":
            return  # superseded mid-election (a leader asserted itself)
        if votes >= self.quorum:
            await self._become_leader(term)
        else:
            self.role = "follower"
            self._reset_election_deadline()

    # -- follower-side handlers (dispatched via GcsServer) ------------
    async def on_request_vote(self, *, term: int, candidate: str,
                              last_index: int,
                              last_term: int) -> Dict[str, Any]:
        if term > self.term:
            self.term = term
            self._become_follower()
        granted = False
        if term == self.term:
            prior = self.voted_for.get(term)
            # Log-completeness criterion: never elect a leader missing a
            # quorum-acked write (the acked frame lives on a majority, so
            # every reachable quorum contains a voter that refuses).
            log_ok = ((last_term, last_index)
                      >= (self.last_term, self.last_index))
            if prior in (None, candidate) and log_ok \
                    and self.role != "leader":
                self.voted_for[term] = candidate
                # The vote counts only once it is durable: granting and
                # then crashing before the fsync would let this replica
                # re-vote in the same term after restart. The in-memory
                # vote stays even on failure (conservative — we still
                # refuse other candidates this incarnation).
                if await self._persist_hard_state():
                    granted = True
                    self._reset_election_deadline()
        return {"term": self.term, "granted": granted}

    async def on_replicate(self, *, term: int, leader: str,
                           index: int = 0,
                           prev_term: Optional[int] = None,
                           frame: Optional[bytes] = None) -> Dict[str, Any]:
        if term < self.term:
            return {"ok": False, "term": self.term}
        if term > self.term or self.leader_id != leader \
                or self.role != "follower":
            self.term = term
            self._become_follower(leader)
        self.leaders_by_term.setdefault(term, leader)
        self._reset_election_deadline()
        if frame is None:  # lease-renewal heartbeat
            # Reply with our full log head: the leader compares it to its
            # own and snapshots us if we trail it OR diverge from it.
            return {"ok": True, "term": self.term,
                    "index": self.last_index, "log_term": self.last_term}
        if index > self.last_index + 1:
            return {"ok": False, "term": self.term,
                    "need": self.last_index + 1}
        if prev_term is not None:
            # No-rollback only holds for frames that extend a matching
            # log. A crash can replay an UNCOMMITTED frame (appended
            # locally, quorum never reached) as if committed; when the
            # next leader — elected without it — sends a conflicting
            # frame at an overlapping index, blind application would
            # leave the divergent cells in place forever. Detect the
            # mismatch and demand a snapshot install (which rolls the
            # tail back) instead of applying.
            diverged = (
                (index == self.last_index + 1
                 and prev_term != self.last_term)
                or (index <= self.last_index
                    and (index, term) != (self.last_index,
                                          self.last_term)))
            if diverged:
                return {"ok": False, "term": self.term, "need": index,
                        "diverged": True}
        await self._apply_frame(index, term, frame)
        return {"ok": True, "term": self.term, "index": self.last_index}

    async def _apply_frame(self, index: int, term: int,
                           frame: bytes) -> None:
        """Apply a replicated frame: mutate the tables (absolute cells —
        idempotent under leader retries at the same index) and append the
        identical frame to our own WAL, so this replica's disk recovery
        is byte-for-byte the leader's."""
        import pickle
        import struct

        server = self.server
        async with server._flush_lock:
            (n,) = struct.unpack("<I", frame[:4])
            records = pickle.loads(frame[4:4 + n])
            for table, key, present, value in records:
                if table == "replication_meta" and key == "vote":
                    continue  # per-replica hard state, never replicated
                tbl = getattr(server, table, None)
                if tbl is None:
                    continue
                if present:
                    tbl[key] = value
                else:
                    tbl.pop(key, None)
            await asyncio.to_thread(server._append_wal, frame)
            self.last_index = max(self.last_index, index)
            self.last_term = term
            if server._wal_size >= ray_config().gcs_wal_compact_bytes:
                await server._compact()

    async def on_install_snapshot(self, *, term: int, leader: str,
                                  index: int, log_term: int,
                                  snapshot: bytes) -> Dict[str, Any]:
        if term < self.term:
            return {"ok": False, "term": self.term}
        if term > self.term or self.leader_id != leader:
            self.term = term
            self._become_follower(leader)
        self._reset_election_deadline()
        import pickle

        tables = pickle.loads(snapshot)
        server = self.server
        async with server._flush_lock:
            # The install may REGRESS our (term, index) — that is the
            # rollback path for a crash-replayed uncommitted tail — but
            # our own vote record must survive it (Raft hard state is
            # per-replica, not part of the replicated log).
            local_vote = server.replication_meta.get("vote")
            for t in server._PERSISTED_TABLES:
                tbl = getattr(server, t)
                tbl.clear()
                tbl.update(tables.get(t, {}))
            if local_vote is not None:
                server.replication_meta["vote"] = local_vote
            else:
                server.replication_meta.pop("vote", None)
            self.last_index = index
            self.last_term = log_term
            # Persist the installed state as a compacted snapshot so a
            # crash right after catch-up recovers to it.
            await server._compact()
        return {"ok": True, "term": self.term, "index": self.last_index}

    # -- outbound plumbing --------------------------------------------
    async def _broadcast(self, method: str, **kw) -> List[tuple]:
        results = await asyncio.gather(
            *(self._call_peer(p, method, **kw) for p in self.peers))
        return list(zip(self.peers, results))

    async def _call_peer(self, peer: str, method: str,
                         **kw) -> Optional[Dict[str, Any]]:
        timeout = self._cfg_s("gcs_ha_replicate_timeout_ms")
        try:
            return await asyncio.wait_for(
                self._peer_call(peer, method, **kw), timeout=timeout)
        except asyncio.CancelledError:
            raise
        except Exception:
            return None  # dead/partitioned peer — counts as no ack

    async def _dial_peer(self, peer: str, method: str, **kw) -> Any:
        """Production outbound path: lazily-dialed RpcClients keyed by
        replica id (the simcluster injects `peer_call` instead)."""
        from ray_tpu.core.rpc import RpcClient

        client = self._peer_clients.get(peer)
        if client is None or not client.connected:
            addr = self._peer_addrs[peer]
            client = RpcClient(addr)
            await client.connect(timeout=5.0)
            self._peer_clients[peer] = client
        return await client.call(method, timeout=10.0, **kw)
