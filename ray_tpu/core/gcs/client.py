"""GCS client: typed accessors over the RPC client.

Reference equivalent: `src/ray/gcs/gcs_client/accessor.h` (Node/Actor/Job/
InternalKV accessors) + `python/ray/_raylet.pyx:2473 GcsClient`.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from ray_tpu.core.rpc import RpcClient


class GcsClient:
    def __init__(self, address: str):
        self.rpc = RpcClient(address)

    async def connect(self, timeout: float = 10.0) -> None:
        await self.rpc.connect(timeout=timeout)

    async def close(self) -> None:
        await self.rpc.close()

    # -- pubsub ---------------------------------------------------------
    async def subscribe(self, channel: str,
                        handler: Callable[[Any], Any]) -> None:
        self.rpc.on_push(channel, handler)
        await self.rpc.call("subscribe", channel=channel)

    async def publish(self, channel: str, data: Any) -> None:
        await self.rpc.call("publish", channel=channel, data=data)

    # -- nodes ----------------------------------------------------------
    async def register_node(self, **kwargs: Any) -> Dict[str, Any]:
        return await self.rpc.call("register_node", **kwargs)

    async def heartbeat(self, node_id: str,
                        resources_available: Dict[str, float],
                        load: Optional[dict] = None) -> None:
        await self.rpc.call("heartbeat", node_id=node_id,
                            resources_available=resources_available,
                            load=load, timeout=5.0)

    async def get_nodes(self) -> List[Dict[str, Any]]:
        return await self.rpc.call("get_nodes")

    async def drain_node(self, node_id: str) -> None:
        await self.rpc.call("drain_node", node_id=node_id)

    # -- actors ---------------------------------------------------------
    async def register_actor(self, actor_id: str,
                             info: Dict[str, Any]) -> Dict[str, Any]:
        return await self.rpc.call("register_actor", actor_id=actor_id,
                                   info=info)

    async def update_actor(self, actor_id: str,
                           updates: Dict[str, Any]) -> bool:
        return await self.rpc.call("update_actor", actor_id=actor_id,
                                   updates=updates)

    async def get_actor(self, actor_id: Optional[str] = None,
                        name: Optional[str] = None,
                        namespace: str = "default"
                        ) -> Optional[Dict[str, Any]]:
        return await self.rpc.call("get_actor", actor_id=actor_id, name=name,
                                   namespace=namespace)

    async def list_actors(self) -> List[Dict[str, Any]]:
        return await self.rpc.call("list_actors")

    # -- jobs -----------------------------------------------------------
    async def add_job(self, job_id: str, info: Dict[str, Any]) -> None:
        await self.rpc.call("add_job", job_id=job_id, info=info)

    async def get_job(self, job_id: str) -> Optional[Dict[str, Any]]:
        return await self.rpc.call("get_job", job_id=job_id)

    async def mark_job_finished(self, job_id: str) -> None:
        await self.rpc.call("mark_job_finished", job_id=job_id)

    async def list_jobs(self) -> List[Dict[str, Any]]:
        return await self.rpc.call("list_jobs")

    # -- task events ------------------------------------------------------
    async def add_task_events(self, events: List[Dict[str, Any]]) -> bool:
        return await self.rpc.call("add_task_events", events=events)

    async def get_task_events(self, job_id: Optional[str] = None
                              ) -> List[Dict[str, Any]]:
        return await self.rpc.call("get_task_events", job_id=job_id)

    # -- kv -------------------------------------------------------------
    async def kv_put(self, key: str, value: bytes,
                     overwrite: bool = True) -> bool:
        return await self.rpc.call("kv_put", key=key, value=value,
                                   overwrite=overwrite)

    async def kv_get(self, key: str) -> Optional[bytes]:
        return await self.rpc.call("kv_get", key=key)

    async def kv_del(self, key: str) -> bool:
        return await self.rpc.call("kv_del", key=key)

    async def kv_keys(self, prefix: str) -> List[str]:
        return await self.rpc.call("kv_keys", prefix=prefix)

    async def kv_exists(self, key: str) -> bool:
        return await self.rpc.call("kv_exists", key=key)

    # -- placement groups ------------------------------------------------
    async def register_placement_group(self, pg_id: str,
                                       info: Dict[str, Any]) -> bool:
        return await self.rpc.call("register_placement_group", pg_id=pg_id,
                                   info=info)

    async def update_placement_group(self, pg_id: str,
                                     updates: Dict[str, Any],
                                     expect_state: Optional[str] = None
                                     ) -> bool:
        return await self.rpc.call("update_placement_group", pg_id=pg_id,
                                   updates=updates,
                                   expect_state=expect_state)

    async def get_placement_group(self, pg_id: str
                                  ) -> Optional[Dict[str, Any]]:
        return await self.rpc.call("get_placement_group", pg_id=pg_id)

    async def list_placement_groups(self) -> List[Dict[str, Any]]:
        return await self.rpc.call("list_placement_groups")

    # -- misc -----------------------------------------------------------
    async def ping(self) -> str:
        return await self.rpc.call("ping", timeout=5.0)

    async def cluster_info(self) -> Dict[str, Any]:
        return await self.rpc.call("cluster_info")
