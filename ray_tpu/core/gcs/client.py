"""GCS client: typed accessors over the RPC client.

Reference equivalent: `src/ray/gcs/gcs_client/accessor.h` (Node/Actor/Job/
InternalKV accessors) + `python/ray/_raylet.pyx:2473 GcsClient`.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.core.rpc import ConnectionLost, RpcClient, RpcError

logger = logging.getLogger(__name__)


def backoff_delay(attempt: int, rng=None, *,
                  base_s: Optional[float] = None,
                  cap_s: Optional[float] = None) -> float:
    """Capped exponential backoff with FULL jitter (AWS-style:
    sleep = uniform(0, min(cap, base * 2^attempt))).

    One definition shared by every control-plane retry loop — the real
    `_ReconnectingRpc._reconnect` below and `core/simcluster.py`'s
    simulated clients — so the de-synchronization property the scale
    harness measures is the property production runs. A fixed sleep here
    (the pre-round-14 0.5 s) synchronizes 100 reconnecting clients into
    a thundering herd against a just-restarted GCS."""
    import random

    from ray_tpu.core.config import ray_config

    cfg = ray_config()
    base = (cfg.gcs_reconnect_backoff_base_ms / 1000.0
            if base_s is None else base_s)
    cap = (cfg.gcs_reconnect_backoff_max_ms / 1000.0
           if cap_s is None else cap_s)
    ceiling = min(cap, base * (2 ** min(attempt, 32)))
    return (rng or random).uniform(0.0, ceiling)


class _ReconnectingRpc:
    """RpcClient facade that survives a GCS restart (reference: GCS
    fault tolerance — workers/raylets reconnect against the restarted
    server, `gcs_client` retry machinery + `redis_store_client.h`
    persistence on the server side).

    On ConnectionLost: reconnect within the `gcs_rpc_timeout_s` window,
    re-attach push handlers, re-issue channel subscriptions, then retry
    the call once. GCS table ops are keyed/overwriting (idempotent), so
    a single retry is safe.

    HA (round 18): `address` may be a comma-separated replica set. The
    target is RE-RESOLVED on every reconnect attempt (never bound at
    construction — a moved or failed-over GCS used to be unreachable
    forever), rotating the set and preferring the leader hint carried by
    `NotLeaderError` redirects, so a raylet/driver rides its ordinary
    jittered-backoff path onto whichever replica wins the election."""

    def __init__(self, address: str):
        self.addresses = [a.strip() for a in address.split(",")
                          if a.strip()]
        # The configured replica set is the durable core of the rotation
        # set; leader hints learned from redirects are kept separately
        # and BOUNDED, so stale hints from old incarnations can't grow
        # the set (or keep dead addresses in rotation) forever.
        self._seed_addresses = list(self.addresses)
        self._hint_addresses: List[str] = []
        self._rr = 0  # rotation cursor, persistent across reconnects
        self.address = self.addresses[0]  # current target
        self._leader_hint: Optional[str] = None
        self._client = RpcClient(self.address)
        self._push_handlers: Dict[str, Callable] = {}
        self._subscribed: set = set()
        self._reconnect_lock: Optional[asyncio.Lock] = None
        self._closed = False
        self._cluster_id: Optional[str] = None

    @property
    def connected(self) -> bool:
        return self._client.connected

    async def connect(self, timeout: float = 10.0) -> None:
        self._reconnect_lock = asyncio.Lock()
        loop = asyncio.get_running_loop()
        # Split the caller's budget across the replica set: one dead
        # replica eating the FULL timeout would starve the live ones and
        # turn worst-case initial connect into N*timeout.
        deadline = loop.time() + timeout
        share = max(0.5, timeout / max(1, len(self.addresses)))
        last_err: Optional[Exception] = None
        connected = False
        for addr in self.addresses:
            budget = min(share, deadline - loop.time())
            if budget <= 0:
                break
            client = RpcClient(addr)
            try:
                await client.connect(timeout=budget)
                self.address = addr
                self._client = client
                connected = True
                break
            except Exception as e:  # noqa: BLE001
                last_err = e
                try:
                    await client.close()
                except Exception:
                    pass
        if not connected:
            raise last_err if last_err is not None else ConnectionLost(
                f"GCS at {','.join(self.addresses)} unreachable")
        try:
            self._cluster_id = await self._client.call("cluster_id",
                                                       timeout=10.0)
        except Exception:
            self._cluster_id = None

    async def close(self) -> None:
        self._closed = True
        await self._client.close()

    def on_push(self, channel: str, handler: Callable) -> None:
        self._push_handlers[channel] = handler
        self._client.on_push(channel, handler)

    def mark_subscribed(self, channel: str) -> None:
        self._subscribed.add(channel)

    async def call(self, method: str, **kwargs: Any) -> Any:
        try:
            return await self._client.call(method, **kwargs)
        except ConnectionLost:
            if self._closed:
                raise
            await self._reconnect()
            return await self._redirect_aware_call(method, kwargs)
        except RpcError as e:
            if self._closed or not self._note_redirect(e):
                raise
            if not self._leader_hint:
                # No hint to follow: rotate off this replica NOW (it may
                # be minority-partitioned yet still accepting calls) so
                # the retry loop starts against a different one.
                try:
                    await self._client.close()
                except Exception:
                    pass
                await self._reconnect()
            return await self._redirect_aware_call(method, kwargs)

    def _note_redirect(self, err: Exception) -> bool:
        """Record the leader hint from a NOT_LEADER error string (the
        follower's NotLeaderError crosses the wire as a plain handler
        error). True if this was a redirect. A QuorumLostError is
        retryable the same way: the replica we reached cannot commit
        right now (minority side of a partition) — rotate and let
        whoever leads next serve the retry."""
        from ray_tpu.core.gcs.replication import parse_not_leader

        if "QuorumLostError" in str(err):
            self._leader_hint = None
            return True
        hint = parse_not_leader(str(err))
        if hint is None:
            return False
        leader = hint.get("leader")
        if leader and leader != self.address:
            self._leader_hint = leader
        return True

    async def _redirect_aware_call(self, method: str,
                                   kwargs: Dict[str, Any]) -> Any:
        """Retry loop after a reconnect or redirect: follow NOT_LEADER
        hints (switching replicas) within the gcs_rpc_timeout_s window.
        A vacant leadership (election in progress) shows up as repeated
        redirects-with-no-hint and is ridden out on the same jittered
        backoff the reconnect path uses."""
        from ray_tpu.core.config import ray_config

        loop = asyncio.get_running_loop()
        deadline = loop.time() + ray_config().gcs_rpc_timeout_s
        attempt = 0
        while True:
            if self._leader_hint and self._leader_hint != self.address:
                # A redirect told us who leads: drop the current replica
                # and let _reconnect dial the hint.
                try:
                    await self._client.close()
                except Exception:
                    pass
                await self._reconnect()
            try:
                return await self._client.call(method, **kwargs)
            except ConnectionLost:
                if self._closed or loop.time() >= deadline:
                    raise
                await self._reconnect()
            except RpcError as e:
                if (self._closed or not self._note_redirect(e)
                        or loop.time() >= deadline):
                    raise
                if not self._leader_hint:
                    # Hint-less redirect (election running) or
                    # QuorumLostError (minority-side replica): re-calling
                    # the SAME replica would spin on it until the window
                    # expires even when a majority-side leader is
                    # reachable. Rotate off it through _reconnect after
                    # the jittered backoff.
                    await asyncio.sleep(backoff_delay(attempt))
                    try:
                        await self._client.close()
                    except Exception:
                        pass
                    await self._reconnect()
            attempt += 1

    def _note_hint_address(self, addr: str) -> None:
        """Admit a redirect hint into the rotation set without letting
        stale hints accumulate: the set is the configured seed replicas
        plus at most a replica-set's worth of the newest hints."""
        if addr in self._seed_addresses:
            return
        if addr in self._hint_addresses:
            self._hint_addresses.remove(addr)
        self._hint_addresses.append(addr)
        keep = max(1, len(self._seed_addresses))
        del self._hint_addresses[:-keep]
        self.addresses = self._seed_addresses + self._hint_addresses

    def _resolve_target(self, attempt: int) -> str:
        """Pick the address for THIS reconnect attempt. Re-resolving
        per attempt (instead of binding at construction) is what lets a
        client follow a GCS that moved or failed over: prefer the last
        NOT_LEADER hint, otherwise rotate the replica set — skipping the
        address we just gave up on, so a deliberate rotation (hint-less
        redirect off a minority replica) never re-dials it first."""
        if self._leader_hint:
            hint, self._leader_hint = self._leader_hint, None
            self._note_hint_address(hint)
            return hint
        n = len(self.addresses)
        addr = self.addresses[self._rr % n]
        self._rr += 1
        if addr == self.address and n > 1:
            addr = self.addresses[self._rr % n]
            self._rr += 1
        return addr

    async def _reconnect(self) -> None:
        from ray_tpu.core import flight
        from ray_tpu.core.config import ray_config

        async with self._reconnect_lock:
            if self._client.connected:
                return  # another caller already reconnected
            loop = asyncio.get_running_loop()
            window = ray_config().gcs_rpc_timeout_s
            deadline = loop.time() + window
            last_err: Optional[Exception] = None
            attempt = 0
            while loop.time() < deadline:
                target = self._resolve_target(attempt)
                fresh = RpcClient(target)
                try:
                    if flight.enabled:
                        flight.instant("gcs", "gcs.retry", arg=attempt)
                    # Short per-dial budget: RpcClient.connect retries a
                    # refused/dead address internally until its timeout,
                    # so a generous budget here turns every dead replica
                    # in the rotation into a multi-second sink (a 3-of-4
                    # set with one dead node would burn most of the
                    # reconnect window on it). THIS loop is the retry
                    # mechanism — move on to the next replica quickly.
                    await fresh.connect(
                        timeout=min(1.0, max(0.25,
                                             deadline - loop.time())))
                    if self._cluster_id:
                        # Ephemeral-port reuse: whoever answers on the
                        # cached address must be OUR cluster, not a new
                        # one that grabbed the freed port.
                        cid = await fresh.call("cluster_id", timeout=5.0)
                        if cid != self._cluster_id:
                            raise ConnectionLost(
                                f"{target} now serves a different "
                                f"cluster ({cid[:8]}…)")
                    for ch, h in self._push_handlers.items():
                        fresh.on_push(ch, h)
                    old, self._client = self._client, fresh
                    self.address = target
                    try:
                        await old.close()
                    except Exception:
                        pass
                    for ch in self._subscribed:
                        await fresh.call("subscribe", channel=ch)
                    logger.info("reconnected to GCS at %s (attempt %d)",
                                target, attempt)
                    if flight.enabled:
                        flight.instant("gcs", "gcs.reconnect", arg=attempt)
                    return
                except Exception as e:  # noqa: BLE001
                    last_err = e
                    try:
                        await fresh.close()
                    except Exception:
                        pass
                    # Capped exponential backoff with full jitter: a herd
                    # of clients that lost the GCS together must not
                    # retry in lockstep (satellite of ISSUE 14; fixed
                    # 0.5 s before).
                    await asyncio.sleep(backoff_delay(attempt))
                    attempt += 1
            raise ConnectionLost(
                f"GCS at {','.join(self.addresses)} unreachable for "
                f"{window}s ({attempt} attempts): {last_err}")


class GcsClient:
    def __init__(self, address: str, rpc: Optional[Any] = None):
        # `address` may be a comma-separated HA replica set; the
        # reconnecting facade rotates it and follows NOT_LEADER
        # redirects. `rpc` is injectable so core/simcluster.py can bind
        # the SAME typed accessors to an in-process loopback channel:
        # the sim's raylets speak to the real GcsServer through the real
        # client code, minus the TCP socket.
        self.rpc = rpc if rpc is not None else _ReconnectingRpc(address)

    async def connect(self, timeout: float = 10.0) -> None:
        await self.rpc.connect(timeout=timeout)

    async def close(self) -> None:
        await self.rpc.close()

    # -- pubsub ---------------------------------------------------------
    async def subscribe(self, channel: str,
                        handler: Callable[[Any], Any]) -> None:
        # Deliveries arrive as typed PubsubMessage envelopes
        # (core/wire.py); unwrap HERE so channel handlers receive the
        # plain payload. A malformed delivery raises WireDecodeError
        # into the push dispatcher's log instead of corrupting handlers.
        # The per-channel seq detects dropped deliveries (a seq that
        # moves backwards is a GCS restart: counters reset, not a drop).
        last_seq = [0]

        def unwrap(payload):
            if isinstance(payload, dict) and payload.get(
                    "_t") == "PubsubMessage":
                from ray_tpu.core.wire import from_wire

                msg = from_wire(payload, expect="PubsubMessage")
                if msg.seq is not None:
                    if last_seq[0] and msg.seq > last_seq[0] + 1:
                        logger.warning(
                            "pubsub channel %r: %d deliveries lost "
                            "(seq %d -> %d)", channel,
                            msg.seq - last_seq[0] - 1, last_seq[0],
                            msg.seq)
                    last_seq[0] = msg.seq
                payload = msg.data
            return handler(payload)

        self.rpc.on_push(channel, unwrap)
        await self.rpc.call("subscribe", channel=channel)
        self.rpc.mark_subscribed(channel)

    async def publish(self, channel: str, data: Any) -> None:
        await self.rpc.call("publish", channel=channel, data=data)

    # -- nodes ----------------------------------------------------------
    async def register_node(self, **kwargs: Any) -> Dict[str, Any]:
        from ray_tpu.core.wire import NodeInfo, to_wire

        return await self.rpc.call("register_node",
                                   node=to_wire(NodeInfo(**kwargs)))

    async def heartbeat(self, node_id: str,
                        resources_available: Dict[str, float],
                        load: Optional[dict] = None,
                        metrics: Optional[List[dict]] = None,
                        workers: Optional[List[dict]] = None) -> bool:
        """False = the GCS does not recognize this node (it restarted or
        declared the node dead): the caller must re-register.

        `metrics` is the node's coalesced metrics-pipeline batch (round
        17) and `workers` the node's batched per-worker state (round 18):
        piggybacking both here keeps the fleet at one push RPC per node
        per interval regardless of worker count, and keeps worker churn
        off the quorum-replicated write path (it lands as GCS soft
        state)."""
        return await self.rpc.call(
            "heartbeat", node_id=node_id,
            resources_available=resources_available, load=load,
            metrics=metrics, workers=workers, timeout=5.0)

    async def get_nodes(self) -> List[Dict[str, Any]]:
        return await self.rpc.call("get_nodes")

    async def drain_node(self, node_id: str) -> None:
        await self.rpc.call("drain_node", node_id=node_id)

    # -- actors ---------------------------------------------------------
    async def register_actor(self, actor_id: str,
                             info: Dict[str, Any]) -> Dict[str, Any]:
        # Typed wire envelope (core/wire.py ActorInfo): registration is
        # the durable record — validate it at the schema boundary.
        from ray_tpu.core.wire import ActorInfo, to_wire

        if isinstance(info, dict):
            info = ActorInfo(actor_id=actor_id,
                             state=info.get("state", "PENDING"),
                             **{k: v for k, v in info.items()
                                if k != "state"})
        return await self.rpc.call("register_actor", actor_id=actor_id,
                                   info=to_wire(info))

    async def update_actor(self, actor_id: str,
                           updates: Dict[str, Any]) -> bool:
        return await self.rpc.call("update_actor", actor_id=actor_id,
                                   updates=updates)

    async def get_actor(self, actor_id: Optional[str] = None,
                        name: Optional[str] = None,
                        namespace: str = "default"
                        ) -> Optional[Dict[str, Any]]:
        return await self.rpc.call("get_actor", actor_id=actor_id, name=name,
                                   namespace=namespace)

    async def list_actors(self) -> List[Dict[str, Any]]:
        return await self.rpc.call("list_actors")

    # -- jobs -----------------------------------------------------------
    async def add_job(self, job_id: str, info: Dict[str, Any]) -> None:
        from ray_tpu.core.wire import JobInfo, to_wire

        if isinstance(info, dict):
            info = JobInfo(job_id=job_id, **info)
        await self.rpc.call("add_job", job_id=job_id, info=to_wire(info))

    async def get_job(self, job_id: str) -> Optional[Dict[str, Any]]:
        return await self.rpc.call("get_job", job_id=job_id)

    async def mark_job_finished(self, job_id: str) -> None:
        await self.rpc.call("mark_job_finished", job_id=job_id)

    async def list_jobs(self) -> List[Dict[str, Any]]:
        return await self.rpc.call("list_jobs")

    # -- task events ------------------------------------------------------
    async def add_task_events(self, events: List[Dict[str, Any]]) -> bool:
        return await self.rpc.call("add_task_events", events=events)

    async def get_task_events(self, job_id: Optional[str] = None
                              ) -> List[Dict[str, Any]]:
        return await self.rpc.call("get_task_events", job_id=job_id)

    # -- kv -------------------------------------------------------------
    async def kv_put(self, key: str, value: bytes,
                     overwrite: bool = True) -> bool:
        return await self.rpc.call("kv_put", key=key, value=value,
                                   overwrite=overwrite)

    async def kv_get(self, key: str) -> Optional[bytes]:
        return await self.rpc.call("kv_get", key=key)

    async def kv_del(self, key: str) -> bool:
        return await self.rpc.call("kv_del", key=key)

    async def kv_keys(self, prefix: str) -> List[str]:
        return await self.rpc.call("kv_keys", prefix=prefix)

    async def kv_exists(self, key: str) -> bool:
        return await self.rpc.call("kv_exists", key=key)

    # -- placement groups ------------------------------------------------
    async def register_placement_group(self, pg_id: str,
                                       info: Dict[str, Any]) -> bool:
        return await self.rpc.call("register_placement_group", pg_id=pg_id,
                                   info=info)

    async def update_placement_group(self, pg_id: str,
                                     updates: Dict[str, Any],
                                     expect_state: Optional[str] = None
                                     ) -> bool:
        return await self.rpc.call("update_placement_group", pg_id=pg_id,
                                   updates=updates,
                                   expect_state=expect_state)

    async def get_placement_group(self, pg_id: str
                                  ) -> Optional[Dict[str, Any]]:
        return await self.rpc.call("get_placement_group", pg_id=pg_id)

    async def list_placement_groups(self) -> List[Dict[str, Any]]:
        return await self.rpc.call("list_placement_groups")

    # -- metrics pipeline + SLOs (round 17) -----------------------------
    async def query_metrics(self, series: str, window_s: float = 60.0,
                            agg: str = "raw",
                            labels: Optional[Dict[str, str]] = None,
                            group_by: Optional[List[str]] = None
                            ) -> Dict[str, Any]:
        return await self.rpc.call(
            "query_metrics", series=series, window_s=window_s, agg=agg,
            labels=labels, group_by=group_by, timeout=10.0)

    async def latest_metrics(self) -> List[Dict[str, Any]]:
        return await self.rpc.call("latest_metrics", timeout=10.0)

    async def metrics_stats(self) -> Dict[str, Any]:
        return await self.rpc.call("metrics_stats", timeout=5.0)

    async def register_slo(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        return await self.rpc.call("register_slo", spec=spec, timeout=10.0)

    async def remove_slo(self, name: str) -> bool:
        return await self.rpc.call("remove_slo", name=name, timeout=10.0)

    async def get_slo(self) -> List[Dict[str, Any]]:
        return await self.rpc.call("get_slo", timeout=10.0)

    async def dump_flight_record(self, window_s: Optional[float] = None,
                                 include_events: bool = True
                                 ) -> Dict[str, Any]:
        return await self.rpc.call(
            "dump_flight_record", window_s=window_s,
            include_events=include_events, timeout=10.0)

    # -- misc -----------------------------------------------------------
    async def ping(self) -> str:
        return await self.rpc.call("ping", timeout=5.0)

    async def cluster_info(self) -> Dict[str, Any]:
        return await self.rpc.call("cluster_info")
