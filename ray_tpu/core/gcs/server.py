"""GCS — the cluster-global control plane.

Reference equivalent: `src/ray/gcs/gcs_server/` (GcsNodeManager,
GcsActorManager tables, GcsKvManager, InternalPubSub, GcsHealthCheckManager,
GcsJobManager — `gcs_server.cc:189-237` init sequence). Design deviation:
actor *placement* is owner-led (the creating worker leases the actor worker
itself, like a task); the GCS stores the actor table, watches liveness, and
publishes updates. GCS-led scheduling of detached actors is layered on top
via the same table.

State is held in a pluggable store (in-memory now, matching the reference's
`InMemoryStoreClient`; a persistent backend can be swapped in for GCS
fault tolerance like `RedisStoreClient`).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional, Set

from ray_tpu.core.config import ray_config
from ray_tpu.core.rpc import RpcServer, ServerConnection

logger = logging.getLogger(__name__)


class GcsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 storage_path: Optional[str] = None):
        self._rpc = RpcServer(self, host, port)
        # Durable table storage (reference: gcs redis_store_client /
        # observable_store_client): load at boot, snapshot when dirty.
        self._storage_path = storage_path
        self._dirty = False
        self._dirty_keys: Set[tuple] = set()   # (table, key) pending flush
        self._snapshot_task: Optional[asyncio.Task] = None
        self._flush_lock = asyncio.Lock()
        self._flush_gen = 0
        self._flushed_gen = 0  # last generation SUCCESSFULLY written
        self._wal_size = 0
        # -- tables (reference: gcs_table_storage.h) ----------------------
        self.nodes: Dict[str, Dict[str, Any]] = {}       # node_id hex -> info
        self.actors: Dict[str, Dict[str, Any]] = {}      # actor_id hex -> info
        self.named_actors: Dict[str, str] = {}           # "ns/name" -> actor id
        self.jobs: Dict[str, Dict[str, Any]] = {}
        self.placement_groups: Dict[str, Dict[str, Any]] = {}
        self.kv: Dict[str, bytes] = {}
        self.workers: Dict[str, Dict[str, Any]] = {}
        # Task-event store, bounded (reference: GcsTaskManager's
        # max_num_task_events_stored).
        from collections import deque

        self.task_events: deque = deque(maxlen=100_000)
        # -- pubsub (reference: InternalPubSub / pubsub/) -----------------
        self._subs: Dict[str, Set[ServerConnection]] = {}
        self._pub_seq: Dict[str, int] = {}
        self._heartbeats: Dict[str, float] = {}
        self._health_task: Optional[asyncio.Task] = None
        self._start_time = time.time()
        # Post-restart grace: until this instant, nodes recovered from
        # persisted state (stale_view=True) are exempt from health-check
        # death — they need at least one full heartbeat interval to find
        # the restarted server before we may judge them (set by
        # _load_storage when it recovers alive nodes).
        self._restart_grace_until = 0.0
        # GCS-led placement-group rescheduling (round 15): pg_id -> the
        # asyncio task re-placing its lost bundles. Spawned by
        # _mark_node_dead, resumed at start() for groups recovered
        # mid-RESCHEDULING, re-kicked by the health loop when a stuck
        # group's cluster changes.
        self._reschedule_tasks: Dict[str, asyncio.Task] = {}
        # Outbound raylet clients for the reschedule 2PC. The simcluster
        # harness overrides `raylet_client_factory` to route through its
        # fault-injected dispatch; production dials RpcClients.
        self.raylet_client_factory = None
        self._raylet_clients: Dict[str, Any] = {}
        # -- metrics pipeline (round 17) ----------------------------------
        # metric_series is the PERSISTED half (series metadata: identity,
        # type, labels, help, boundaries — rides the WAL like any table);
        # the retention rings live only in the store: after a kill -9 the
        # recovered metadata makes re-pushed series land on their old
        # identity instead of registering duplicates, while point history
        # restarts empty.
        from ray_tpu.core.gcs.metrics_store import MetricsStore, SloTracker

        self.metric_series: Dict[str, Dict[str, Any]] = {}
        # -- HA replication (round 18) ------------------------------------
        # When `replication` is attached (multi-replica boot), every
        # write-through frame reaches a quorum before acking and
        # non-leader replicas redirect mutations via NotLeaderError.
        # `replication_meta` is an ordinary persisted table: the leader
        # stamps (term, index) into each replicated frame so WAL replay
        # restores a rejoining replica's log position for free.
        self.replication = None
        self.replication_meta: Dict[str, Any] = {}
        cfg = ray_config()
        self.metrics = MetricsStore(
            max_series=cfg.metrics_max_series,
            points=cfg.metrics_retention_points,
            on_register=self._on_series_register)
        self.slo = SloTracker(on_transition=self._on_slo_transition)
        self._slo_last_eval = 0.0

    def _on_series_register(self, key: str, meta: Dict[str, Any]) -> None:
        self.metric_series[key] = meta
        self.mark_dirty("metric_series", key)  # 1 Hz debounced flush

    def _on_slo_transition(self, name: str, old: str, new: str,
                           burn: float) -> None:
        from ray_tpu.core import flight

        logger.warning("SLO %s: %s -> %s (burn %.2fx)", name, old, new, burn)
        if flight.enabled:
            flight.instant("slo", "slo.burn",
                           arg=f"{name}:{old}->{new}:burn={burn:.2f}")

    @property
    def address(self) -> str:
        return self._rpc.address

    async def start(self, serve_rpc: bool = True) -> None:
        """`serve_rpc=False` runs the full control plane — storage
        recovery, health loop, snapshot loop, every handler — without
        binding a TCP listener. core/simcluster.py uses it to drive N
        simulated raylets against this REAL server through in-process
        loopback dispatch."""
        self._load_storage()
        if self.replication is not None:
            # A rejoining replica votes with its recovered log position,
            # never as if its log were empty.
            self.replication.recover()
        # Re-pushed series after a restart must reuse their WAL-recovered
        # identity (no duplicate registration): seed the store with the
        # persisted metadata before the first heartbeat can arrive.
        self.metrics.adopt_metadata(self.metric_series)
        self._recover_slos()
        # Cluster identity: ephemeral ports get reused across test
        # clusters on one box, and a reconnecting client could silently
        # adopt a FOREIGN cluster that happens to listen on its cached
        # address. The id survives GCS restarts (persisted in kv) so
        # legitimate FT reconnects still pass the check (reference: the
        # cluster ID stamped into every GCS connection, gcs_client).
        import uuid

        cid = self.kv.get("__cluster_id__")
        if cid is not None:
            self.cluster_id = (cid.decode() if isinstance(cid, bytes)
                               else str(cid))
        elif self.replication is not None and self.replication.active:
            # Replicated boot: each replica generating its own id would
            # fork the cluster identity. The FIRST leader mints it with a
            # quorum-replicated write-through (_on_promoted); until then
            # the id is pending and cluster_id queries fail-and-retry.
            self.cluster_id = ""
        else:
            self.cluster_id = uuid.uuid4().hex
            self.kv["__cluster_id__"] = self.cluster_id.encode()
            self.mark_dirty("kv", "__cluster_id__")
        if serve_rpc:
            await self._rpc.start()
        self._health_task = asyncio.ensure_future(self._health_loop())
        if self._storage_path:
            self._snapshot_task = asyncio.ensure_future(
                self._snapshot_loop())
        # Crash-resume: a kill -9 mid-reschedule leaves groups
        # RESCHEDULING (the transition was written through); a crash
        # BEFORE the transition leaves a CREATED group pointing at a
        # node recovered as dead. Both resume here. (A follower replica
        # skips this — the scan is leader work, resumed at promotion.)
        await self._rescan_reschedules()
        if self.replication is not None:
            self.replication.start()
        if serve_rpc:
            logger.info("GCS listening on %s", self.address)

    async def handle_cluster_id(self, conn: ServerConnection) -> str:
        if not self.cluster_id:
            # Replicated boot before the first election: the id arrives
            # via the leader's quorum write. Pick it up if replication
            # delivered it; otherwise the client retries on its backoff.
            cid = self.kv.get("__cluster_id__")
            if cid is None:
                raise RuntimeError("cluster id pending leader election")
            self.cluster_id = (cid.decode() if isinstance(cid, bytes)
                               else str(cid))
        return self.cluster_id

    # -- durable storage (reference: gcs_table_storage.h over a store
    # client, redis_store_client.h's per-key writes). Incremental: each
    # flush appends only the mutated (table, key) records to a write-ahead
    # log; a full snapshot is written only when the WAL grows past
    # `gcs_wal_compact_bytes` (compaction), so flush cost is O(delta), not
    # O(cluster state). --------------------------------------------------
    # Nodes persist too (round 14): at 100 nodes, losing the membership
    # table on every GCS restart forced a full re-registration storm
    # before any scheduling could resume. Recovered records come back
    # with stale_view=True (resource view unconfirmed) and enjoy a
    # health-check grace window; a node's first post-restart heartbeat
    # reconciles the live view and clears the flag — no re-register RPC
    # needed, no herd.
    _PERSISTED_TABLES = ("nodes", "actors", "named_actors", "jobs",
                         "placement_groups", "kv", "metric_series",
                         "replication_meta")

    def mark_dirty(self, table: Optional[str] = None,
                   *keys: str) -> None:
        """Record mutated rows for the next flush. With no arguments the
        entire persisted state is marked (recovery/migration path)."""
        self._dirty = True
        if not self._storage_path:
            return  # nothing consumes the key set; don't grow it unbounded
        if table is None:
            for t in self._PERSISTED_TABLES:
                self._dirty_keys.update((t, k) for k in getattr(self, t))
        else:
            self._dirty_keys.update((table, k) for k in keys)

    async def flush_now(self) -> None:
        """Write-through for registration-class mutations (named actors,
        KV, jobs, PGs): the reference GCS acks only after the store
        client persisted (redis_store_client.h), so a crash must not
        lose an acked registration. High-churn updates (heartbeats,
        actor state transitions) stay on the 1 Hz debounce."""
        if not self._storage_path:
            return
        repl = self.replication
        if repl is not None and repl.active and not repl.is_leader():
            # A follower's tables mutate only through replicated frames;
            # anything dirty here is a leftover from a previous role and
            # must not fork the log.
            from ray_tpu.core.gcs.replication import NotLeaderError

            raise NotLeaderError(repl.leader_address(), repl.term)
        import pickle
        import struct

        my_gen = self._flush_gen
        async with self._flush_lock:
            if self._flushed_gen > my_gen:
                # A flush that STARTED after this caller's mutation (and
                # after it queued here) captured it AND hit disk: coalesce
                # instead of writing once per acked KV put. Comparing
                # against the successfully-WRITTEN generation matters —
                # coalescing on a failed overlapping write would ack a
                # mutation that never persisted.
                return
            gen = self._flush_gen = self._flush_gen + 1
            self._dirty = False
            keys = self._dirty_keys
            self._dirty_keys = set()
            if not keys:
                self._flushed_gen = gen
                return
            # Serialize ON the event loop: handlers can't mutate records
            # while we pickle, so no deep copy is needed and the writer
            # thread only ever touches immutable bytes.
            records = []
            for table, key in keys:
                if table == "replication_meta" and key == "vote":
                    # Raft hard state is per-replica and written through
                    # its own direct WAL path — it must never ride a
                    # replicated frame onto a follower.
                    continue
                tbl = getattr(self, table)
                records.append((table, key, key in tbl, tbl.get(key)))
            if repl is not None and repl.active:
                # Stamp the leader's (term, next index) into the frame:
                # followers persist it through the ordinary record path,
                # so every replica's WAL replay restores its log position.
                records.append(repl.stamp_record())
            payload = pickle.dumps(records, protocol=5)
            frame = struct.pack("<I", len(payload)) + payload
            try:
                await asyncio.to_thread(self._append_wal, frame)
                if repl is not None and repl.active:
                    # The leader acks a write-through only after a quorum
                    # holds the frame — the election's log-completeness
                    # criterion then guarantees no acked write is
                    # forgotten across failover (PG 2PC atomicity rides
                    # the same path).
                    await repl.commit(frame)
                self._flushed_gen = gen
            except Exception:
                self._dirty_keys |= keys
                self._dirty = True  # snapshot loop retries
                logger.warning("GCS write-through failed", exc_info=True)
                # Callers ack durability to their clients — a failed
                # write must surface as a failed mutation, not a silent
                # success that a crash then forgets.
                raise
            if self._wal_size >= ray_config().gcs_wal_compact_bytes:
                await self._compact()

    _SNAP_MAGIC = b"GSNP1\x00"

    async def _compact(self) -> None:
        """Fold the WAL into a fresh full snapshot. Caller holds
        _flush_lock, so no deltas append concurrently. Records are pickled
        on the loop in small batches with a yield between them, so the loop
        never stalls for the whole state (heartbeats keep flowing); a
        record mutated after its batch was serialized is in _dirty_keys
        and its delta lands in the (empty) WAL right after compaction.
        Crash between the snapshot rename and the WAL truncate is safe:
        replaying the stale WAL re-applies values the snapshot already
        contains."""
        import pickle
        import struct

        frames = [self._SNAP_MAGIC]
        for t in self._PERSISTED_TABLES:
            tbl = getattr(self, t)
            keys = list(tbl)
            for i in range(0, len(keys), 500):
                batch = [(t, k, True, tbl[k]) for k in keys[i:i + 500]
                         if k in tbl]
                payload = pickle.dumps(batch, protocol=5)
                frames.append(struct.pack("<I", len(payload)) + payload)
                await asyncio.sleep(0)
        blob = b"".join(frames)
        try:
            await asyncio.to_thread(self._write_snapshot_and_truncate, blob)
        except Exception:
            logger.warning("GCS compaction failed (WAL keeps growing)",
                           exc_info=True)

    def _load_storage(self) -> None:
        if not self._storage_path:
            return
        import os
        import pickle
        import struct

        if os.path.exists(self._storage_path):
            try:
                with open(self._storage_path, "rb") as f:
                    head = f.read(len(self._SNAP_MAGIC))
                    if head == self._SNAP_MAGIC:
                        # Framed snapshot (same record format as the WAL).
                        self._replay_frames(f, torn_ok=False)
                    else:
                        # Legacy single-pickle snapshot.
                        f.seek(0)
                        snap = pickle.load(f)
                        for table in self._PERSISTED_TABLES:
                            getattr(self, table).update(snap.get(table, {}))
            except Exception:
                logger.warning(
                    "GCS snapshot at %s unreadable; starting from WAL only",
                    self._storage_path, exc_info=True)
        # Replay the delta log over the snapshot. A torn tail (crash mid
        # append) ends the replay at the last complete frame — and the
        # file MUST then be truncated to that frame before _append_wal
        # reopens it in append mode: new fsynced+acked frames written
        # after a surviving partial frame would be unreachable to every
        # future replay (ADVICE r5 high: acked writes silently dropped
        # on the second restart).
        wal = self._wal_path()
        if os.path.exists(wal):
            with open(wal, "rb") as f:
                replayed, clean_end = self._replay_frames(f, torn_ok=True)
            wal_size = os.path.getsize(wal)
            if clean_end < wal_size:
                logger.warning(
                    "GCS WAL has a torn tail (%d of %d bytes replayable);"
                    " truncating before accepting new appends",
                    clean_end, wal_size)
                with open(wal, "r+b") as f:
                    f.truncate(clean_end)
                    f.flush()
                    os.fsync(f.fileno())
                wal_size = clean_end
            self._wal_size = wal_size
            if replayed:
                logger.info("GCS replayed %d WAL batches", replayed)
        # Recovered actor records point at pre-restart workers; their
        # liveness is re-established by owners / health checks. Recovered
        # NODE records carry a pre-crash resource view: mark them stale
        # (cleared by their first live heartbeat; pg_scheduler deprefers
        # stale views) and open the post-restart grace window so the
        # health loop cannot storm _mark_node_dead before the raylets
        # have had one full heartbeat interval to find us.
        recovered_alive = [n for n in self.nodes.values()
                           if n.get("alive")]
        if recovered_alive:
            cfg = ray_config()
            grace_ms = cfg.gcs_restart_node_grace_ms or (
                cfg.health_check_period_ms
                * cfg.health_check_failure_threshold)
            now = time.time()
            self._restart_grace_until = now + grace_ms / 1000.0
            for info in recovered_alive:
                info["stale_view"] = True
                # Seed the heartbeat clock at boot: a recovered node that
                # never reports again ages out of the grace window into a
                # normal missed-heartbeat death instead of living forever
                # on a missing dict entry.
                self._heartbeats.setdefault(info["node_id"], now)
        logger.info("GCS recovered %d actors, %d jobs, %d kv keys, "
                    "%d nodes (%d alive, grace %.1fs) from %s",
                    len(self.actors), len(self.jobs), len(self.kv),
                    len(self.nodes), len(recovered_alive),
                    max(0.0, self._restart_grace_until - time.time()),
                    self._storage_path)

    async def _snapshot_loop(self) -> None:
        while True:
            await asyncio.sleep(1.0)
            if not self._dirty:
                continue
            # flush_now serializes every writer through _flush_lock —
            # an unsynchronized periodic write could capture older tables
            # yet land over a newer write-through.
            try:
                await self.flush_now()
            except Exception:
                pass  # stays dirty; retried next tick

    def _replay_frames(self, f, torn_ok: bool):
        """Apply length-prefixed record batches from an open file. A torn
        tail (crash mid-append) ends a WAL replay at the last complete
        frame; in a snapshot it means corruption, so raise. Returns
        (frames_applied, offset_after_last_complete_frame) — the offset
        is what a WAL load truncates to."""
        import pickle
        import struct

        replayed = 0
        clean_end = f.tell()
        while True:
            hdr = f.read(4)
            if not hdr:
                break
            if len(hdr) < 4:
                if torn_ok:
                    break
                raise EOFError("truncated snapshot frame header")
            (n,) = struct.unpack("<I", hdr)
            payload = f.read(n)
            if len(payload) < n:
                if torn_ok:
                    break
                raise EOFError("truncated snapshot frame")
            try:
                records = pickle.loads(payload)
            except Exception:
                if torn_ok:
                    break
                raise
            for table, key, present, value in records:
                tbl = getattr(self, table, None)
                if tbl is None:
                    continue
                if present:
                    tbl[key] = value
                else:
                    tbl.pop(key, None)
            replayed += 1
            clean_end = f.tell()
        return replayed, clean_end

    def _wal_path(self) -> str:
        return f"{self._storage_path}.wal"

    def _append_wal(self, frame: bytes) -> None:
        import os

        if not self._storage_path:
            # Storage severed under us (simcluster kill -9: a flush
            # already past flush_now's entry check must fail, not land
            # in a stray file): surface as a failed write.
            raise OSError("GCS storage detached")
        with open(self._wal_path(), "ab") as f:
            f.write(frame)
            f.flush()
            os.fsync(f.fileno())
            self._wal_size = f.tell()

    def _write_snapshot_and_truncate(self, blob: bytes) -> None:
        import os
        import threading

        if not self._storage_path:
            raise OSError("GCS storage detached")

        # Unique tmp per writer: stop()'s final flush may overlap an
        # in-flight to_thread write; each renames atomically.
        tmp = (f"{self._storage_path}.tmp.{os.getpid()}"
               f".{threading.get_ident()}")
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._storage_path)
        with open(self._wal_path(), "wb") as f:
            f.flush()
            os.fsync(f.fileno())
        self._wal_size = 0

    async def stop(self) -> None:
        if self.replication is not None:
            self.replication.stop()
        if self._health_task:
            self._health_task.cancel()
        if self._snapshot_task:
            self._snapshot_task.cancel()
        for task in self._reschedule_tasks.values():
            task.cancel()
        self._reschedule_tasks.clear()
        for client in self._raylet_clients.values():
            try:
                await client.close()
            except Exception:
                pass
        self._raylet_clients.clear()
        if self._storage_path and self._dirty:
            # Final flush: acked mutations survive a clean shutdown
            # (through the same lock as every other writer).
            try:
                await self.flush_now()
            except Exception:
                pass  # already logged; shutdown must proceed
        await self._rpc.stop()

    # ------------------------------------------------------------------
    # HA replication (round 18; ray_tpu/core/gcs/replication.py)
    # ------------------------------------------------------------------
    # RPCs a follower replica serves locally. Everything else redirects
    # with NotLeaderError: reads included, so clients never observe a
    # stale follower view, and mutations included, so the replicated log
    # has exactly one writer per term.
    _FOLLOWER_LOCAL = frozenset((
        "ping", "cluster_id", "cluster_info", "metrics_stats",
        "dump_flight_record", "replicate_wal", "request_vote",
        "install_snapshot"))

    def check_dispatch(self, method: str) -> None:
        """Admission gate invoked by ServerConnection._dispatch before
        every handler (and therefore by the loopback sim path too)."""
        repl = self.replication
        if repl is None or not repl.active or repl.is_leader():
            return
        if method in self._FOLLOWER_LOCAL:
            return
        from ray_tpu.core.gcs.replication import NotLeaderError

        raise NotLeaderError(repl.leader_address(), repl.term)

    async def handle_replicate_wal(self, conn: ServerConnection, *,
                                   term: int, leader: str, index: int = 0,
                                   prev_term: Optional[int] = None,
                                   frame: Optional[bytes] = None
                                   ) -> Dict[str, Any]:
        return await self.replication.on_replicate(
            term=term, leader=leader, index=index, prev_term=prev_term,
            frame=frame)

    async def handle_request_vote(self, conn: ServerConnection, *,
                                  term: int, candidate: str,
                                  last_index: int, last_term: int
                                  ) -> Dict[str, Any]:
        return await self.replication.on_request_vote(
            term=term, candidate=candidate, last_index=last_index,
            last_term=last_term)

    async def handle_install_snapshot(self, conn: ServerConnection, *,
                                      term: int, leader: str, index: int,
                                      log_term: int, snapshot: bytes
                                      ) -> Dict[str, Any]:
        return await self.replication.on_install_snapshot(
            term=term, leader=leader, index=index, log_term=log_term,
            snapshot=snapshot)

    async def _on_promoted(self, term: int) -> None:
        """Election win: promotion is restart-equivalent recovery. The
        replicated tables are already ours; the SOFT state (heartbeat
        clocks, metric identities, SLO watchers, stuck reschedules)
        rebuilds through the same contracts a restarted GCS uses, and
        alive nodes get the same stale-view grace window so a failover
        never reads as mass node death."""
        cfg = ray_config()
        now = time.time()
        grace_ms = cfg.gcs_restart_node_grace_ms or (
            cfg.health_check_period_ms
            * cfg.health_check_failure_threshold)
        # Followers observed no heartbeats while the election ran (those
        # are leader-gated), so the silence clock owes the fleet the
        # election window too — otherwise a failover reads as node death.
        grace_ms += 2 * cfg.gcs_ha_lease_ms
        self._restart_grace_until = now + grace_ms / 1000.0
        for info in self.nodes.values():
            if info.get("alive"):
                info["stale_view"] = True
                self._heartbeats.setdefault(info["node_id"], now)
        self.metrics.adopt_metadata(self.metric_series)
        self._recover_slos()
        if not self.cluster_id:
            # A replica that never served a cluster_id RPC still has the
            # lazy "" sentinel even when the replicated kv already holds
            # the identity — adopt it. Minting a fresh id here would fork
            # the cluster identity at every failover and lock out every
            # client that cached the original (their reconnect identity
            # check would read the new leader as a foreign cluster).
            cid = self.kv.get("__cluster_id__")
            if cid is not None:
                self.cluster_id = (cid.decode() if isinstance(cid, bytes)
                                   else str(cid))
        if not self.cluster_id:
            # First leader of the cluster's life mints the identity with
            # a quorum write so every replica serves the same id.
            import uuid

            self.cluster_id = uuid.uuid4().hex
            self.kv["__cluster_id__"] = self.cluster_id.encode()
            self.mark_dirty("kv", "__cluster_id__")
            try:
                await self.flush_now()
            except Exception:
                logger.warning("cluster id write-through failed at "
                               "promotion; snapshot loop retries",
                               exc_info=True)
        await self._rescan_reschedules()

    # ------------------------------------------------------------------
    # health checking (reference: gcs_health_check_manager.h:39)
    # ------------------------------------------------------------------
    async def _health_loop(self) -> None:
        cfg = ray_config()
        period = cfg.health_check_period_ms / 1000.0
        threshold = cfg.health_check_failure_threshold
        while True:
            await asyncio.sleep(period)
            if (self.replication is not None and self.replication.active
                    and not self.replication.is_leader()):
                # Followers see no heartbeats (those are leader-gated):
                # a death verdict here would be judged on silence the
                # node never owed us. Health, reschedules and SLO eval
                # are leader work.
                continue
            now = time.time()
            for node_id, info in list(self.nodes.items()):
                if not info.get("alive"):
                    continue
                if (info.get("stale_view")
                        and now < self._restart_grace_until):
                    # Post-restart grace: this node was recovered from
                    # storage and has not re-confirmed yet — give it a
                    # full re-registration window before any death
                    # verdict (a restart must not read as 100
                    # simultaneous node failures).
                    continue
                last = self._heartbeats.get(node_id, now)
                if now - last > period * threshold:
                    logger.warning("node %s missed heartbeats; marking dead",
                                   node_id[:8])
                    await self._mark_node_dead(node_id)
            # Re-kick stuck reschedules + the mid-pass-race safety net
            # (one shared scan; see _rescan_reschedules).
            await self._rescan_reschedules()
            # SLO burn-rate evaluation rides this loop rather than its
            # own task: the simcluster kill -9 cancels a known task set,
            # and one more periodic scan does not deserve one more task.
            if self.slo.slos and (
                    now - self._slo_last_eval
                    >= cfg.slo_eval_period_ms / 1000.0):
                self._slo_last_eval = now
                try:
                    self.slo.evaluate(self.metrics, now=now)
                except Exception:
                    logger.warning("SLO evaluation failed", exc_info=True)

    async def _mark_node_dead(self, node_id: str) -> None:
        info = self.nodes.get(node_id)
        if info is None or not info.get("alive"):
            return
        info["alive"] = False
        info["end_time"] = time.time()
        self.mark_dirty("nodes", node_id)
        from ray_tpu.core import flight

        if flight.enabled:
            flight.instant("node", "node.dead", arg=node_id[:8])
        await self._publish("node", {
            "node_id": node_id, "alive": False,
            "address": (self.nodes.get(node_id) or {}).get("address")})
        # Fail actors that lived on the node.
        for actor_id, a in self.actors.items():
            if a.get("node_id") == node_id and a["state"] not in (
                    "DEAD",):
                a["state"] = "DEAD"
                a["death_cause"] = "node_died"
                await self._publish(f"actor:{actor_id}", a)
        # GCS-led PG rescheduling (round 15): a CREATED group with a
        # bundle on the dead node goes RESCHEDULING (write-through CAS)
        # and a recovery pass re-places only the lost bundles onto
        # survivors. Owner-led recovery is impossible here — the owner
        # may have died WITH the node. Same scan the health loop runs.
        await self._rescan_reschedules()

    # ------------------------------------------------------------------
    # GCS-led placement-group rescheduling (round 15; reference:
    # GcsPlacementGroupScheduler rescheduling on node removal)
    # ------------------------------------------------------------------
    async def _rescan_reschedules(self) -> None:
        """The one reschedule scan (start() crash-resume, health loop):
        RESCHEDULING groups get a live pass (stuck ones re-kick each
        period — new node registrations make yesterday's infeasible
        placement feasible), and CREATED groups naming a non-alive
        node re-begin. The CREATED check is the SAFETY NET for the
        mid-pass race: a node that dies while its group is already
        RESCHEDULING is skipped by _mark_node_dead's CREATED-only
        trigger, so the pass can land CREATED with a location table
        naming the fresh corpse — this scan heals it."""
        if (self.replication is not None and self.replication.active
                and not self.replication.is_leader()):
            return  # reschedule 2PC is leader work (resumed at promotion)
        for pg_id, pg in list(self.placement_groups.items()):
            state = pg.get("state")
            if state == "RESCHEDULING":
                self._spawn_reschedule(pg_id)
            elif state == "CREATED" and any(
                    not (self.nodes.get(loc.get("node_id")) or {})
                    .get("alive", False)
                    for loc in pg.get("bundle_locations") or []):
                await self._begin_reschedule(pg_id)

    async def _begin_reschedule(self, pg_id: str) -> None:
        """CAS a CREATED group to RESCHEDULING (write-through: the
        raylet reconciler must see the group still stands behind its
        surviving bundles across a GCS crash) and spawn the recovery
        pass."""
        ok = await self.handle_update_placement_group(
            None, pg_id=pg_id, updates={"state": "RESCHEDULING"},
            expect_state="CREATED")
        if ok:
            self._spawn_reschedule(pg_id)

    def _spawn_reschedule(self, pg_id: str) -> None:
        task = self._reschedule_tasks.get(pg_id)
        if task is not None and not task.done():
            return
        task = asyncio.ensure_future(self._reschedule_pg(pg_id))
        self._reschedule_tasks[pg_id] = task
        # Self-pruning: a finished pass must not pin its Task (frame,
        # locals) for the life of the process under PG churn.
        task.add_done_callback(
            lambda t, pg_id=pg_id: (
                self._reschedule_tasks.pop(pg_id, None)
                if self._reschedule_tasks.get(pg_id) is t else None))

    async def _reschedule_pg(self, pg_id: str) -> None:
        from ray_tpu.core.pg_scheduler import reschedule_placement_group

        try:
            state = await reschedule_placement_group(
                self._local_accessor(), self._raylet_client_for, pg_id)
            if state == "RESCHEDULING":
                logger.warning(
                    "placement group %s still RESCHEDULING after every "
                    "attempt (no feasible placement); the health loop "
                    "re-kicks when the cluster changes", pg_id[:8])
        except Exception:
            logger.warning("pg %s reschedule pass crashed", pg_id[:8],
                           exc_info=True)

    def _local_accessor(self) -> Any:
        """What `reschedule_placement_group` needs from 'the GCS' — the
        same three accessors the owner-side 2PC uses, served from our
        own tables so the protocol definition stays shared."""
        server = self

        class _Accessor:
            async def get_placement_group(self, pg_id):
                return server.placement_groups.get(pg_id)

            async def get_nodes(self):
                return list(server.nodes.values())

            async def update_placement_group(self, pg_id, updates,
                                             expect_state=None):
                return await server.handle_update_placement_group(
                    None, pg_id=pg_id, updates=updates,
                    expect_state=expect_state)

        return _Accessor()

    async def _raylet_client_for(self, address: str) -> Any:
        """Outbound raylet client for the reschedule 2PC. The sim
        harness injects `raylet_client_factory` to route through its
        fault plan; production dials (and caches) a real RpcClient."""
        if self.raylet_client_factory is not None:
            return self.raylet_client_factory(address)
        from ray_tpu.core.rpc import RpcClient

        client = self._raylet_clients.get(address)
        if client is None or not client.connected:
            if client is not None:
                # Replace-without-close leaks the dead client's
                # transport on every raylet flap.
                try:
                    await client.close()
                except Exception:
                    pass
            client = RpcClient(address)
            await client.connect(timeout=5.0)
            self._raylet_clients[address] = client
        return client

    # ------------------------------------------------------------------
    # pubsub
    # ------------------------------------------------------------------
    async def _publish(self, channel: str, data: Any) -> None:
        # Typed pubsub envelope (core/wire.py PubsubMessage): per-channel
        # delivery sequence numbers let subscribers detect drops; the
        # client unwraps centrally so channel handlers see plain data.
        from ray_tpu.core.wire import PubsubMessage, to_wire

        seq = self._pub_seq[channel] = self._pub_seq.get(channel, 0) + 1
        frame = to_wire(PubsubMessage(channel=channel, data=data, seq=seq))
        for conn in list(self._subs.get(channel, ())):
            if conn.closed:
                self._subs[channel].discard(conn)
            else:
                await conn.push(channel, frame)

    async def handle_subscribe(self, conn: ServerConnection, *,
                               channel: str) -> bool:
        self._subs.setdefault(channel, set()).add(conn)
        conn.metadata.setdefault("channels", set()).add(channel)
        return True

    async def handle_unsubscribe(self, conn: ServerConnection, *,
                                 channel: str) -> bool:
        self._subs.get(channel, set()).discard(conn)
        return True

    async def handle_publish(self, conn: ServerConnection, *, channel: str,
                             data: Any) -> bool:
        await self._publish(channel, data)
        return True

    async def on_client_disconnect(self, conn: ServerConnection) -> None:
        for channel in conn.metadata.get("channels", ()):
            self._subs.get(channel, set()).discard(conn)
        node_id = conn.metadata.get("node_id")
        if node_id:
            await self._mark_node_dead(node_id)
        worker_id = conn.metadata.get("worker_id")
        if worker_id and worker_id in self.workers:
            self.workers[worker_id]["alive"] = False

    # ------------------------------------------------------------------
    # nodes (reference: GcsNodeManager + NodeInfoGcsService)
    # ------------------------------------------------------------------
    async def handle_register_node(self, conn: ServerConnection, *,
                                   node: Optional[dict] = None,
                                   node_id: str = "", address: str = "",
                                   object_store_address: str = "",
                                   resources: Optional[Dict[str, float]]
                                   = None,
                                   labels: Optional[Dict[str, str]] = None,
                                   is_head: bool = False) -> Dict[str, Any]:
        if node is not None:
            from ray_tpu.core.wire import from_wire

            n = from_wire(node, expect="NodeInfo")
            node_id, address = n.node_id, n.address
            object_store_address = n.object_store_address or address
            resources, labels = n.resources, n.labels
            is_head = n.is_head
        resources = resources or {}
        labels = labels or {}
        # A node re-registering after WE declared it dead must be told:
        # the cluster already restarted its actors and reconstructed its
        # objects elsewhere, so its surviving actor workers are stale.
        was_dead = (node_id in self.nodes
                    and not self.nodes[node_id].get("alive", True))
        self.nodes[node_id] = {
            "node_id": node_id,
            "address": address,
            "object_store_address": object_store_address,
            "resources_total": resources,
            "resources_available": dict(resources),
            "labels": labels,
            "alive": True,
            "is_head": is_head,
            "start_time": time.time(),
        }
        self._heartbeats[node_id] = time.time()
        conn.metadata["node_id"] = node_id
        self.mark_dirty("nodes", node_id)
        await self._publish("node", {"node_id": node_id, "alive": True})
        return {"ok": True, "was_dead": was_dead}

    async def handle_heartbeat(self, conn: ServerConnection, *, node_id: str,
                               resources_available: Dict[str, float],
                               load: Optional[Dict[str, Any]] = None,
                               metrics: Optional[List[Dict[str, Any]]] = None,
                               workers: Optional[List[Dict[str, Any]]] = None,
                               ) -> bool:
        info = self.nodes.get(node_id)
        if info is None or not info.get("alive", False):
            # Unknown (registration lost with an unpersisted crash) or
            # previously declared dead: the raylet must re-register
            # before its heartbeats count (GCS FT re-registration
            # contract — raylet re-registers on a False reply).
            return False
        self._heartbeats[node_id] = time.time()
        info["resources_available"] = resources_available
        # First heartbeat after a restart reconciles the recovered
        # record: the live view replaces the persisted snapshot.
        info.pop("stale_view", None)
        # Bind the node to this connection so a post-restart disconnect
        # still marks it dead promptly — recovered nodes never re-call
        # register_node, which is where the binding used to happen.
        conn.metadata["node_id"] = node_id
        if load is not None:
            info["load"] = load
        if metrics:
            # The node's coalesced metrics push rides the heartbeat — one
            # RPC per node per interval, whatever the worker count.
            try:
                self.metrics.ingest(
                    metrics, extra_labels={"node_id": node_id[:8]})
            except Exception:
                logger.warning("bad metrics batch from %s",
                               node_id[:8], exc_info=True)
        if workers is not None:
            # Batched per-worker state (ROADMAP 4d): the raylet folds its
            # whole worker table into the node heartbeat — one RPC per
            # tick, not one per worker — and the records land as SOFT
            # state (not in _PERSISTED_TABLES), so worker churn never
            # touches the quorum-replicated write path.
            now = time.time()
            seen = set()
            for w in workers:
                wid = w.get("worker_id")
                if not wid:
                    continue
                seen.add(wid)
                self.workers[wid] = dict(
                    w, node_id=node_id, alive=True, last_seen=now)
            for wid, info in list(self.workers.items()):
                if info.get("node_id") == node_id and wid not in seen:
                    # Absent from its raylet's batch: the worker exited
                    # (the raylet reports its whole live table each tick).
                    del self.workers[wid]
        return True

    async def handle_get_nodes(self, conn: ServerConnection,
                               ) -> List[Dict[str, Any]]:
        return list(self.nodes.values())

    async def handle_drain_node(self, conn: ServerConnection, *,
                                node_id: str) -> bool:
        await self._mark_node_dead(node_id)
        return True

    # ------------------------------------------------------------------
    # actors (reference: GcsActorManager; lifecycle gcs_actor_manager.h:251)
    # ------------------------------------------------------------------
    async def handle_register_actor(self, conn: ServerConnection, *,
                                    actor_id: str, info: Dict[str, Any]
                                    ) -> Dict[str, Any]:
        if isinstance(info, dict) and "_t" in info:
            # Typed decode (core/wire.py ActorInfo): malformed peers fail
            # here with a WireDecodeError naming the bad field; the table
            # stores the validated plain record.
            from ray_tpu.core.wire import from_wire

            info = from_wire(info, expect="ActorInfo").as_dict()
        name = info.get("name")
        ns = info.get("namespace") or "default"
        if name:
            key = f"{ns}/{name}"
            existing = self.named_actors.get(key)
            if existing == actor_id:
                pass  # at-least-once retry of our own registration
            elif existing is not None:
                state = self.actors.get(existing, {}).get("state")
                if state not in ("DEAD", None):
                    return {"ok": False,
                            "error": f"actor name '{name}' already taken in "
                                     f"namespace '{ns}'"}
            self.named_actors[key] = actor_id
            self.mark_dirty("named_actors", key)
        self.mark_dirty("actors", actor_id)
        info = dict(info, actor_id=actor_id, state=info.get("state",
                                                            "PENDING"))
        self.actors[actor_id] = info
        await self._publish(f"actor:{actor_id}", info)
        if name:
            # Only NAMED registrations are looked up after a restart;
            # anonymous actors ride the 1 Hz debounce (a full-table
            # snapshot per short-lived actor would serialize creation).
            await self.flush_now()
        return {"ok": True}

    async def handle_update_actor(self, conn: ServerConnection, *,
                                  actor_id: str,
                                  updates: Dict[str, Any]) -> bool:
        info = self.actors.get(actor_id)
        if info is None:
            return False
        info.update(updates)
        self.mark_dirty("actors", actor_id)
        await self._publish(f"actor:{actor_id}", info)
        if info.get("state") == "DEAD":
            name = info.get("name")
            ns = info.get("namespace") or "default"
            # A restartable actor keeps its name through death: its owner
            # may revive it (reference: gcs_actor_manager.h RESTARTING
            # keeps the registration). Intentional kills and
            # non-restartable actors free the name immediately.
            restartable = (info.get("max_restarts", 0) != 0
                           and updates.get("death_cause") != "ray.kill")
            if (name and not restartable
                    and self.named_actors.get(f"{ns}/{name}") == actor_id):
                del self.named_actors[f"{ns}/{name}"]
                self.mark_dirty("named_actors", f"{ns}/{name}")
        return True

    async def handle_get_actor(self, conn: ServerConnection, *,
                               actor_id: Optional[str] = None,
                               name: Optional[str] = None,
                               namespace: str = "default"
                               ) -> Optional[Dict[str, Any]]:
        if actor_id is None and name is not None:
            actor_id = self.named_actors.get(f"{namespace}/{name}")
        if actor_id is None:
            return None
        return self.actors.get(actor_id)

    async def handle_list_actors(self, conn: ServerConnection
                                 ) -> List[Dict[str, Any]]:
        return list(self.actors.values())

    # ------------------------------------------------------------------
    # jobs (reference: GcsJobManager)
    # ------------------------------------------------------------------
    async def handle_add_job(self, conn: ServerConnection, *, job_id: str,
                             info: Dict[str, Any]) -> bool:
        if isinstance(info, dict) and "_t" in info:
            from ray_tpu.core.wire import from_wire

            info = from_wire(info, expect="JobInfo").as_dict()
        self.jobs[job_id] = dict(info, job_id=job_id,
                                 start_time=time.time())
        self.mark_dirty("jobs", job_id)
        return True

    async def handle_get_job(self, conn: ServerConnection, *,
                             job_id: str) -> Optional[Dict[str, Any]]:
        return self.jobs.get(job_id)

    async def handle_mark_job_finished(self, conn: ServerConnection, *,
                                       job_id: str) -> bool:
        if job_id in self.jobs:
            self.jobs[job_id]["finished"] = True
            self.jobs[job_id]["end_time"] = time.time()
            self.mark_dirty("jobs", job_id)
        # Non-detached actors die with their job (reference:
        # GcsActorManager::OnJobFinished); raylets subscribe and reap
        # their local actor workers. Detached actors survive.
        for actor_id, info in list(self.actors.items()):
            if (info.get("job_id") == job_id
                    and not info.get("detached")
                    and info.get("state") not in ("DEAD",)):
                info["state"] = "DEAD"
                info["death_cause"] = "job finished"
                self.mark_dirty("actors", actor_id)
                await self._publish(f"actor:{actor_id}", info)
        await self._publish("job", {"job_id": job_id, "finished": True})
        return True

    async def handle_list_jobs(self, conn: ServerConnection
                               ) -> List[Dict[str, Any]]:
        return list(self.jobs.values())

    # ------------------------------------------------------------------
    # task events (reference: GcsTaskManager + task_event_buffer flushes)
    # ------------------------------------------------------------------
    async def handle_add_task_events(self, conn: ServerConnection, *,
                                     events: List[Dict[str, Any]]) -> bool:
        self.task_events.extend(events)
        return True

    async def handle_get_task_events(
            self, conn: ServerConnection, *,
            job_id: Optional[str] = None) -> List[Dict[str, Any]]:
        events = list(self.task_events)
        if job_id is not None:
            events = [e for e in events if e.get("job_id") == job_id]
        return events

    # ------------------------------------------------------------------
    # internal KV (reference: GcsKvManager / InternalKV service)
    # ------------------------------------------------------------------
    async def handle_kv_put(self, conn: ServerConnection, *, key: bytes,
                            value: bytes, overwrite: bool = True) -> bool:
        k = key.decode() if isinstance(key, bytes) else key
        if not overwrite and k in self.kv:
            # Equal value => treat as an at-least-once retry of the put
            # that already won (the client may never have seen the ack).
            return self.kv[k] == value
        self.kv[k] = value
        self.mark_dirty("kv", k)
        await self.flush_now()  # KV acks are durable (Serve state, etc.)
        return True

    async def handle_kv_get(self, conn: ServerConnection, *,
                            key: bytes) -> Optional[bytes]:
        k = key.decode() if isinstance(key, bytes) else key
        return self.kv.get(k)

    async def handle_kv_del(self, conn: ServerConnection, *,
                            key: bytes) -> bool:
        k = key.decode() if isinstance(key, bytes) else key
        existed = self.kv.pop(k, None) is not None
        self.mark_dirty("kv", k)
        await self.flush_now()
        return existed

    async def handle_kv_keys(self, conn: ServerConnection, *,
                             prefix: str) -> List[str]:
        return [k for k in self.kv if k.startswith(prefix)]

    async def handle_kv_exists(self, conn: ServerConnection, *,
                               key: bytes) -> bool:
        k = key.decode() if isinstance(key, bytes) else key
        return k in self.kv

    # ------------------------------------------------------------------
    # placement groups (table only; 2PC runs between owner and raylets)
    # ------------------------------------------------------------------
    async def handle_register_placement_group(
            self, conn: ServerConnection, *, pg_id: str,
            info: Dict[str, Any]) -> bool:
        self.placement_groups[pg_id] = dict(info, pg_id=pg_id)
        self.mark_dirty("placement_groups", pg_id)
        # Write-through: the registered record is what raylet-side
        # bundle reconciliation trusts after a crash — a PG whose
        # registration died with the debounce would read as "lost" and
        # have its half-prepared bundles returned while the owner still
        # believes it is scheduling (2PC atomicity, ISSUE 14).
        await self.flush_now()
        return True

    async def handle_update_placement_group(
            self, conn: ServerConnection, *, pg_id: str,
            updates: Dict[str, Any],
            expect_state: Optional[str] = None) -> bool:
        """`expect_state` makes the update conditional (CAS): the async
        owner-side scheduler must not resurrect a REMOVED group."""
        info = self.placement_groups.get(pg_id)
        if info is None:
            return False
        if expect_state is not None and info.get("state") != expect_state:
            return False
        info.update(updates)
        self.mark_dirty("placement_groups", pg_id)
        await self._publish(f"pg:{pg_id}", info)
        if updates.get("state") in ("CREATED", "REMOVED", "INFEASIBLE",
                                    "RESCHEDULING"):
            # Terminal transitions are registration-class (see
            # flush_now docstring): an acked CREATED that a kill -9
            # forgets would leave committed bundles pointing at a
            # PENDING ghost after restart — exactly the half-reserved
            # state the chaos test forbids. RESCHEDULING writes through
            # too: the recovery pass must resume (not vanish) across a
            # GCS crash, and the raylet reconciler must keep standing
            # behind the surviving bundles it reads this state for.
            await self.flush_now()
        return True

    async def handle_get_placement_group(
            self, conn: ServerConnection, *,
            pg_id: str) -> Optional[Dict[str, Any]]:
        return self.placement_groups.get(pg_id)

    async def handle_list_placement_groups(
            self, conn: ServerConnection) -> List[Dict[str, Any]]:
        return list(self.placement_groups.values())

    # ------------------------------------------------------------------
    # metrics pipeline + SLOs (round 17 observability)
    # ------------------------------------------------------------------
    async def handle_query_metrics(
            self, conn: ServerConnection, *, series: str,
            window_s: float = 60.0, agg: str = "raw",
            labels: Optional[Dict[str, str]] = None,
            group_by: Optional[List[str]] = None) -> Dict[str, Any]:
        return self.metrics.query(series, window_s=float(window_s),
                                  agg=agg, labels=labels, group_by=group_by)

    async def handle_latest_metrics(self, conn: ServerConnection
                                    ) -> List[Dict[str, Any]]:
        """The latest cluster-wide fold, registry-snapshot shaped (what
        the dashboard renders as Prometheus text at GET /metrics)."""
        return self.metrics.latest_fold()

    async def handle_metrics_stats(self, conn: ServerConnection
                                   ) -> Dict[str, Any]:
        return self.metrics.stats()

    async def handle_register_slo(self, conn: ServerConnection, *,
                                  spec: Dict[str, Any]) -> Dict[str, Any]:
        spec = self.slo.register(dict(spec))
        # Specs are cheap and declarative — persist them in kv so a
        # restarted GCS keeps watching the same objectives.
        import json

        self.kv[f"__slo__/{spec['name']}"] = json.dumps(spec).encode()
        self.mark_dirty("kv", f"__slo__/{spec['name']}")
        await self.flush_now()
        return spec

    async def handle_remove_slo(self, conn: ServerConnection, *,
                                name: str) -> bool:
        self.kv.pop(f"__slo__/{name}", None)
        self.mark_dirty("kv", f"__slo__/{name}")
        return self.slo.remove(name)

    async def handle_get_slo(self, conn: ServerConnection
                             ) -> List[Dict[str, Any]]:
        return self.slo.status(self.metrics)

    def _recover_slos(self) -> None:
        import json

        for k, v in self.kv.items():
            if not k.startswith("__slo__/"):
                continue
            try:
                self.slo.register(json.loads(
                    v.decode() if isinstance(v, bytes) else v))
            except Exception:
                logger.warning("unreadable persisted SLO %s", k,
                               exc_info=True)

    async def handle_dump_flight_record(
            self, conn: ServerConnection, *,
            window_s: Optional[float] = None,
            include_events: bool = True) -> Dict[str, Any]:
        """The GCS's own flight ring (slo.burn, node.dead, ...), shaped
        like the raylet's dump handler so the dashboard merge code can
        treat the GCS as one more source on /api/timeline."""
        from ray_tpu.core import flight

        if not flight.enabled:
            return {"node_id": "gcs", "records": []}
        return {"node_id": "gcs",
                "records": [flight.dump(window_s=window_s,
                                        include_events=include_events)]}

    # ------------------------------------------------------------------
    # misc
    # ------------------------------------------------------------------
    async def handle_ping(self, conn: ServerConnection) -> str:
        return "pong"

    async def handle_cluster_info(self, conn: ServerConnection
                                  ) -> Dict[str, Any]:
        info = {
            "address": self.address,
            "cluster_id": self.cluster_id,
            "uptime": time.time() - self._start_time,
            "num_nodes": sum(1 for n in self.nodes.values() if n["alive"]),
            "num_workers": len(self.workers),
        }
        if self.replication is not None:
            # Served by followers too (_FOLLOWER_LOCAL): the dashboard
            # and failover clients may be pointed at any replica and
            # still learn who leads and how far replication lags.
            info["ha"] = self.replication.status()
        return info


def main() -> None:
    """`python -m ray_tpu.core.gcs.server --port P` — standalone GCS."""
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--storage", default=None,
                        help="snapshot file for GCS fault tolerance; "
                             "restart with the same path to recover "
                             "tables")
    parser.add_argument("--replica-id", default=None,
                        help="this replica's id in an HA replica set "
                             "(e.g. gcs0); requires --peers and --storage")
    parser.add_argument("--peers", default=None,
                        help="comma-separated id=host:port for the OTHER "
                             "replicas (e.g. gcs1=10.0.0.2:6380,"
                             "gcs2=10.0.0.3:6380)")
    args = parser.parse_args()

    logging.basicConfig(level=logging.INFO)

    from ray_tpu.core import flight

    if flight.enabled:
        # The standalone GCS is a flight source too: slo.burn and
        # node.dead events merge onto /api/timeline next to the stalls
        # that caused them (the dashboard scrapes dump_flight_record).
        flight.set_role("gcs")

    async def run():
        server = GcsServer(args.host, args.port,
                           storage_path=args.storage)
        if args.replica_id:
            if not (args.peers and args.storage):
                parser.error("--replica-id requires --peers and --storage")
            from ray_tpu.core.gcs.replication import Replication

            peer_addrs = dict(p.split("=", 1)
                              for p in args.peers.split(",") if p)
            peer_addrs[args.replica_id] = f"{args.host}:{args.port}"
            server.replication = Replication(
                server, args.replica_id, sorted(peer_addrs),
                peer_addrs=peer_addrs)
        await server.start()
        print(f"GCS_ADDRESS={server.address}", flush=True)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
