"""Owner-side lineage bookkeeping + the reconstruction decision.

Reference equivalent: `src/ray/core_worker/task_manager.h` (lineage
pinning, `RetryTaskIfPossible`) + `object_recovery_manager.h` — the
owner of an object retains the wire-encoded spec of the task that
produced it (and pins that task's argument objects) for as long as any
return ref lives, so a lost copy can be recovered by re-executing the
task instead of failing the borrower's `get()`.

This module holds the POLICY half — retention gating, the bounded
per-object re-execution budget, inflight dedup, live-ref accounting —
factored out of `ClusterRuntime` so `core/simcluster.py` drives the
IDENTICAL state machine at 100 simulated nodes under seeded fault
schedules. The IO half (resetting owner entries to pending, resubmitting
through the dispatch tiers) stays with each consumer: the runtime
resubmits real wire specs, the sim re-runs simulated producer tasks.

The `spec` a record carries is opaque to the table: the production
runtime stores the lazily wire-encoded TaskSpec dict, the sim harness a
producer descriptor.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.config import ray_config

logger = logging.getLogger(__name__)

# begin_reexec verdicts
STARTED = "started"          # budget charged; caller must re-execute
INFLIGHT = "inflight"        # a re-execution is already running
EXHAUSTED = "exhausted"      # budget spent: degrade to ObjectLostError
UNRETAINED = "unretained"    # no lineage (flag off, or ref released)


class LineageTable:
    """Return-oid -> shared producing-task record. One record per task,
    indexed under every return oid; released when the last return ref
    is freed (the caller then unpins the record's argument objects)."""

    def __init__(self):
        self._records: Dict[str, dict] = {}
        # Recovery throughput counters (surfaced by stats()).
        self.reexecs = 0
        self.exhausted = 0

    def __len__(self) -> int:
        # Distinct records, not index entries (multi-return tasks index
        # one record N times).
        return len({id(r) for r in self._records.values()})

    @staticmethod
    def enabled() -> bool:
        return bool(ray_config().lineage_reconstruction)

    def retain(self, ref_oids: List[str], spec: Any, pinned: List[Any],
               budget: int) -> Optional[dict]:
        """Retain `spec` for the task whose returns are `ref_oids`.
        Returns the record, or None when lineage reconstruction is
        disabled (the caller then releases its arg pins normally).
        `budget` is the per-object re-execution allowance — bounded by
        `lineage_reconstruction_budget` so a max_retries=-1 style
        request can never re-execute unboundedly."""
        if not self.enabled():
            return None
        cap = max(0, int(ray_config().lineage_reconstruction_budget))
        if budget < 0:
            budget = cap
        rec = {
            "spec": spec,
            "ref_oids": list(ref_oids),
            "pinned": pinned,
            "left": min(max(int(budget), 0), cap),
            "live": len(ref_oids),
            "inflight": False,
        }
        for oid in ref_oids:
            self._records[oid] = rec
        return rec

    def get(self, oid: str) -> Optional[dict]:
        return self._records.get(oid)

    def release(self, oid: str) -> Optional[List[Any]]:
        """One return ref was freed. Returns the record's pinned arg
        list when this was the LAST live ref (the caller unpins), else
        None."""
        rec = self._records.pop(oid, None)
        if rec is None:
            return None
        rec["live"] -= 1
        if rec["live"] <= 0:
            pinned, rec["pinned"] = rec["pinned"], []
            return pinned
        return None

    def drop_record(self, rec: dict) -> List[Any]:
        """Drop a whole record early (every result landed inline: the
        owner future holds the values, nothing is ever losable).
        Returns the pinned arg list for the caller to unpin."""
        for oid in rec["ref_oids"]:
            if self._records.get(oid) is rec:
                del self._records[oid]
        rec["live"] = 0
        pinned, rec["pinned"] = rec["pinned"], []
        return pinned

    def begin_reexec(self, oid: str) -> Tuple[str, Optional[dict]]:
        """The reconstruction decision for one lost object: STARTED
        charges the budget and flags the record inflight (the caller
        MUST call end_reexec when the re-execution settles); INFLIGHT
        means keep waiting; EXHAUSTED/UNRETAINED mean the loss is
        final and the typed error stands."""
        rec = self._records.get(oid)
        if rec is None:
            return (UNRETAINED, None)
        if rec["inflight"]:
            return (INFLIGHT, rec)
        if rec["left"] <= 0:
            self.exhausted += 1
            return (EXHAUSTED, rec)
        rec["inflight"] = True
        rec["left"] -= 1
        self.reexecs += 1
        from ray_tpu.core import flight

        if flight.enabled:
            name = (rec["spec"].get("name")
                    if isinstance(rec["spec"], dict) else str(rec["spec"]))
            flight.instant("lineage", "lineage.reexec",
                           arg=f"{name} left={rec['left']}")
        return (STARTED, rec)

    def end_reexec(self, rec: dict) -> None:
        rec["inflight"] = False

    def stats(self) -> Dict[str, int]:
        return {"retained": len(self), "reexecs": self.reexecs,
                "exhausted": self.exhausted}
