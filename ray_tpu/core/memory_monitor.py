"""Node memory monitor + worker-killing policy (OOM defense).

Reference equivalent: `src/ray/common/memory_monitor.h:52` (threshold
sampling of /proc + cgroup limits) and
`src/ray/raylet/worker_killing_policy.h:34` (pick a victim worker instead
of letting the kernel OOM-kill the raylet). Policy here mirrors the
reference's retriable-FIFO default with the group-by-owner tie-break:
kill the NEWEST leased task first (its lost work is smallest and it is
retriable), preferring owners with multiple running tasks so no caller
is starved completely.

The monitor is process-agnostic: the raylet feeds it candidate workers
and it returns victims; killing and the retriable OutOfMemoryError reply
stay in the raylet.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

_CGROUP_V2_ROOT = "/sys/fs/cgroup"


def _read_int(path: str) -> Optional[int]:
    try:
        with open(path) as f:
            raw = f.read().strip()
        return None if raw == "max" else int(raw)
    except (OSError, ValueError):
        return None


def node_memory_usage() -> Tuple[int, int]:
    """(used_bytes, total_bytes) for this node.

    cgroup-v2 limits win over /proc/meminfo when present (containers:
    the box's meminfo lies about what WE may use — reference:
    memory_monitor.cc GetMemoryBytes cgroup handling)."""
    cg_limit = _read_int(f"{_CGROUP_V2_ROOT}/memory.max")
    cg_used = _read_int(f"{_CGROUP_V2_ROOT}/memory.current")
    if cg_limit and cg_used is not None:
        return cg_used, cg_limit
    total = avail = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
    except OSError:
        pass
    if total is None or avail is None:
        return 0, 1
    return total - avail, total


def process_rss(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


@dataclass
class WorkerCandidate:
    """What the killing policy needs to know about one leased worker."""

    worker_id: str
    pid: int
    task_id: Optional[str]
    owner_address: Optional[str]   # task submitter (group-by-owner)
    granted_at: float              # lease grant time (newest dies first)
    retriable: bool = True


def pick_victim(candidates: Sequence[WorkerCandidate]
                ) -> Optional[WorkerCandidate]:
    """Reference policy composition (worker_killing_policy.h): prefer
    retriable tasks; among those, group by owner and take the newest
    task of the owner with the MOST running tasks (that owner keeps
    making progress on its older tasks); fall back to the newest
    non-retriable task only when nothing is retriable."""
    if not candidates:
        return None
    retriable = [c for c in candidates if c.retriable]
    pool = retriable or list(candidates)
    by_owner: dict = {}
    for c in pool:
        by_owner.setdefault(c.owner_address, []).append(c)
    owner, tasks = max(by_owner.items(),
                       key=lambda kv: (len(kv[1]),
                                       max(c.granted_at for c in kv[1])))
    return max(tasks, key=lambda c: c.granted_at)


class MemoryMonitor:
    """Threshold sampler. `tick()` returns the victim to kill (or None);
    the caller owns the actual kill + retry semantics."""

    def __init__(self,
                 usage_threshold: float,
                 candidates_fn: Callable[[], List[WorkerCandidate]],
                 usage_fn: Callable[[], Tuple[int, int]] =
                 node_memory_usage,
                 min_kill_interval_s: float = 1.0):
        self.usage_threshold = usage_threshold
        self._candidates_fn = candidates_fn
        self._usage_fn = usage_fn
        self._min_kill_interval_s = min_kill_interval_s
        self._last_kill = 0.0
        self.last_usage_fraction = 0.0

    def tick(self) -> Optional[WorkerCandidate]:
        used, total = self._usage_fn()
        if total <= 0:
            return None
        frac = self.last_usage_fraction = used / total
        if frac < self.usage_threshold:
            return None
        if time.monotonic() - self._last_kill < self._min_kill_interval_s:
            return None  # give the last kill time to free memory
        victim = pick_victim(self._candidates_fn())
        if victim is not None:
            self._last_kill = time.monotonic()
            logger.warning(
                "memory usage %.1f%% >= %.1f%%: killing worker %s "
                "(task %s, rss %.0f MB) to protect the node",
                frac * 100, self.usage_threshold * 100,
                victim.worker_id[:8], (victim.task_id or "?")[:12],
                process_rss(victim.pid) / 1e6)
        return victim
