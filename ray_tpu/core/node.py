"""Node process supervisor: spawns and babysits GCS + raylet.

Reference equivalent: `python/ray/_private/node.py:38` (`Node`,
`start_gcs_server :1103`, `start_raylet :1134`, `start_head_processes
:1300`). Session layout mirrors the reference: a per-session directory with
process logs.
"""

from __future__ import annotations

import atexit
import json
import os
import re
import subprocess
import sys
import time
from typing import Dict, Optional

from ray_tpu.core.ids import NodeID


def detect_node_resources(num_cpus: Optional[int] = None,
                          num_gpus: Optional[int] = None,
                          resources: Optional[Dict[str, float]] = None
                          ) -> Dict[str, float]:
    """CPU/memory autodetection plus TPU chips as a first-class resource
    (reference: _private/accelerators/tpu.py — but pod-aware here)."""
    out: Dict[str, float] = {}
    out["CPU"] = float(num_cpus if num_cpus is not None
                       else (os.cpu_count() or 1))
    if num_gpus:
        out["GPU"] = float(num_gpus)
    try:
        import psutil
        out["memory"] = float(psutil.virtual_memory().available)
    except Exception:
        out["memory"] = 4e9
    try:
        from ray_tpu.parallel.tpu import local_tpu_resources
        out.update(local_tpu_resources())
    except Exception:
        pass
    out.update(resources or {})
    return out


def _wait_for_line(proc: subprocess.Popen, pattern: str,
                   timeout: float = 30.0) -> str:
    """Read stdout lines until one matches `pattern`; returns the match."""
    regex = re.compile(pattern)
    deadline = time.time() + timeout
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"process exited with code {proc.returncode} before "
                    f"printing {pattern!r}")
            time.sleep(0.05)
            continue
        text = line.decode(errors="replace").strip()
        m = regex.search(text)
        if m:
            return m.group(1)
    raise TimeoutError(f"timed out waiting for {pattern!r}")


class NodeSupervisor:
    def __init__(self, session_dir: str):
        self.session_dir = session_dir
        self.log_dir = os.path.join(session_dir, "logs")
        os.makedirs(self.log_dir, exist_ok=True)
        self.processes: Dict[str, subprocess.Popen] = {}
        self.gcs_address: Optional[str] = None
        self.raylet_address: Optional[str] = None
        self.dashboard_address: Optional[str] = None
        self.node_id: Optional[str] = None
        atexit.register(self.stop)

    # -- head bring-up (reference: node.py start_head_processes) ---------
    @classmethod
    def start_head(cls, num_cpus=None, num_gpus=None, resources=None,
                   object_store_memory=None,
                   session_root: str = "/tmp/ray_tpu_sessions",
                   include_dashboard: bool = True) -> "NodeSupervisor":
        session_dir = os.path.join(
            session_root, f"session_{time.strftime('%Y%m%d-%H%M%S')}_"
                          f"{os.getpid()}")
        node = cls(session_dir)
        node._start_gcs()
        node._start_raylet(
            detect_node_resources(num_cpus, num_gpus, resources),
            object_store_memory, is_head=True)
        if include_dashboard:
            node._start_dashboard()
        return node

    def _child_env(self) -> dict:
        env = dict(os.environ)
        env["RAY_TPU_LOG_DIR"] = self.log_dir
        # Capture the host's ambient platform FIRST so TPU-leased workers
        # can restore it (jax_platform.enable_host_platform), then default
        # children to CPU: workers must not grab the TPU chip the driver
        # may be using, nor spend seconds initializing a TPU runtime per
        # process. (Env alone is advisory — site PJRT plugins may ignore
        # it; the authoritative pin is jax_platform.pin_worker_platform in
        # worker_main.)
        from ray_tpu.core.jax_platform import HOST_ENV

        env.setdefault(HOST_ENV, env.get("JAX_PLATFORMS", ""))
        env.setdefault("JAX_PLATFORMS", "cpu")
        return env

    def _spawn(self, name: str, cmd, pattern: str) -> str:
        log = open(os.path.join(self.log_dir, f"{name}.err"), "ab")
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=log,
                                env=self._child_env())
        self.processes[name] = proc
        return _wait_for_line(proc, pattern)

    def _start_gcs(self) -> None:
        self.gcs_address = self._spawn(
            "gcs", [sys.executable, "-m", "ray_tpu.core.gcs.server",
                    "--storage",
                    os.path.join(self.session_dir, "gcs_storage.pkl")],
            r"GCS_ADDRESS=(\S+)")

    def _start_raylet(self, resources: Dict[str, float],
                      object_store_memory: Optional[int],
                      is_head: bool = False) -> None:
        self.node_id = NodeID.from_random().hex()
        cmd = [sys.executable, "-m", "ray_tpu.core.raylet",
               "--gcs", self.gcs_address, "--node-id", self.node_id,
               "--resources", json.dumps(resources)]
        if object_store_memory:
            cmd += ["--object-store-memory", str(object_store_memory)]
        if is_head:
            cmd += ["--head"]
        self.raylet_address = self._spawn(
            "raylet", cmd, r"RAYLET_ADDRESS=(\S+)")

    def kill_gcs(self) -> None:
        """Fault injection: hard-kill the GCS process (reference:
        test_gcs_fault_tolerance.py)."""
        proc = self.processes["gcs"]
        proc.kill()
        proc.wait()

    def restart_gcs(self) -> None:
        """Bring the GCS back at the SAME address with its persisted
        storage; raylets re-register via the heartbeat False-reply
        contract, clients reconnect via _ReconnectingRpc."""
        host, port = self.gcs_address.rsplit(":", 1)
        addr = self._spawn(
            "gcs", [sys.executable, "-m", "ray_tpu.core.gcs.server",
                    "--host", host, "--port", port, "--storage",
                    os.path.join(self.session_dir, "gcs_storage.pkl")],
            r"GCS_ADDRESS=(\S+)")
        assert addr == self.gcs_address, (addr, self.gcs_address)

    def _start_dashboard(self) -> None:
        """Observability HTTP head (reference: dashboard/head.py). A
        dashboard failure must never block cluster bring-up."""
        try:
            self.dashboard_address = self._spawn(
                "dashboard",
                [sys.executable, "-m", "ray_tpu.dashboard",
                 "--gcs", self.gcs_address],
                r"DASHBOARD_READY (\S+)")
        except Exception:
            self.dashboard_address = None

    def stop(self) -> None:
        for name, proc in reversed(list(self.processes.items())):
            if proc.poll() is None:
                proc.terminate()
        deadline = time.time() + 3
        for proc in self.processes.values():
            try:
                proc.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                proc.kill()
        self.processes.clear()
