"""Runtime environments: per-task/actor env_vars and working_dir.

Reference equivalent: `python/ray/_private/runtime_env/` (the working_dir
and env_vars plugins of the runtime env agent). The driver packages a
working_dir into a content-addressed zip in the GCS KV; workers download
and extract it once per content hash, then put it on sys.path and chdir
for execution. env_vars apply to the worker process before user code
runs. Isolation note: distinct runtime envs hash into the lease
scheduling key, so concurrent tasks with different envs never share a
leased worker.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import sys
import zipfile
from typing import Any, Dict, Optional

_MAX_WORKING_DIR_BYTES = 100 * 1024 * 1024
_EXTRACT_ROOT = "/tmp/ray_tpu_runtime_envs"


def env_hash(runtime_env: Optional[Dict[str, Any]]) -> str:
    """Stable hash for scheduling-key isolation ('' = no env)."""
    if not runtime_env:
        return ""
    return hashlib.sha1(
        json.dumps(runtime_env, sort_keys=True).encode()).hexdigest()[:12]


def validate(runtime_env: Dict[str, Any]) -> None:
    allowed = {"env_vars", "working_dir", "working_dir_key", "pip"}
    unknown = set(runtime_env) - allowed
    if unknown:
        raise ValueError(
            f"unsupported runtime_env fields {sorted(unknown)}; "
            f"supported: {sorted(allowed)}")
    env_vars = runtime_env.get("env_vars")
    if env_vars is not None and not all(
            isinstance(k, str) and isinstance(v, str)
            for k, v in env_vars.items()):
        raise ValueError("runtime_env env_vars must be {str: str}")
    pip = runtime_env.get("pip")
    if pip is not None and not (
            isinstance(pip, (list, tuple))
            and all(isinstance(r, str) for r in pip)):
        raise ValueError(
            "runtime_env pip must be a list of requirement strings "
            "(wheel paths / source dirs work offline)")


def pack_working_dir(path: str) -> bytes:
    """Deterministic zip of a directory tree."""
    if not os.path.isdir(path):
        raise ValueError(f"working_dir {path!r} is not a directory")
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git"))
            for fname in sorted(files):
                full = os.path.join(root, fname)
                rel = os.path.relpath(full, path)
                zf.write(full, rel)
    data = buf.getvalue()
    if len(data) > _MAX_WORKING_DIR_BYTES:
        raise ValueError(
            f"working_dir zip is {len(data)} bytes; limit "
            f"{_MAX_WORKING_DIR_BYTES} (exclude data files)")
    return data


def upload_working_dir(rt, path: str) -> str:
    """Driver-side: zip + content-addressed KV upload; returns the key."""
    data = pack_working_dir(path)
    digest = hashlib.sha1(data).hexdigest()[:16]
    key = f"runtime_env:working_dir:{digest}".encode()
    rt.kv_put(key, data, overwrite=False)
    return key.decode()


def prepare_spec_env(rt, runtime_env: Optional[Dict[str, Any]]
                     ) -> Optional[Dict[str, Any]]:
    """Resolve a user runtime_env into its wire form (working_dir
    uploaded, replaced by its KV key)."""
    if not runtime_env:
        return None
    validate(runtime_env)
    out = dict(runtime_env)
    wd = out.pop("working_dir", None)
    if wd:
        out["working_dir_key"] = upload_working_dir(rt, wd)
    return out


# -- pip plugin (reference: _private/runtime_env/pip.py) -----------------
_PIP_ROOT = os.path.join(_EXTRACT_ROOT, "pip")


# Key memoization: walking a large source tree per TASK would tax the
# hot path; a short TTL still catches source edits promptly.
_pip_key_cache: Dict[tuple, tuple] = {}
_PIP_KEY_TTL_S = 10.0


def pip_env_key(requirements) -> str:
    """Content key: same requirement set -> same cached env. Local
    source/wheel requirements fold in their file stats, so editing the
    package invalidates the cache instead of serving a stale install."""
    import time as _time

    cache_key = tuple(sorted(str(r) for r in requirements))
    hit = _pip_key_cache.get(cache_key)
    if hit is not None and _time.monotonic() - hit[1] < _PIP_KEY_TTL_S:
        return hit[0]
    key = _pip_env_key_uncached(requirements)
    _pip_key_cache[cache_key] = (key, _time.monotonic())
    return key


def _pip_env_key_uncached(requirements) -> str:
    parts = []
    for r in sorted(str(r) for r in requirements):
        parts.append(r)
        if os.path.exists(r):
            if os.path.isdir(r):
                for root, dirs, files in os.walk(r):
                    # Exclude what pip's in-tree build writes back
                    # (egg-info, build/, dist/) or the key would change
                    # after the first install and never cache-hit.
                    dirs[:] = sorted(
                        d for d in dirs
                        if d not in ("__pycache__", ".git", "build",
                                     "dist")
                        and not d.endswith(".egg-info"))
                    for fname in sorted(files):
                        full = os.path.join(root, fname)
                        try:
                            st = os.stat(full)
                            parts.append(
                                f"{full}:{st.st_mtime_ns}:{st.st_size}")
                        except OSError:
                            pass
            else:
                st = os.stat(r)
                parts.append(f"{st.st_mtime_ns}:{st.st_size}")
    return hashlib.sha1("\n".join(parts).encode()).hexdigest()[:16]


def ensure_pip_env(requirements) -> str:
    """Install `requirements` into a per-node cached target directory
    keyed by the requirements hash; returns the directory. Reference
    builds a full virtualenv per env (pip.py); here packages install
    with `pip --target` and join sys.path — same isolation-by-
    scheduling-key model, no interpreter restart. `--no-build-isolation`
    keeps source installs working offline (zero-egress hosts)."""
    import subprocess

    key = pip_env_key(requirements)
    target = os.path.join(_PIP_ROOT, key)
    marker = os.path.join(target, ".ray_tpu_pip_done")
    if os.path.exists(marker):
        return target  # cache hit: another task on this node built it
    tmp = f"{target}.tmp.{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    cmd = [sys.executable, "-m", "pip", "install", "--quiet",
           "--no-build-isolation", "--target", tmp,
           *sorted(str(r) for r in requirements)]
    from ray_tpu.exceptions import RuntimeEnvSetupError

    from ray_tpu.core.config import ray_config

    timeout_s = ray_config().pip_install_timeout_s
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        raise RuntimeEnvSetupError(
            f"pip install timed out after {timeout_s:.0f}s: "
            f"{requirements}")
    if proc.returncode != 0:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        raise RuntimeEnvSetupError(
            f"pip install failed (rc={proc.returncode}):\n"
            f"{proc.stderr[-2000:]}")
    with open(os.path.join(tmp, ".ray_tpu_pip_done"), "w") as f:
        f.write(key)
    try:
        os.rename(tmp, target)
    except OSError:
        # Concurrent install won the rename: use theirs.
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return target


def apply_runtime_env(rt, runtime_env: Optional[Dict[str, Any]]) -> None:
    """Worker-side: make the env effective for this process."""
    if not runtime_env:
        return
    env_vars = runtime_env.get("env_vars") or {}
    os.environ.update(env_vars)
    pip = runtime_env.get("pip")
    if pip:
        target = ensure_pip_env(pip)
        if target not in sys.path:
            sys.path.insert(0, target)
    key = runtime_env.get("working_dir_key")
    if key:
        target = os.path.join(_EXTRACT_ROOT, key.rsplit(":", 1)[-1])
        if not os.path.isdir(target):
            blob = rt.kv_get(key.encode())
            if blob is None:
                raise FileNotFoundError(
                    f"runtime_env working_dir blob {key} not in GCS KV")
            tmp = f"{target}.tmp.{os.getpid()}"
            os.makedirs(tmp, exist_ok=True)
            with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                zf.extractall(tmp)
            try:
                os.rename(tmp, target)
            except OSError:
                # Concurrent extract won the rename: use theirs.
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
        if target not in sys.path:
            sys.path.insert(0, target)
        os.chdir(target)
