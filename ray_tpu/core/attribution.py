"""Per-call attribution for the task-plane hot path.

Reference equivalent: the per-RPC latency histograms the reference keeps
in `stats/metric_defs.h` (e.g. `scheduler_task_time`) that make a task
regression attributable to a stage instead of an archaeology project.

Design constraints, in order:

1. **Zero cost when off.** Every instrumentation site is guarded by the
   module-level `enabled` bool — one global load per call site, no
   function call, no clock read. The hot path (submit -> lease -> push
   -> decode -> dispatch) pays nothing in normal operation.
2. **Cheap when on.** `record()` is two dict ops on a plain dict; spans
   accumulate (count, total_s, max_s) per label, never per-event lists,
   so a 100k-task bench can't blow memory.
3. **Cross-process.** The driver enables attribution via the
   `RAY_TPU_ATTRIBUTION` env var, which spawned workers inherit; the
   worker folds its own decode/execute timings into each task reply
   (a few ints, only when enabled) so the driver-side snapshot covers
   both sides of the wire without a separate scrape protocol.

Labels in the submit-path breakdown (see `python -m ray_tpu.perf
--attribute` and the PROFILE.md table):

- ``submit.encode``     spec construction + template/wire encode
- ``submit.lease``      time waiting for a leased worker (pool hit ~= 0)
- ``submit.push_rtt``   push_task RPC round trip (includes execution)
- ``rpc.frame_write``   transport write syscalls (batched writer)
- ``wire.decode``       validated from_wire (whichever process decodes)
- ``wire.decode_fast``  post-handshake fast-path decode
- ``worker.decode``       worker-side task-spec decode (from replies)
- ``worker.arg_resolve``  worker-side arg deserialization + ref fetches
- ``worker.exec``         worker-side user-code wall time
- ``worker.result_pack``  worker-side return serialization + store
- ``get.local_shm``     node-local shm reads that bypassed the raylet
- ``get.pull_rpc``      gets that did take the raylet pull_object RPC

Round-8 task-plane labels: ``submit.inline`` / ``submit.remote`` count
the dispatch split (inline executions vs leased pushes);
``inline.arg_resolve`` / ``inline.exec`` / ``inline.result_pack`` are
the caller-thread analogue of the worker split; ``lease.batch_size`` is
a dimensionless distribution (``value()``: count = batched lease RPCs,
mean/max = grants per RPC); ``ring.enq`` / ``ring.deq`` /
``ring.doorbell`` / ``ring.fallback`` count ring-primitive traffic
(fallback = specs the ring could not carry that took the RPC path).

Round-10 worker-direct ring labels: ``ring.direct_enq`` counts task
deltas the driver published straight onto a leased worker's ring (the
zero-syscall dispatch tier; compare against ``ring.doorbell`` — under
load doorbells must be ≪ enqueues), ``ring.worker_deq`` counts deltas
the worker-side consumer decoded (its process's table), ``ring.reply``
counts replies that came back over the twin ring and
``ring.reply_fallback`` those that had to ride a server push instead
(a full or broken reply ring shows up here, never hidden inside
ring.reply); ``lease.return_batch`` is the return-side mirror of
``lease.batch_size`` (count = batched return RPCs, mean/max = leases
returned per RPC).

Round-16 caller-thread dispatch labels: ``submit.caller_enq`` counts
submits the CALLER thread published straight onto a worker ring (the
fifth dispatch tier — no loop wakeup, no coroutine; compare against
``submit.remote`` for the tier split) and ``submit.caller_rtt`` times
their publish→completion round trip; ``submit.caller_fallback`` counts
caller attempts that exhausted the bounded full-ring wait and fell
back to the loop-hop queue (the <5% honesty bound in the perf guard);
``ring.handoff`` counts producer-side ownership migrations through the
ProducerLatch (loop ⇄ caller ⇄ teardown — a ping-ponging latch would
eat the tier's win); ``ring.producer_violation`` counts overlapping
pushes the writer's re-entrancy sentinel observed (MUST stay 0 — a
nonzero value means the SPSC invariant broke); ``ring.busy_poll`` /
``worker.busy_poll`` count post-drain spin windows that found the next
entry without an epoll wakeup (driver reply side / worker submit
side), ``ring.busy_poll_hit`` the spins that paid off inside
`ring.busy_poll()` itself; ``inline.revoked`` counts cost-model-v2
revocation windows (inlining suspended under caller-dispatch
pressure). All are counts, not durations, except ``submit.caller_rtt``.

Data-plane counters (round 7, the zero-copy audit — counts, not
durations): ``get.nd_view`` array gets served as a zero-copy view over
the store segment (no pickler ran); ``put.sharded``/``get.sharded``
manifest-based multi-device array put/get; ``chan.device_send``
device-channel tensors that moved over collective p2p instead of the
RPC byte plane. A hot array path that is truly zero-copy shows ONLY
these counters — any ``copy.*`` label appearing next to them names the
stage that still copies.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict

ENV_FLAG = "RAY_TPU_ATTRIBUTION"

# Module-level guard, read directly by hot-path call sites:
#   if attribution.enabled: t0 = time.perf_counter(); ...
enabled = bool(os.environ.get(ENV_FLAG))

_lock = threading.Lock()
_stats: Dict[str, list] = {}   # label -> [count, total_s, max_s]


def enable() -> None:
    """Turn attribution on for this process AND processes spawned after
    this call (the env var rides into workers via their inherited
    environment)."""
    global enabled
    enabled = True
    os.environ[ENV_FLAG] = "1"


def disable() -> None:
    global enabled
    enabled = False
    os.environ.pop(ENV_FLAG, None)


def reset() -> None:
    with _lock:
        _stats.clear()
        # Value-label markers are part of the recorded state: a label
        # reused as a duration after reset must not keep rendering in
        # sample units.
        _value_labels.clear()


def record(label: str, dt: float) -> None:
    """Fold one span of `dt` seconds into `label`'s accumulator."""
    s = _stats.get(label)
    if s is None:
        with _lock:
            s = _stats.setdefault(label, [0, 0.0, 0.0])
    # Benign races on += under the GIL can undercount slightly; a
    # profiler trades that for not taking a lock per span.
    s[0] += 1
    s[1] += dt
    if dt > s[2]:
        s[2] = dt


def count(label: str, n: int = 1) -> None:
    """Count an event with no duration (e.g. a bypass hit)."""
    s = _stats.get(label)
    if s is None:
        with _lock:
            s = _stats.setdefault(label, [0, 0.0, 0.0])
    s[0] += n


_value_labels: set = set()


def value(label: str, v: float) -> None:
    """Fold a dimensionless sample (e.g. a lease batch size) into
    `label`: snapshot reports mean/max in the sample's own units
    instead of microseconds."""
    _value_labels.add(label)
    record(label, v)


def snapshot() -> Dict[str, Dict[str, float]]:
    """{label: {count, total_ms, mean_us, max_us}} for reporting."""
    out = {}
    with _lock:
        items = [(k, list(v)) for k, v in _stats.items()]
    for label, (n, total, mx) in sorted(items):
        if label in _value_labels:
            out[label] = {
                "count": n,
                "total": round(total, 3),
                "mean": round(total / n, 2) if n else 0.0,
                "max": round(mx, 2),
            }
            continue
        out[label] = {
            "count": n,
            "total_ms": round(total * 1e3, 3),
            "mean_us": round(total / n * 1e6, 2) if n else 0.0,
            "max_us": round(mx * 1e6, 2),
        }
    return out


def fold(remote: Dict[str, Any], prefix: str = "worker.") -> None:
    """Fold a worker-reported fragment into the local table.

    Duration entries arrive as microsecond ints: ``{label: us}``.
    Dimensionless entries (worker-side `value()` samples) MUST arrive
    marked — ``{label: [sample, "v"]}``, built with `value_marked` —
    because `_value_labels` is process-local: an unmarked sample folded
    from a worker fragment would render as microseconds in the owner's
    `snapshot()`."""
    for label, us in remote.items():
        if isinstance(us, (list, tuple)):
            # (sample, "v") marker: a dimensionless value() sample.
            value(prefix + label, us[0])
        else:
            record(prefix + label, us / 1e6)


def value_marked(v: float) -> list:
    """Wrap a dimensionless sample for a cross-process fragment so
    `fold()` on the receiving side keeps its units (see `fold`)."""
    return [v, "v"]
