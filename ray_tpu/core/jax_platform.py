"""Per-process JAX platform pinning for worker processes.

Reference equivalent: `python/ray/_private/accelerators/tpu.py:214` keeps
worker processes off accelerators they were not granted via visibility env
vars. On this stack env vars are not enough: a site-installed PJRT plugin
(e.g. the tunnel TPU client) may claim the default backend regardless of
`JAX_PLATFORMS`, so a plain CPU task worker would initialize — and contend
for — the host's TPU the moment user code imports jax. The only reliable
switch is `jax.config.update("jax_platforms", ...)` before backends
initialize, so workers pin lazily: a meta-path hook applies the pin the
instant `jax` finishes importing, costing nothing for workers that never
touch jax.

Workers granted TPU chips at lease time undo the pin with
`enable_host_platform()` (see `cluster_runtime._apply_visible_chips`).
"""

from __future__ import annotations

import importlib.abc
import importlib.util
import os
import sys
from typing import Optional

# What platform workers pin to at jax-import time (default: cpu).
PIN_ENV = "RAY_TPU_WORKER_JAX_PLATFORMS"
# The host's ambient JAX_PLATFORMS, captured by the node bootstrap BEFORE
# any defaulting, so a TPU-leased worker can restore it ("" = autodetect).
HOST_ENV = "RAY_TPU_HOST_JAX_PLATFORMS"


class _JaxPlatformPinner(importlib.abc.MetaPathFinder):
    """Wraps the real jax loader so the platform pin lands immediately
    after `import jax`, before any backend can initialize."""

    def __init__(self, platform: str):
        self.platform = platform
        self._resolving = False

    def find_spec(self, name, path, target=None):
        if name != "jax" or self._resolving:
            return None
        self._resolving = True
        try:
            spec = importlib.util.find_spec("jax")
        finally:
            self._resolving = False
        if spec is None or spec.loader is None:
            return None
        orig_loader = spec.loader
        pinner = self

        class _PinningLoader(importlib.abc.Loader):
            def create_module(self, s):
                return orig_loader.create_module(s)

            def exec_module(self, module):
                orig_loader.exec_module(module)
                try:
                    module.config.update("jax_platforms", pinner.platform)
                except Exception:
                    pass
                try:
                    sys.meta_path.remove(pinner)
                except ValueError:
                    pass

        spec.loader = _PinningLoader()
        return spec


def pin_worker_platform(platform: Optional[str] = None) -> None:
    """Install the lazy pin (idempotent). Called from worker_main before
    any user code runs."""
    platform = platform or os.environ.get(PIN_ENV, "cpu")
    if "jax" in sys.modules:
        try:
            sys.modules["jax"].config.update("jax_platforms", platform)
        except Exception:
            pass
        return
    if any(isinstance(f, _JaxPlatformPinner) for f in sys.meta_path):
        return
    sys.meta_path.insert(0, _JaxPlatformPinner(platform))


def enable_host_platform() -> None:
    """Undo the CPU pin for a worker that was granted TPU chips: restore
    the host's platform selection and drop any CPU-only backends already
    built, so the next jax call sees the accelerator."""
    host = os.environ.get(HOST_ENV)
    if host is None:
        host = os.environ.get("JAX_PLATFORMS", "")
    for finder in list(sys.meta_path):
        if isinstance(finder, _JaxPlatformPinner):
            sys.meta_path.remove(finder)
    import jax

    try:
        jax.config.update("jax_platforms", host or None)
    except Exception:
        return
    try:
        from jax._src import xla_bridge as _xb

        if _xb.backends_are_initialized():
            _xb._clear_backends()
    except Exception:
        pass
