"""Placement-group bundle→node selection policies.

Reference equivalent: `src/ray/raylet/scheduling/policy/
bundle_scheduling_policy.h` (+ `scorer.h`) — STRICT_PACK / PACK / SPREAD /
STRICT_SPREAD over a cluster resource view. Runs owner-side here (the
creating worker drives the 2PC), against the GCS node table; staleness is
handled by the caller retrying on prepare failure.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


def validate_pg_args(bundles, strategy: str) -> None:
    """Shared by every runtime that creates placement groups."""
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"Invalid placement strategy {strategy!r}; "
                         f"valid: {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("placement group requires non-empty bundles")


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())


def _take(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


def select_pg_nodes(bundles: List[Dict[str, float]],
                    nodes: List[Dict[str, Any]], strategy: str,
                    target_node_ids: Optional[List[str]] = None
                    ) -> Optional[List[Dict[str, Any]]]:
    """Pick one node per bundle, or None if infeasible against this view.

    `target_node_ids` pins bundle i to the node with that id (used by the
    TPU slice strategy: one bundle per host of one slice)."""
    avail = {n["node_id"]: dict(n.get("resources_available", {}))
             for n in nodes}
    by_id = {n["node_id"]: n for n in nodes}

    if target_node_ids is not None:
        if len(target_node_ids) != len(bundles):
            return None
        out = []
        for demand, nid in zip(bundles, target_node_ids):
            if nid not in avail or not _fits(avail[nid], demand):
                return None
            _take(avail[nid], demand)
            out.append(by_id[nid])
        return out

    # Most-available-first ordering (scorer.h tie-break: spread load).
    # Nodes whose resource view is STALE — recovered from persisted GCS
    # state after a restart, not yet re-confirmed by a heartbeat — sort
    # behind every fresh node: their recorded availability may describe
    # a pre-crash world, and a prepare against it fails and burns a 2PC
    # round trip.
    def capacity(nid: str) -> float:
        a = avail[nid]
        return a.get("CPU", 0.0) + a.get("TPU", 0.0)

    def freshness_then_capacity(nid: str):
        return (0 if by_id[nid].get("stale_view") else 1, capacity(nid))

    ordered = sorted(avail, key=freshness_then_capacity, reverse=True)

    if strategy == "STRICT_PACK":
        total: Dict[str, float] = {}
        for b in bundles:
            for k, v in b.items():
                total[k] = total.get(k, 0.0) + v
        for nid in ordered:
            if _fits(avail[nid], total):
                return [by_id[nid]] * len(bundles)
        return None

    if strategy == "STRICT_SPREAD":
        out, used = [], set()
        for demand in bundles:
            nid = next((n for n in ordered
                        if n not in used and _fits(avail[n], demand)), None)
            if nid is None:
                return None
            used.add(nid)
            _take(avail[nid], demand)
            out.append(by_id[nid])
        return out

    if strategy == "PACK":
        out: List[Dict[str, Any]] = []
        used_order: List[str] = []
        for demand in bundles:
            # Prefer nodes already holding a bundle of this group.
            nid = next((n for n in used_order if _fits(avail[n], demand)),
                       None)
            if nid is None:
                nid = next((n for n in ordered if _fits(avail[n], demand)),
                           None)
            if nid is None:
                return None
            if nid not in used_order:
                used_order.append(nid)
            _take(avail[nid], demand)
            out.append(by_id[nid])
        return out

    if strategy == "SPREAD":
        out = []
        last: Optional[str] = None
        for demand in bundles:
            # Best-effort spread: most-available feasible node that isn't
            # the one we just used, falling back to any feasible node.
            candidates = sorted((n for n in avail if _fits(avail[n], demand)),
                                key=freshness_then_capacity, reverse=True)
            if not candidates:
                return None
            nid = next((n for n in candidates if n != last), candidates[0])
            last = nid
            _take(avail[nid], demand)
            out.append(by_id[nid])
        return out

    raise ValueError(f"unknown placement strategy {strategy!r}; "
                     f"valid: {VALID_STRATEGIES}")
