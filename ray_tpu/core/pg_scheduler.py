"""Placement-group bundle→node selection policies + GCS-led rescheduling.

Reference equivalent: `src/ray/raylet/scheduling/policy/
bundle_scheduling_policy.h` (+ `scorer.h`) — STRICT_PACK / PACK / SPREAD /
STRICT_SPREAD over a cluster resource view. Initial placement runs
owner-side (the creating worker drives the 2PC) against the GCS node
table; staleness is handled by the caller retrying on prepare failure.

Round 15 adds `reschedule_placement_group`: the GCS-led recovery pass
(reference: GcsPlacementGroupScheduler rescheduling on node death) that
re-places only a CREATED group's LOST bundles onto survivors — surviving
bundles keep their reservations — through the same prepare/commit 2PC,
with every state transition written through so a crash mid-reschedule is
resumable and cannot leak capacity (the raylet-side reconciler returns
commits the final location table did not keep).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


def validate_pg_args(bundles, strategy: str) -> None:
    """Shared by every runtime that creates placement groups."""
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"Invalid placement strategy {strategy!r}; "
                         f"valid: {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("placement group requires non-empty bundles")


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())


def _take(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


def select_pg_nodes(bundles: List[Dict[str, float]],
                    nodes: List[Dict[str, Any]], strategy: str,
                    target_node_ids: Optional[List[str]] = None
                    ) -> Optional[List[Dict[str, Any]]]:
    """Pick one node per bundle, or None if infeasible against this view.

    `target_node_ids` pins bundle i to the node with that id (used by the
    TPU slice strategy: one bundle per host of one slice)."""
    avail = {n["node_id"]: dict(n.get("resources_available", {}))
             for n in nodes}
    by_id = {n["node_id"]: n for n in nodes}

    if target_node_ids is not None:
        if len(target_node_ids) != len(bundles):
            return None
        out = []
        for demand, nid in zip(bundles, target_node_ids):
            if nid not in avail or not _fits(avail[nid], demand):
                return None
            _take(avail[nid], demand)
            out.append(by_id[nid])
        return out

    # Most-available-first ordering (scorer.h tie-break: spread load).
    # Nodes whose resource view is STALE — recovered from persisted GCS
    # state after a restart, not yet re-confirmed by a heartbeat — sort
    # behind every fresh node: their recorded availability may describe
    # a pre-crash world, and a prepare against it fails and burns a 2PC
    # round trip.
    def capacity(nid: str) -> float:
        a = avail[nid]
        return a.get("CPU", 0.0) + a.get("TPU", 0.0)

    def freshness_then_capacity(nid: str):
        return (0 if by_id[nid].get("stale_view") else 1, capacity(nid))

    ordered = sorted(avail, key=freshness_then_capacity, reverse=True)

    if strategy == "STRICT_PACK":
        total: Dict[str, float] = {}
        for b in bundles:
            for k, v in b.items():
                total[k] = total.get(k, 0.0) + v
        for nid in ordered:
            if _fits(avail[nid], total):
                return [by_id[nid]] * len(bundles)
        return None

    if strategy == "STRICT_SPREAD":
        out, used = [], set()
        for demand in bundles:
            nid = next((n for n in ordered
                        if n not in used and _fits(avail[n], demand)), None)
            if nid is None:
                return None
            used.add(nid)
            _take(avail[nid], demand)
            out.append(by_id[nid])
        return out

    if strategy == "PACK":
        out: List[Dict[str, Any]] = []
        used_order: List[str] = []
        for demand in bundles:
            # Prefer nodes already holding a bundle of this group.
            nid = next((n for n in used_order if _fits(avail[n], demand)),
                       None)
            if nid is None:
                nid = next((n for n in ordered if _fits(avail[n], demand)),
                           None)
            if nid is None:
                return None
            if nid not in used_order:
                used_order.append(nid)
            _take(avail[nid], demand)
            out.append(by_id[nid])
        return out

    if strategy == "SPREAD":
        out = []
        last: Optional[str] = None
        for demand in bundles:
            # Best-effort spread: most-available feasible node that isn't
            # the one we just used, falling back to any feasible node.
            candidates = sorted((n for n in avail if _fits(avail[n], demand)),
                                key=freshness_then_capacity, reverse=True)
            if not candidates:
                return None
            nid = next((n for n in candidates if n != last), candidates[0])
            last = nid
            _take(avail[nid], demand)
            out.append(by_id[nid])
        return out

    raise ValueError(f"unknown placement strategy {strategy!r}; "
                     f"valid: {VALID_STRATEGIES}")


async def reschedule_placement_group(gcs, raylet_client_for, pg_id: str,
                                     *, attempts: int = 8) -> str:
    """Re-place the LOST bundles of a RESCHEDULING group onto surviving
    nodes; bundles whose node is still alive keep their reservations
    untouched. Driven BY THE GCS when `_mark_node_dead` finds a CREATED
    group on the dead node (the owner may itself be gone — recovery
    cannot be owner-led).

    Protocol per attempt: read the group (only the RESCHEDULING state
    proceeds — a user remove wins any race via the CAS), compute lost
    indices against the live node table, select placement for just
    those bundles (STRICT_SPREAD excludes nodes already holding a
    surviving bundle; STRICT_PACK's loss is all-or-nothing by
    construction), 2PC prepare+commit on the chosen nodes, then CAS
    RESCHEDULING -> CREATED with the merged location table
    (write-through — the terminal transition must survive a kill -9).
    Failure rolls back this attempt's reservations and retries; a crash
    between commit and the CAS is healed by the raylet reconciler's
    location check once a later pass lands CREATED elsewhere.

    Returns the state the group was left in: "CREATED" on success,
    "RESCHEDULING" when every attempt failed (the GCS health loop
    re-kicks when the cluster changes), or the foreign terminal state
    observed ("REMOVED"/"INFEASIBLE")."""
    from ray_tpu.core import flight

    for attempt in range(attempts):
        try:
            info = await gcs.get_placement_group(pg_id)
            state = (info or {}).get("state")
            if state != "RESCHEDULING":
                return state or "UNKNOWN"
            bundles = info["bundles"]
            locs = list(info.get("bundle_locations") or [])
            nodes = [n for n in await gcs.get_nodes() if n.get("alive")]
            alive_ids = {n["node_id"] for n in nodes}
            lost = [i for i, loc in enumerate(locs)
                    if loc.get("node_id") not in alive_ids]
            if len(locs) != len(bundles):
                # Defensive: a malformed record can't be re-placed.
                lost = list(range(len(bundles)))
                locs = [{"node_id": None, "address": None}
                        for _ in bundles]
            if not lost:
                # Every location is alive again (e.g. the reschedule
                # raced a transient death verdict): just restore CREATED.
                ok = await gcs.update_placement_group(
                    pg_id, {"state": "CREATED"},
                    expect_state="RESCHEDULING")
                if ok:
                    return "CREATED"
                continue
            surviving_nodes = {locs[i]["node_id"]
                               for i in range(len(locs)) if i not in lost}
            strategy = info["strategy"]
            eligible = (
                [n for n in nodes if n["node_id"] not in surviving_nodes]
                if strategy == "STRICT_SPREAD" else nodes)
            placement = select_pg_nodes([bundles[i] for i in lost],
                                        eligible, strategy)
            if placement is None:
                await asyncio.sleep(0.25 * (attempt + 1))
                continue
            prepared: List[tuple] = []
            failure = None
            try:
                for slot, idx in enumerate(lost):
                    node = placement[slot]
                    client = await raylet_client_for(node["address"])
                    r = await client.call(
                        "prepare_bundle", pg_id=pg_id, bundle_index=idx,
                        resources=bundles[idx], timeout=10.0)
                    if not r.get("ok"):
                        failure = r.get("reason", "prepare rejected")
                        break
                    prepared.append((idx, node))
                if failure is None:
                    for idx, node in prepared:
                        client = await raylet_client_for(node["address"])
                        ok = await client.call("commit_bundle",
                                               pg_id=pg_id,
                                               bundle_index=idx,
                                               timeout=10.0)
                        if not ok:
                            # Reservation vanished between prepare and
                            # commit (raylet restart, concurrent
                            # return): landing it in the location
                            # table would create a CREATED group
                            # nothing can lease against.
                            failure = (f"commit rejected for bundle "
                                       f"{idx} on {node['node_id']}")
                            break
                if failure is None:
                    new_locs = list(locs)
                    for idx, node in prepared:
                        new_locs[idx] = {"node_id": node["node_id"],
                                         "address": node["address"]}
                    ok = await gcs.update_placement_group(pg_id, {
                        "state": "CREATED",
                        "bundle_locations": new_locs,
                    }, expect_state="RESCHEDULING")
                    if ok:
                        if flight.enabled:
                            flight.instant(
                                "pg", "pg.reschedule",
                                arg=f"{pg_id[:8]} n={len(prepared)}")
                        logger.info(
                            "placement group %s rescheduled: %d bundle(s) "
                            "re-placed", pg_id[:8], len(prepared))
                        return "CREATED"
                    failure = "cas rejected"
            except Exception as e:  # noqa: BLE001
                failure = str(e)
            # Only the GCS rescheduler writes CREATED from RESCHEDULING:
            # a CREATED re-read after a CAS miss/error means OUR update
            # applied with a lost ack — keep it. Any other state means
            # roll back this attempt's new reservations and honor it.
            try:
                cur = await gcs.get_placement_group(pg_id)
                if (cur or {}).get("state") == "CREATED":
                    return "CREATED"
            except Exception:
                pass
            logger.warning("pg %s reschedule attempt failed: %s",
                           pg_id[:8], failure)
            if flight.enabled:
                flight.instant("pg", "pg.rollback",
                               arg=f"{pg_id[:8]} resched n={len(prepared)}")
            for idx, node in prepared:
                try:
                    client = await raylet_client_for(node["address"])
                    await client.call("return_bundle", pg_id=pg_id,
                                      bundle_index=idx, timeout=10.0)
                except Exception:
                    pass
            await asyncio.sleep(0.25 * (attempt + 1))
        except Exception as e:  # noqa: BLE001
            logger.warning("pg %s reschedule pass error: %s", pg_id[:8], e)
            await asyncio.sleep(0.25 * (attempt + 1))
    return "RESCHEDULING"
