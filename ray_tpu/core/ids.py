"""Binary identifiers for every entity in the system.

Mirrors the reference's ID scheme (`src/ray/common/id.h`,
`src/ray/design_docs/id_specification.md`): fixed-width random/derived binary
IDs with cheap hashing and hex round-tripping. Sizes follow the reference:
JobID 4 bytes, ActorID 16, TaskID 24, ObjectID 28 (TaskID + 4-byte put/return
index), NodeID/WorkerID/PlacementGroupID 28/28/18.
"""

from __future__ import annotations

import os
import random
import struct
import threading

_NIL = b"\xff"

# ID randomness comes from a process-local PRNG seeded once from the OS
# (reference: id.h fills from an xorshift generator seeded per process,
# not /dev/urandom per ID). IDs need uniqueness, not unpredictability,
# and os.urandom is a syscall — measured 20-25 us on virtualized hosts,
# paid once per submitted task before this. Fork safety: a forked child
# reseeds so parent and child never draw the same stream.
_rand = random.Random(os.urandom(16))
_rand_lock = threading.Lock()

if hasattr(os, "register_at_fork"):
    os.register_at_fork(
        after_in_child=lambda: _rand.seed(os.urandom(16)))


def random_bytes(n: int) -> bytes:
    with _rand_lock:
        return _rand.getrandbits(n * 8).to_bytes(n, "little")


class BaseID:
    SIZE = 28
    __slots__ = ("_binary", "_hash")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._binary = binary
        self._hash = hash(binary)

    @classmethod
    def from_random(cls):
        return cls(random_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(_NIL * cls.SIZE)

    def is_nil(self) -> bool:
        return self._binary == _NIL * self.SIZE

    def binary(self) -> bytes:
        return self._binary

    def hex(self) -> str:
        return self._binary.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._binary == self._binary

    def __lt__(self, other):
        return self._binary < other._binary

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._binary,))


class UniqueID(BaseID):
    SIZE = 28


class JobID(BaseID):
    SIZE = 4

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(struct.pack(">I", value))

    def to_int(self) -> int:
        return struct.unpack(">I", self._binary)[0]


class NodeID(BaseID):
    SIZE = 28


class WorkerID(BaseID):
    SIZE = 28


class ActorID(BaseID):
    SIZE = 16

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(random_bytes(cls.SIZE - JobID.SIZE) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._binary[-JobID.SIZE:])


class TaskID(BaseID):
    SIZE = 24

    @classmethod
    def for_task(cls, job_id: JobID) -> "TaskID":
        return cls(random_bytes(cls.SIZE - JobID.SIZE) + job_id.binary())

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(random_bytes(cls.SIZE - ActorID.SIZE) + actor_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._binary[-JobID.SIZE:])


class ObjectID(BaseID):
    """TaskID (24 bytes) + big-endian uint32 index.

    Index 0 is reserved for `put` objects (the reference reserves index
    semantics similarly); return values use indices 1..n like the reference's
    return-object numbering.
    """

    SIZE = 28

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        return cls(task_id.binary() + struct.pack(">I", 0x80000000 | put_index))

    @classmethod
    def for_return(cls, task_id: TaskID, return_index: int) -> "ObjectID":
        return cls(task_id.binary() + struct.pack(">I", return_index))

    def task_id(self) -> TaskID:
        return TaskID(self._binary[: TaskID.SIZE])

    def index(self) -> int:
        return struct.unpack(">I", self._binary[TaskID.SIZE:])[0]


class PlacementGroupID(BaseID):
    SIZE = 18

    @classmethod
    def of(cls, job_id: JobID) -> "PlacementGroupID":
        return cls(random_bytes(cls.SIZE - JobID.SIZE) + job_id.binary())


class _Counter:
    """Thread-safe monotonically increasing counter (per-worker put/task indices)."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value


__all__ = [
    "BaseID",
    "UniqueID",
    "JobID",
    "NodeID",
    "WorkerID",
    "ActorID",
    "TaskID",
    "ObjectID",
    "PlacementGroupID",
]
