"""Streaming generator refs (`num_returns="streaming"`).

Reference equivalent: `_raylet.pyx:269` streaming generators — a task that
yields produces a stream of ObjectRefs the caller iterates without waiting for
task completion. Consumed by `ray_tpu.data`'s streaming executor.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

_SENTINEL = object()


class ObjectRefGenerator:
    """Iterator over ObjectRefs produced by a streaming task."""

    def __init__(self):
        self._queue: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._error: Optional[BaseException] = None

    # -- producer side -------------------------------------------------
    def _push(self, ref) -> None:
        self._queue.put(ref)

    def _finish(self, error: Optional[BaseException] = None) -> None:
        self._error = error
        self._queue.put(_SENTINEL)
        self._done.set()

    # -- consumer side -------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        item = self._queue.get()
        if item is _SENTINEL:
            self._queue.put(_SENTINEL)  # keep terminal for other iterators
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item

    def next_ready(self, timeout: Optional[float] = None):
        """Like __next__ but with a timeout; raises queue.Empty."""
        item = self._queue.get(timeout=timeout)
        if item is _SENTINEL:
            self._queue.put(_SENTINEL)
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item

    def completed(self) -> bool:
        return self._done.is_set()
