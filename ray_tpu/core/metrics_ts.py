"""Per-process metrics time-series buffer (round 17 observability).

The metrics registry (`util/metrics.py`) answers "what is the value
now"; this module makes that answer shippable over time.  Each process
keeps a `Recorder`: every capture interval it diffs the registry
snapshot against the previous one and appends a **delta-encoded** entry
to a bounded ring — counters and histogram buckets ship increments,
gauges ship levels, and series that did not move ship nothing at all.
The pending ring survives raylet hiccups (entries are only dropped on
ack or when the ring overflows), so a transient push failure loses no
points, only delays them.

Transport is piggybacked on plumbing that already exists:

    worker Recorder --ts_batch on report_metrics--> raylet
    raylet fold (its workers + own runtime gauges)
                   --metrics on the GCS heartbeat--> GCS retention store

so the fleet-wide cost is one coalesced payload per node per heartbeat
interval — O(nodes), not O(processes).

Zero-cost-off discipline mirrors `core/flight.py`: one module-level
``enabled`` bool checked at every call site, toggled through an env
flag that child processes inherit at spawn.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

ENV_FLAG = "RAY_TPU_METRICS_PIPELINE"


def _env_enabled() -> bool:
    val = os.environ.get(ENV_FLAG, "1").strip().lower()
    return val not in ("0", "false", "no", "off", "")


enabled: bool = _env_enabled()


def enable() -> None:
    """Turn the pipeline on for this process and for future children."""
    global enabled
    enabled = True
    os.environ[ENV_FLAG] = "1"


def disable() -> None:
    """Turn the pipeline off for this process and for future children."""
    global enabled
    enabled = False
    os.environ[ENV_FLAG] = "0"


def series_key(name: str, labels: Dict[str, str]) -> str:
    """Deterministic identity for a (name, labels) series.

    The same key is computed by every producer and by the GCS store, so
    a series re-pushed after a GCS restart lands on its recovered
    metadata instead of registering a duplicate.
    """
    return name + "|" + ",".join(
        f"{k}={labels[k]}" for k in sorted(labels))


class Recorder:
    """Delta-encodes registry snapshots into a bounded pending ring.

    Entries are wire-ready batches::

        {"t": <wall time>, "series": [[name, type, labels, payload], ...]}

    where payload is a float increment (counter), a float level (gauge),
    or ``[bucket_deltas, sum_delta, count_delta, boundaries]``
    (histogram — boundaries ride along so quantile-over-time needs no
    out-of-band schema).  A series' first-ever entry carries a fifth
    element, its help string, which the GCS persists as series metadata.
    """

    def __init__(self, capacity: int = 128) -> None:
        self._capacity = max(1, capacity)
        self._lock = threading.Lock()
        self._prev: Dict[Tuple[str, Any], Any] = {}
        self._seen: set = set()
        self._pending: List[Dict[str, Any]] = []
        self.dropped = 0  # entries evicted unacked (ring overflow)

    def configure(self, capacity: int) -> None:
        with self._lock:
            self._capacity = max(1, capacity)

    def capture(self, snapshot: List[Dict[str, Any]],
                t: Optional[float] = None) -> bool:
        """Diff `snapshot` (registry shape) against the previous capture
        and queue one delta entry.  Returns True if anything changed."""
        series: List[List[Any]] = []
        with self._lock:
            for metric in snapshot:
                name = metric.get("name")
                mtype = metric.get("type")
                help_text = metric.get("help", "")
                for sample in metric.get("samples", ()):
                    tags = dict(sample.get("tags") or {})
                    key = (name, tuple(sorted(tags.items())))
                    first = key not in self._seen
                    if mtype == "histogram":
                        buckets = list(sample.get("buckets") or ())
                        total = float(sample.get("sum", 0.0))
                        count = int(sample.get("count", 0))
                        prev = self._prev.get(key)
                        if prev is None:
                            b_delta = buckets
                            s_delta, c_delta = total, count
                        else:
                            pb, ps, pc = prev
                            if len(pb) != len(buckets):  # boundaries changed
                                pb = [0] * len(buckets)
                                ps, pc = 0.0, 0
                            b_delta = [b - p for b, p in zip(buckets, pb)]
                            s_delta, c_delta = total - ps, count - pc
                        self._prev[key] = (buckets, total, count)
                        if c_delta <= 0 and not first:
                            continue
                        payload: Any = [b_delta, s_delta, c_delta,
                                        list(sample.get("boundaries") or ())]
                    elif mtype == "counter":
                        value = float(sample.get("value", 0.0))
                        prev_v = self._prev.get(key)
                        delta = value if prev_v is None else value - prev_v
                        self._prev[key] = value
                        if delta == 0 and not first:
                            continue
                        payload = delta
                    else:  # gauge (and anything unknown degrades to one)
                        value = float(sample.get("value", 0.0))
                        if self._prev.get(key) == value and not first:
                            continue
                        self._prev[key] = value
                        payload = value
                    entry = [name, mtype, tags, payload]
                    if first:
                        self._seen.add(key)
                        entry.append(help_text)
                    series.append(entry)
            if not series:
                return False
            self._pending.append(
                {"t": time.time() if t is None else t, "series": series})
            overflow = len(self._pending) - self._capacity
            if overflow > 0:
                del self._pending[:overflow]
                self.dropped += overflow
            return True

    def pending(self) -> List[Dict[str, Any]]:
        """Unacked entries, oldest first (a snapshot — safe to ship)."""
        with self._lock:
            return list(self._pending)

    def ack(self, n: int) -> None:
        """Drop the oldest `n` entries after a successful push."""
        if n <= 0:
            return
        with self._lock:
            del self._pending[:n]

    def reset(self) -> None:
        with self._lock:
            self._prev.clear()
            self._seen.clear()
            self._pending.clear()
            self.dropped = 0


_recorder = Recorder()


def recorder() -> Recorder:
    return _recorder


def capture(snapshot: List[Dict[str, Any]],
            t: Optional[float] = None) -> bool:
    if not enabled:
        return False
    return _recorder.capture(snapshot, t=t)


def pending() -> List[Dict[str, Any]]:
    return _recorder.pending()


def ack(n: int) -> None:
    _recorder.ack(n)
