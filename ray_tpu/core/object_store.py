"""Per-node shared-memory object store (plasma equivalent), hosted inside
the raylet process like the reference hosts plasma in-process
(`src/ray/object_manager/plasma/store_runner.h`).

Design: one POSIX shm segment per object (`multiprocessing.shared_memory`),
named from the object id — workers on the node attach by name for zero-copy
reads; only control messages (create/seal/get/delete) cross the RPC socket,
the data plane is mmap. Node-to-node transfer (reference:
`object_manager/` push/pull) fetches the payload over the raylet RPC channel
and re-seals it locally. Capacity is enforced with LRU eviction of
unreferenced sealed objects (reference: `eviction_policy.h`).
"""

from __future__ import annotations

import logging
import mmap
import os
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from multiprocessing import shared_memory
from typing import Any, Dict, List, Optional, Set, Tuple

try:
    import _posixshmem   # CPython's shm_open binding (Linux/macOS)
except ImportError:      # pragma: no cover - non-POSIX fallback
    _posixshmem = None

logger = logging.getLogger(__name__)

SHM_PREFIX = "rtpu_"


class _RawShm:
    """Minimal attach to an existing POSIX shm segment: shm_open + mmap,
    with NO resource_tracker registration.

    `multiprocessing.shared_memory.SharedMemory` registers every attach
    with the tracker daemon and our `_untrack` then unregisters it — two
    tracker-pipe writes that cost ~0.5 ms each on virtualized kernels
    and dominated the get-10MB p50 (round-7 copy audit). The raylet owns
    segment lifetime, so a worker attach must be bookkeeping-free."""

    __slots__ = ("name", "buf", "_mmap")

    def __init__(self, name: str):
        fd = _posixshmem.shm_open("/" + name, os.O_RDWR, mode=0)
        try:
            size = os.fstat(fd).st_size
            self._mmap = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self.name = name
        self.buf = memoryview(self._mmap)

    def close(self) -> None:
        if self.buf is not None:
            self.buf.release()   # BufferError while views are alive
            self.buf = None
        self._mmap.close()       # BufferError while derived views live


def attach_segment(name: str):
    """Attach `name` for reading/writing with the cheapest available
    mechanism (raw shm_open on POSIX; SharedMemory elsewhere)."""
    if _posixshmem is not None:
        return _RawShm(name)
    shm = shared_memory.SharedMemory(name=name)
    _untrack(shm)
    return shm


def shm_name_for(object_id_hex: str) -> str:
    """shm names are limited (~31 chars portable). An ObjectID is
    TaskID(48 hex) + index(8 hex) — sibling returns/puts of one task differ
    ONLY in the trailing index, so the name must keep the tail."""
    if len(object_id_hex) <= 25:
        return SHM_PREFIX + object_id_hex
    return SHM_PREFIX + object_id_hex[:17] + object_id_hex[-8:]


@dataclass
class _Entry:
    size: int
    shm: shared_memory.SharedMemory
    sealed: bool = False
    created_at: float = field(default_factory=time.time)
    # pins: worker ids currently using the buffer (get in flight)
    pins: Set[str] = field(default_factory=set)


class LocalObjectStore:
    """The in-raylet store state machine (no I/O here; the raylet wires it
    to RPC handlers)."""

    def __init__(self, capacity_bytes: int):
        self.capacity = capacity_bytes
        self.used = 0
        self._objects: "OrderedDict[str, _Entry]" = OrderedDict()
        # Segments unlinked while a read_view still aliased the mapping:
        # retried on later deletes so their __del__ never squawks.
        self._deferred_close: List[Any] = []

    # -- create/seal (reference: plasma store.cc ProcessCreateRequests) --
    def create(self, oid: str, size: int) -> str:
        if oid in self._objects:
            entry = self._objects[oid]
            if entry.sealed:
                raise FileExistsError(f"object {oid[:8]} already sealed")
            return entry.shm.name
        if size > self.capacity:
            raise MemoryError(
                f"object of {size} bytes exceeds store capacity "
                f"{self.capacity}")
        self._ensure_space(size)
        name = shm_name_for(oid)
        try:
            shm = shared_memory.SharedMemory(name=name, create=True,
                                             size=max(size, 1))
        except FileExistsError:
            # Stale segment from a dead process: reclaim it.
            stale = shared_memory.SharedMemory(name=name)
            stale.close()
            stale.unlink()
            shm = shared_memory.SharedMemory(name=name, create=True,
                                             size=max(size, 1))
        self._objects[oid] = _Entry(size=size, shm=shm)
        self.used += size
        return shm.name

    def seal(self, oid: str) -> None:
        entry = self._objects.get(oid)
        if entry is None:
            raise KeyError(f"cannot seal unknown object {oid[:8]}")
        entry.sealed = True
        self._objects.move_to_end(oid)

    def put_bytes(self, oid: str, data: bytes) -> None:
        """Create+write+seal in one step (used by the pull path)."""
        if self.contains(oid):
            return
        self.create(oid, len(data))
        entry = self._objects[oid]
        entry.shm.buf[: len(data)] = data
        self.seal(oid)

    def create_from(self, oid: str, chunks) -> None:
        """Buffer-protocol put: create+write+seal from a chunk list (any
        bytes-like, including memoryviews over array buffers) with no
        intermediate join — each chunk is copied exactly once, into the
        segment."""
        if self.contains(oid):
            return
        size = sum(len(c) for c in chunks)
        self.create(oid, size)
        entry = self._objects[oid]
        off = 0
        for c in chunks:
            n = len(c)
            entry.shm.buf[off:off + n] = c
            off += n
        self.seal(oid)

    # -- read ------------------------------------------------------------
    def contains(self, oid: str) -> bool:
        entry = self._objects.get(oid)
        return entry is not None and entry.sealed

    def info(self, oid: str) -> Optional[Tuple[str, int]]:
        entry = self._objects.get(oid)
        if entry is None or not entry.sealed:
            return None
        self._objects.move_to_end(oid)  # LRU touch
        return entry.shm.name, entry.size

    def size_of(self, oid: str) -> Optional[int]:
        """Sealed-object size for metadata queries (no LRU touch)."""
        entry = self._objects.get(oid)
        if entry is None or not entry.sealed:
            return None
        return entry.size

    def read_bytes(self, oid: str) -> bytes:
        entry = self._objects.get(oid)
        if entry is None or not entry.sealed:
            raise KeyError(f"object {oid[:8]} not present/sealed")
        return bytes(entry.shm.buf[: entry.size])

    def read_view(self, oid: str) -> memoryview:
        """Zero-copy view over a sealed object's segment.

        Lifetime contract: the view aliases the live mapping. `delete`
        (explicit or via eviction) unlinks the segment but the mapping —
        and therefore an already-taken view — stays readable until the
        last view dies (frozen-mapping guarantee); a read_view AFTER the
        delete raises KeyError."""
        entry = self._objects.get(oid)
        if entry is None or not entry.sealed:
            raise KeyError(f"object {oid[:8]} not present/sealed")
        self._objects.move_to_end(oid)  # LRU touch
        return entry.shm.buf[: entry.size]

    def read_range(self, oid: str, offset: int, length: int) -> bytes:
        """One transfer chunk (reference: object_manager chunked reads,
        object_manager.h default 1 MiB chunks)."""
        entry = self._objects.get(oid)
        if entry is None or not entry.sealed:
            raise KeyError(f"object {oid[:8]} not present/sealed")
        end = min(offset + length, entry.size)
        return bytes(entry.shm.buf[offset:end])

    def write_range(self, oid: str, offset: int, data: bytes) -> None:
        """Fill part of a created-but-unsealed entry (chunked pull)."""
        entry = self._objects.get(oid)
        if entry is None:
            raise KeyError(f"object {oid[:8]} was not created")
        if entry.sealed:
            return  # concurrent pull already completed it
        entry.shm.buf[offset:offset + len(data)] = data

    def pin(self, oid: str, worker_id: str) -> None:
        entry = self._objects.get(oid)
        if entry is not None:
            entry.pins.add(worker_id)

    def unpin(self, oid: str, worker_id: str) -> None:
        entry = self._objects.get(oid)
        if entry is not None:
            entry.pins.discard(worker_id)

    def object_inventory(self) -> list:
        """Resident-object inventory (reference: `ray memory` /
        object_store_stats)."""
        return [{"object_id": oid, "size": e.size, "sealed": e.sealed,
                 "created_at": e.created_at, "num_pins": len(e.pins)}
                for oid, e in self._objects.items()]

    # -- delete/evict ----------------------------------------------------
    def delete(self, oid: str) -> bool:
        entry = self._objects.pop(oid, None)
        if entry is None:
            return False
        self.used -= entry.size
        try:
            entry.shm.unlink()
        except FileNotFoundError:
            pass
        try:
            entry.shm.close()
        except BufferError:
            # A read_view is still alive: the unlinked mapping stays
            # valid for that view (frozen-mapping guarantee); park the
            # handle and retry once the view's holder drops it.
            self._deferred_close.append(entry.shm)
        except FileNotFoundError:
            pass
        if self._deferred_close:
            parked, self._deferred_close = self._deferred_close, []
            for shm in parked:
                try:
                    shm.close()
                except BufferError:
                    self._deferred_close.append(shm)
                except Exception:
                    pass
        return True

    def _ensure_space(self, size: int) -> None:
        if self.used + size <= self.capacity:
            return
        # LRU-evict sealed, unpinned objects (reference: eviction_policy.h).
        for oid in list(self._objects):
            if self.used + size <= self.capacity:
                break
            entry = self._objects[oid]
            if entry.sealed and not entry.pins:
                logger.debug("evicting %s (%d bytes)", oid[:8], entry.size)
                self.delete(oid)
        if self.used + size > self.capacity:
            from ray_tpu.exceptions import ObjectStoreFullError
            raise ObjectStoreFullError(
                f"store full: need {size}, used {self.used}/{self.capacity} "
                "and nothing evictable")

    def stats(self) -> Dict[str, float]:
        return {
            "capacity": self.capacity,
            "used": self.used,
            "num_objects": len(self._objects),
        }

    def shutdown(self) -> None:
        for oid in list(self._objects):
            self.delete(oid)


class NativeObjectStore:
    """ctypes facade over the C++ store (`ray_tpu/native/store.cc`) with the
    same interface as `LocalObjectStore`, plus disk spilling: when the store
    fills, LRU sealed/unpinned objects move to disk and transparently
    restore on the next `info`/read (reference:
    `src/ray/raylet/local_object_manager.h:41`)."""

    _NAME_CAP = 64

    def __init__(self, capacity_bytes: int, *, prefix: str,
                 spill_dir: Optional[str]):
        from ray_tpu.native import native_store_lib

        self._lib = native_store_lib()
        if self._lib is None:
            raise RuntimeError("native store library unavailable")
        self.capacity = capacity_bytes
        self._prefix = prefix
        self._views: Dict[str, Any] = {}   # read_view attachments
        self._deferred_views: List[Any] = []   # closes blocked by views
        self._h = self._lib.rts_open(
            prefix.encode(), (spill_dir or "").encode(), capacity_bytes)
        if not self._h:
            raise RuntimeError("native store init failed")

    @property
    def used(self) -> int:
        return self._lib.rts_used(self._h)

    def create(self, oid: str, size: int) -> str:
        import ctypes

        # The store assigns the segment name: a pre-faulted pooled
        # segment carries a pool name, not an oid-derived one.
        name = ctypes.create_string_buffer(self._NAME_CAP)
        rc = self._lib.rts_create(self._h, oid.encode(), size, name,
                                  self._NAME_CAP)
        if rc == -1:
            raise FileExistsError(f"object {oid[:8]} already sealed")
        if rc == -2:
            raise MemoryError(
                f"object of {size} bytes exceeds store capacity "
                f"{self.capacity}")
        if rc == -3:
            from ray_tpu.exceptions import ObjectStoreFullError
            raise ObjectStoreFullError(
                f"store full: need {size} and nothing evictable")
        if rc not in (0, 1):
            raise OSError(f"native store create failed (rc={rc})")
        return name.value.decode()

    def seal(self, oid: str) -> None:
        if self._lib.rts_seal(self._h, oid.encode()) != 0:
            raise KeyError(f"cannot seal unknown object {oid[:8]}")

    def put_bytes(self, oid: str, data: bytes) -> None:
        if self.contains(oid):
            return
        try:
            self.create(oid, len(data))
        except FileExistsError:
            # Concurrent executor-thread put/pull sealed it between
            # contains() and create(): already present, nothing to do.
            return
        self.write_range(oid, 0, data)
        self.seal(oid)

    def create_from(self, oid: str, chunks) -> None:
        """Buffer-protocol put: chunks land in the segment via pwritev on
        the tmpfs file (kernel copies straight from the source buffers —
        no join, no per-page write faults)."""
        if self.contains(oid):
            return
        size = sum(len(c) for c in chunks)
        try:
            name = self.create(oid, size)
        except FileExistsError:
            return
        try:
            fd = os.open(f"/dev/shm/{name}", os.O_RDWR)
        except OSError:
            off = 0
            for c in chunks:
                self.write_range(oid, off, bytes(c))
                off += len(c)
            self.seal(oid)
            return
        try:
            _pwritev_chunks(fd, chunks)
        finally:
            os.close(fd)
        self.seal(oid)

    def read_view(self, oid: str) -> memoryview:
        """Zero-copy view via a process-local attach of the segment (the
        native store maps it in C; this side maps it again). Same
        lifetime contract as LocalObjectStore.read_view."""
        info = self.info(oid)
        if info is None:
            raise KeyError(f"object {oid[:8]} not present/sealed")
        name, size = info
        shm = self._views.get(name)
        if shm is None:
            shm = self._views[name] = attach_segment(name)
        return shm.buf[:size]

    def contains(self, oid: str) -> bool:
        return bool(self._lib.rts_contains(self._h, oid.encode()))

    def info(self, oid: str) -> Optional[Tuple[str, int]]:
        import ctypes

        name = ctypes.create_string_buffer(self._NAME_CAP)
        size = ctypes.c_uint64()
        rc = self._lib.rts_info(self._h, oid.encode(), name, self._NAME_CAP,
                                ctypes.byref(size))
        if rc != 0:
            return None
        return name.value.decode(), size.value

    def size_of(self, oid: str) -> Optional[int]:
        """Sealed-object size without forcing a spilled copy to restore."""
        n = self._lib.rts_size(self._h, oid.encode())
        return None if n < 0 else n

    def read_bytes(self, oid: str) -> bytes:
        size = self.size_of(oid)
        if size is None:
            raise KeyError(f"object {oid[:8]} not present/sealed")
        return self.read_range(oid, 0, size)

    def read_range(self, oid: str, offset: int, length: int) -> bytes:
        import ctypes

        buf = ctypes.create_string_buffer(max(length, 1))
        n = self._lib.rts_read(self._h, oid.encode(), offset, length, buf)
        if n < 0:
            raise KeyError(f"object {oid[:8]} not present/sealed (rc={n})")
        return buf.raw[:n]

    def write_range(self, oid: str, offset: int, data: bytes) -> None:
        rc = self._lib.rts_write(self._h, oid.encode(), offset,
                                 bytes(data), len(data))
        if rc == -4:
            raise KeyError(f"object {oid[:8]} was not created")
        if rc not in (0,):
            raise OSError(f"native store write failed (rc={rc})")

    def pin(self, oid: str, worker_id: str) -> None:
        self._lib.rts_pin(self._h, oid.encode(), worker_id.encode())

    def unpin(self, oid: str, worker_id: str) -> None:
        self._lib.rts_unpin(self._h, oid.encode(), worker_id.encode())

    def unpin_worker(self, worker_id: str) -> None:
        """Drop every pin a (dead) worker held."""
        self._lib.rts_unpin_worker(self._h, worker_id.encode())

    def delete(self, oid: str) -> bool:
        # Drop this object's read_view attachment with it — otherwise
        # every object ever viewed pins its (unlinked) segment's pages
        # until process shutdown. BufferError (a live view still
        # aliases the mapping) parks the handle for retry on later
        # deletes, mirroring LocalObjectStore's deferred close.
        info = self.info(oid)
        ok = self._lib.rts_delete(self._h, oid.encode()) == 0
        if info is not None:
            shm = self._views.pop(info[0], None)
            if shm is not None:
                self._deferred_views.append(shm)
        if self._deferred_views:
            parked, self._deferred_views = self._deferred_views, []
            for shm in parked:
                try:
                    shm.close()
                except BufferError:
                    self._deferred_views.append(shm)
                except Exception:
                    pass
        return ok

    def object_inventory(self) -> list:
        import ctypes
        import json

        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            need = self._lib.rts_inventory(self._h, buf, cap)
            if need < cap:
                return json.loads(buf.value.decode())
            cap = need + 1024

    def stats(self) -> Dict[str, float]:
        import ctypes

        out = (ctypes.c_uint64 * 5)()
        self._lib.rts_stats(self._h, out)
        return {"capacity": out[0], "used": out[1], "num_objects": out[2],
                "num_spilled": out[3], "spilled_bytes": out[4],
                "backend": "native"}

    def shutdown(self) -> None:
        for shm in self._views.values():
            try:
                shm.close()
            except Exception:
                pass
        self._views.clear()
        if self._h:
            self._lib.rts_shutdown(self._h)
            self._lib.rts_close(self._h)
            self._h = None


def make_store(capacity_bytes: int, *, node_id: str = ""):
    """Store factory: native C++ store when buildable and enabled, else the
    Python one. The prefix tags segment names per store instance so two
    co-located raylets holding the same object id never collide."""
    from ray_tpu.core.config import ray_config

    cfg = ray_config()
    if cfg.native_object_store:
        try:
            import os

            prefix = f"rt{(node_id or str(os.getpid()))[:6]}_"
            spill_dir = None
            if cfg.object_spilling_enabled:
                spill_dir = (cfg.object_spill_dir
                             or f"/tmp/ray_tpu_spill_{node_id or os.getpid()}")
            return NativeObjectStore(capacity_bytes, prefix=prefix,
                                     spill_dir=spill_dir)
        except Exception as exc:  # noqa: BLE001
            logger.warning("native store unavailable (%s); "
                           "using Python store", exc)
    return LocalObjectStore(capacity_bytes)


def _untrack(shm: shared_memory.SharedMemory) -> None:
    """The raylet owns segment lifetime; detach this process's
    resource_tracker registration so it neither warns nor double-unlinks
    at interpreter exit."""
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
    except Exception:
        pass


def _pwritev_chunks(fd: int, chunks) -> None:
    """Scatter-gather write of a chunk list at offset 0 of `fd`."""
    iov = [memoryview(c) for c in chunks if len(c)]
    off = 0
    while iov:
        # Kernel iovec limit: feed at most IOV_MAX (1024) chunks
        # per call; the partial-write loop naturally resumes.
        n = os.pwritev(fd, iov[:1024], off)
        if n <= 0:
            raise OSError("pwritev returned %d" % n)
        off += n
        # Drop fully-written chunks; split a partial one.
        while iov and n >= len(iov[0]):
            n -= len(iov[0])
            iov.pop(0)
        if iov and n:
            iov[0] = iov[0][n:]


class WorkerStoreClient:
    """Worker-side zero-copy access to the node store: control via raylet
    RPC (done by the caller), data via direct shm attach (reference:
    plasma/client.h). Attaches use raw shm_open+mmap (`attach_segment`),
    never `SharedMemory` — the latter's resource-tracker registration
    costs two tracker-pipe writes (~1 ms total on virtualized kernels)
    per attach/release cycle, which dominated get-10MB before round 7."""

    def __init__(self):
        self._attached: Dict[str, Any] = {}

    def write(self, shm_name: str, payload_writer) -> None:
        shm = attach_segment(shm_name)
        try:
            payload_writer(shm.buf)
        finally:
            shm.close()

    def write_chunks(self, shm_name: str, chunks) -> None:
        """Write the object image via pwritev on the tmpfs file.

        Writing through a fresh shared mapping pays a write fault per
        page (~1 ms/MB on this kernel class, even for pre-faulted
        pages); pwritev copies in the kernel with no user page-table
        faults — ~memcpy speed into a pool-prefaulted segment. One
        syscall, scatter-gather over the serialized chunks."""
        try:
            fd = os.open(f"/dev/shm/{shm_name}", os.O_RDWR)
        except OSError:
            # Non-tmpfs shm layout: fall back to the mmap path.
            self.write(shm_name, lambda buf: _copy_chunks_into(buf, chunks))
            return
        try:
            _pwritev_chunks(fd, chunks)
        finally:
            os.close(fd)

    def read(self, shm_name: str, size: int) -> memoryview:
        """Attach and return a zero-copy view. The segment stays attached
        until `release` (the view must not outlive it)."""
        shm = self._attached.get(shm_name)
        if shm is None:
            shm = attach_segment(shm_name)
            self._attached[shm_name] = shm
        return shm.buf[:size]

    def try_attach(self, shm_name: str) -> bool:
        """Attach `shm_name` if it still exists; False when the store
        unlinked it (evicted/spilled). Used by the node-local read
        bypass: attaching is the liveness check — the store never reuses
        a segment name for another object and an existing mapping stays
        valid after eviction (store.cc frozen-mapping guarantee), so
        success here means a later `read` returns the right bytes."""
        if shm_name in self._attached:
            return True
        try:
            shm = attach_segment(shm_name)
        except (FileNotFoundError, OSError, ValueError):
            return False
        self._attached[shm_name] = shm
        return True

    # Mappings whose buffers were still referenced by deserialized
    # zero-copy arrays at release time: parked here and retried on later
    # releases, so a streaming consumer's mappings unmap one step behind
    # consumption instead of accumulating for process lifetime.
    _deferred: list = []

    def _try_close(self, shm) -> None:
        try:
            shm.close()
        except BufferError:
            self._deferred.append(shm)

    def _sweep_deferred(self) -> None:
        parked, self._deferred = self._deferred, []
        for shm in parked:
            self._try_close(shm)

    def release(self, shm_name: str) -> None:
        shm = self._attached.pop(shm_name, None)
        if shm is not None:
            self._try_close(shm)
        self._sweep_deferred()

    def close(self) -> None:
        for shm in self._attached.values():
            self._try_close(shm)
        self._attached.clear()


def _copy_chunks_into(buf, chunks) -> None:
    off = 0
    for c in chunks:
        n = len(c)
        buf[off:off + n] = c
        off += n


class _WriteIntoShm:
    """Adapter: SerializedObject.write_into target backed by an shm buffer."""

    def __init__(self, buf: memoryview):
        self._buf = buf
        self._off = 0

    def __iadd__(self, data) -> "_WriteIntoShm":
        n = len(data)
        self._buf[self._off: self._off + n] = bytes(data) if not isinstance(
            data, (bytes, bytearray, memoryview)) else data
        self._off += n
        return self
