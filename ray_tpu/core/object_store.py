"""Per-node shared-memory object store (plasma equivalent), hosted inside
the raylet process like the reference hosts plasma in-process
(`src/ray/object_manager/plasma/store_runner.h`).

Design: one POSIX shm segment per object (`multiprocessing.shared_memory`),
named from the object id — workers on the node attach by name for zero-copy
reads; only control messages (create/seal/get/delete) cross the RPC socket,
the data plane is mmap. Node-to-node transfer (reference:
`object_manager/` push/pull) fetches the payload over the raylet RPC channel
and re-seals it locally. Capacity is enforced with LRU eviction of
unreferenced sealed objects (reference: `eviction_policy.h`).
"""

from __future__ import annotations

import logging
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from multiprocessing import shared_memory
from typing import Dict, List, Optional, Set, Tuple

logger = logging.getLogger(__name__)

SHM_PREFIX = "rtpu_"


def shm_name_for(object_id_hex: str) -> str:
    # shm names are limited (~31 chars portable); ids are unique enough
    # truncated.
    return SHM_PREFIX + object_id_hex[:24]


@dataclass
class _Entry:
    size: int
    shm: shared_memory.SharedMemory
    sealed: bool = False
    created_at: float = field(default_factory=time.time)
    # pins: worker ids currently using the buffer (get in flight)
    pins: Set[str] = field(default_factory=set)


class LocalObjectStore:
    """The in-raylet store state machine (no I/O here; the raylet wires it
    to RPC handlers)."""

    def __init__(self, capacity_bytes: int):
        self.capacity = capacity_bytes
        self.used = 0
        self._objects: "OrderedDict[str, _Entry]" = OrderedDict()

    # -- create/seal (reference: plasma store.cc ProcessCreateRequests) --
    def create(self, oid: str, size: int) -> str:
        if oid in self._objects:
            entry = self._objects[oid]
            if entry.sealed:
                raise FileExistsError(f"object {oid[:8]} already sealed")
            return entry.shm.name
        if size > self.capacity:
            raise MemoryError(
                f"object of {size} bytes exceeds store capacity "
                f"{self.capacity}")
        self._ensure_space(size)
        name = shm_name_for(oid)
        try:
            shm = shared_memory.SharedMemory(name=name, create=True,
                                             size=max(size, 1))
        except FileExistsError:
            # Stale segment from a dead process: reclaim it.
            stale = shared_memory.SharedMemory(name=name)
            stale.close()
            stale.unlink()
            shm = shared_memory.SharedMemory(name=name, create=True,
                                             size=max(size, 1))
        self._objects[oid] = _Entry(size=size, shm=shm)
        self.used += size
        return shm.name

    def seal(self, oid: str) -> None:
        entry = self._objects.get(oid)
        if entry is None:
            raise KeyError(f"cannot seal unknown object {oid[:8]}")
        entry.sealed = True
        self._objects.move_to_end(oid)

    def put_bytes(self, oid: str, data: bytes) -> None:
        """Create+write+seal in one step (used by the pull path)."""
        if self.contains(oid):
            return
        self.create(oid, len(data))
        entry = self._objects[oid]
        entry.shm.buf[: len(data)] = data
        self.seal(oid)

    # -- read ------------------------------------------------------------
    def contains(self, oid: str) -> bool:
        entry = self._objects.get(oid)
        return entry is not None and entry.sealed

    def info(self, oid: str) -> Optional[Tuple[str, int]]:
        entry = self._objects.get(oid)
        if entry is None or not entry.sealed:
            return None
        self._objects.move_to_end(oid)  # LRU touch
        return entry.shm.name, entry.size

    def read_bytes(self, oid: str) -> bytes:
        entry = self._objects.get(oid)
        if entry is None or not entry.sealed:
            raise KeyError(f"object {oid[:8]} not present/sealed")
        return bytes(entry.shm.buf[: entry.size])

    def read_range(self, oid: str, offset: int, length: int) -> bytes:
        """One transfer chunk (reference: object_manager chunked reads,
        object_manager.h default 1 MiB chunks)."""
        entry = self._objects.get(oid)
        if entry is None or not entry.sealed:
            raise KeyError(f"object {oid[:8]} not present/sealed")
        end = min(offset + length, entry.size)
        return bytes(entry.shm.buf[offset:end])

    def write_range(self, oid: str, offset: int, data: bytes) -> None:
        """Fill part of a created-but-unsealed entry (chunked pull)."""
        entry = self._objects.get(oid)
        if entry is None:
            raise KeyError(f"object {oid[:8]} was not created")
        if entry.sealed:
            return  # concurrent pull already completed it
        entry.shm.buf[offset:offset + len(data)] = data

    def pin(self, oid: str, worker_id: str) -> None:
        entry = self._objects.get(oid)
        if entry is not None:
            entry.pins.add(worker_id)

    def unpin(self, oid: str, worker_id: str) -> None:
        entry = self._objects.get(oid)
        if entry is not None:
            entry.pins.discard(worker_id)

    def object_inventory(self) -> list:
        """Resident-object inventory (reference: `ray memory` /
        object_store_stats)."""
        return [{"object_id": oid, "size": e.size, "sealed": e.sealed,
                 "created_at": e.created_at, "num_pins": len(e.pins)}
                for oid, e in self._objects.items()]

    # -- delete/evict ----------------------------------------------------
    def delete(self, oid: str) -> bool:
        entry = self._objects.pop(oid, None)
        if entry is None:
            return False
        self.used -= entry.size
        try:
            entry.shm.close()
            entry.shm.unlink()
        except FileNotFoundError:
            pass
        return True

    def _ensure_space(self, size: int) -> None:
        if self.used + size <= self.capacity:
            return
        # LRU-evict sealed, unpinned objects (reference: eviction_policy.h).
        for oid in list(self._objects):
            if self.used + size <= self.capacity:
                break
            entry = self._objects[oid]
            if entry.sealed and not entry.pins:
                logger.debug("evicting %s (%d bytes)", oid[:8], entry.size)
                self.delete(oid)
        if self.used + size > self.capacity:
            from ray_tpu.exceptions import ObjectStoreFullError
            raise ObjectStoreFullError(
                f"store full: need {size}, used {self.used}/{self.capacity} "
                "and nothing evictable")

    def stats(self) -> Dict[str, float]:
        return {
            "capacity": self.capacity,
            "used": self.used,
            "num_objects": len(self._objects),
        }

    def shutdown(self) -> None:
        for oid in list(self._objects):
            self.delete(oid)


def _untrack(shm: shared_memory.SharedMemory) -> None:
    """The raylet owns segment lifetime; detach this process's
    resource_tracker registration so it neither warns nor double-unlinks
    at interpreter exit."""
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(shm._name, "shared_memory")  # noqa: SLF001
    except Exception:
        pass


class WorkerStoreClient:
    """Worker-side zero-copy access to the node store: control via raylet
    RPC (done by the caller), data via direct shm attach (reference:
    plasma/client.h)."""

    def __init__(self):
        self._attached: Dict[str, shared_memory.SharedMemory] = {}

    def write(self, shm_name: str, payload_writer) -> None:
        shm = shared_memory.SharedMemory(name=shm_name)
        _untrack(shm)
        try:
            payload_writer(shm.buf)
        finally:
            shm.close()

    def read(self, shm_name: str, size: int) -> memoryview:
        """Attach and return a zero-copy view. The segment stays attached
        until `release` (the view must not outlive it)."""
        shm = self._attached.get(shm_name)
        if shm is None:
            shm = shared_memory.SharedMemory(name=shm_name)
            _untrack(shm)
            self._attached[shm_name] = shm
        return shm.buf[:size]

    # Mappings whose buffers are still referenced by deserialized
    # zero-copy arrays at close time: kept alive for process lifetime so
    # neither close() nor GC raises BufferError (OS reclaims at exit).
    _leaked: list = []

    def release(self, shm_name: str) -> None:
        shm = self._attached.pop(shm_name, None)
        if shm is not None:
            try:
                shm.close()
            except BufferError:
                self._leaked.append(shm)

    def close(self) -> None:
        for shm in self._attached.values():
            try:
                shm.close()
            except BufferError:
                self._leaked.append(shm)
        self._attached.clear()


class _WriteIntoShm:
    """Adapter: SerializedObject.write_into target backed by an shm buffer."""

    def __init__(self, buf: memoryview):
        self._buf = buf
        self._off = 0

    def __iadd__(self, data) -> "_WriteIntoShm":
        n = len(data)
        self._buf[self._off: self._off + n] = bytes(data) if not isinstance(
            data, (bytes, bytearray, memoryview)) else data
        self._off += n
        return self
