"""Config flag registry.

Equivalent of the reference's `RAY_CONFIG(type, name, default)` system
(`src/ray/common/ray_config_def.h`, 209 entries materialized into a singleton,
settable via `RAY_{name}` env vars and a `_system_config` dict from init).
Here: typed declarations, `RAY_TPU_{NAME}` env overrides, and an
`apply_system_config` hook from `ray_tpu.init(_system_config=...)`.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict

_ENV_PREFIX = "RAY_TPU_"


@dataclass
class _Flag:
    name: str
    default: Any
    type: Callable[[str], Any]
    doc: str


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


class Config:
    """Singleton flag store. Declare with `_declare`, read as attributes."""

    _flags: Dict[str, _Flag] = {}

    def __init__(self):
        self._values: Dict[str, Any] = {}

    @classmethod
    def _declare(cls, name: str, default: Any, doc: str = ""):
        if isinstance(default, bool):
            typ: Callable[[str], Any] = _parse_bool
        elif isinstance(default, int):
            typ = int
        elif isinstance(default, float):
            typ = float
        else:
            typ = str
        cls._flags[name] = _Flag(name, default, typ, doc)

    def __getattr__(self, name: str) -> Any:
        flags = type(self)._flags
        if name.startswith("_") or name not in flags:
            raise AttributeError(name)
        if name in self._values:
            return self._values[name]
        env = os.environ.get(_ENV_PREFIX + name.upper())
        if env is not None:
            return flags[name].type(env)
        return flags[name].default

    def apply_system_config(self, system_config: Dict[str, Any] | str | None):
        if system_config is None:
            return
        if isinstance(system_config, str):
            system_config = json.loads(system_config)
        for k, v in system_config.items():
            if k not in type(self)._flags:
                raise ValueError(f"Unknown system config key: {k}")
            self._values[k] = v

    def serialize(self) -> str:
        """Serialize overrides so child processes inherit driver-set config."""
        return json.dumps(self._values)


_D = Config._declare

# -- core ---------------------------------------------------------------
_D("max_direct_call_object_size", 100 * 1024,
   "Objects <= this many bytes are returned inline / kept in the in-process "
   "memory store instead of the shared-memory store (reference: "
   "ray_config_def.h max_direct_call_object_size).")
_D("object_store_memory_bytes", 2 * 1024**3,
   "Default per-node object store capacity.")
_D("object_store_full_delay_ms", 100, "Retry delay when the store is full.")
_D("task_retry_delay_ms", 0, "Delay before retrying a failed task.")
_D("max_task_retries_default", 3, "Default retries for idempotent tasks.")
_D("worker_lease_timeout_ms", 30_000, "Lease request timeout.")
_D("num_workers_soft_limit", 0, "0 = #CPUs on the node.")
_D("worker_startup_timeout_s", 60.0, "Max time to wait for a worker process.")
_D("health_check_period_ms", 1000,
   "GCS->raylet health check interval (reference: gcs_health_check_manager).")
_D("health_check_failure_threshold", 5,
   "Missed health checks before a node is marked dead.")
_D("gcs_rpc_timeout_s", 30.0, "Client-side timeout for GCS RPCs.")
_D("gcs_reconnect_backoff_base_ms", 50.0,
   "First retry delay of the GCS-reconnect backoff. Retries grow "
   "exponentially from here with FULL jitter (each sleep is uniform in "
   "[0, min(cap, base*2^attempt)]) so 100 clients losing the GCS at once "
   "de-synchronize instead of hammering the restarted server in "
   "lockstep (the classic thundering-herd fix; reference: gcs_client "
   "reconnect backoff).")
_D("gcs_reconnect_backoff_max_ms", 5000.0,
   "Cap on the GCS-reconnect backoff delay.")
_D("gcs_restart_node_grace_ms", 0,
   "After a GCS restart recovers persisted node records, a recovered "
   "node is not declared dead until this grace has passed without a "
   "heartbeat — every raylet needs at least one full heartbeat interval "
   "to find the restarted server before the health loop may judge it. "
   "0 = derive from health_check_period_ms * health_check_failure_"
   "threshold.")
_D("gcs_ha_lease_ms", 1500.0,
   "HA GCS leadership lease. A follower that hears nothing from the "
   "leader for lease * (1 + jitter) stands for election; a leader that "
   "cannot reach a quorum for a full lease steps down. Bounds failover "
   "time from below (a kill -9'd leader is replaced within roughly one "
   "jittered lease) and stale-leader serving time from above.")
_D("gcs_ha_renew_ms", 500.0,
   "How often the HA GCS leader renews its lease (heartbeats the "
   "replicas over the same RPC plane the WAL replicates on). Keep well "
   "under gcs_ha_lease_ms (classic rule: a third).")
_D("gcs_ha_replicate_timeout_ms", 2000.0,
   "Per-peer timeout for one replication/vote RPC. A peer that misses "
   "it counts as no-ack for that frame (the quorum may still land).")
_D("owner_unreachable_grace_s", 5.0,
   "How long a borrower-side pull tolerates an unreachable object owner "
   "before declaring the owner dead: within the grace the pull retries "
   "(transient blip, GCS failover), past it the get fails loudly with "
   "OwnerDiedError instead of hanging or mislabeling the loss "
   "(reference: OBJECT_UNRECOVERABLE_OWNER_DIED).")
_D("pg_reconcile_interval_s", 5.0,
   "How often a raylet reconciles its committed placement-group bundles "
   "against the GCS table, returning reservations whose group is "
   "REMOVED/INFEASIBLE/lost — the backstop that stops a mid-2PC crash "
   "(owner or GCS) from leaking capacity cluster-wide.")
_D("pg_stuck_commit_s", 60.0,
   "A committed bundle whose placement group never reached CREATED "
   "within this window is returned by the reconciler (owner died "
   "between commit and the CREATED CAS).")
_D("raylet_heartbeat_period_ms", 250, "Raylet->GCS resource report interval.")
_D("cluster_view_refresh_ms", 1000,
   "How often a raylet refreshes its full cluster view (the node table "
   "feeding spillback targeting and dead-address checks), decoupled "
   "from the heartbeat. Round-15 1000-node profiling found the "
   "per-heartbeat get_nodes() fetch is the GCS dispatch wall at scale: "
   "N nodes × (1/period) full-table replies per second is O(N^2) "
   "records/s — at N=1000 that alone saturated the sim's GCS loop. "
   "Liveness still rides every heartbeat; the view tolerates seconds "
   "of staleness (the retry/spillback discipline re-resolves).")
_D("actor_restart_backoff_ms", 1000, "Backoff between actor restarts.")
_D("metrics_report_interval_ms", 2000, "Metrics agent scrape/export interval.")
_D("task_events_flush_interval_ms", 1000,
   "Task event buffer flush interval (reference: task_event_buffer.h).")
_D("max_pending_lease_requests_per_scheduling_category", 10,
   "Pipelined lease requests per scheduling key (reference name identical).")
_D("worker_pipeline_depth", 8,
   "Tasks pushed to one leased worker before its first reply returns. "
   "Keeps the worker's (single-threaded) execution queue fed across the "
   "push/reply round trip instead of idling it for one RTT per task "
   "(reference: lease reuse in direct_task_transport.cc OnWorkerIdle).")
_D("scheduler_spread_threshold", 0.5,
   "Hybrid policy utilization threshold below which tasks pack on the local "
   "node (reference: hybrid_scheduling_policy.h).")
_D("object_timeout_ms", 100, "Plasma get poll interval.")
_D("native_object_store", True,
   "Use the C++ shared-memory object store (ray_tpu/native/store.cc) when "
   "the toolchain can build it; falls back to the Python store otherwise.")
_D("object_spilling_enabled", True,
   "Spill LRU objects to disk instead of evicting when the store is full "
   "(native store only; reference: local_object_manager.h SpillObjects).")
_D("object_spill_dir", "",
   "Spill directory; empty = /tmp/ray_tpu_spill_<node_id>.")
_D("memory_monitor_refresh_ms", 250, "OOM monitor interval; 0 disables.")
_D("memory_usage_threshold", 0.95, "Node memory fraction that triggers the OOM killer.")
_D("lineage_reconstruction", True,
   "Owner-side lineage reconstruction of lost objects (round 15): the "
   "owner retains the wire-encoded spec of any task whose result was "
   "store-sealed (and pins the task's argument objects) while a return "
   "ref lives, and re-executes it through the normal dispatch tiers "
   "when the last copy is lost (holder node died, evicted everywhere). "
   "Borrowers' in-flight gets block-and-retry through the re-execution "
   "instead of failing. Disabling restores the pre-round-15 behavior: "
   "loss surfaces immediately as the typed ObjectLostError "
   "(reference: task_manager.h lineage pinning + "
   "object_recovery_manager.h).")
_D("lineage_reconstruction_budget", 8,
   "Hard cap on per-object re-executions, regardless of max_retries: "
   "a flapping node must not re-run a task unboundedly. Exhausting "
   "the budget degrades the next loss to ObjectLostError.")
_D("cgraph_restart", True,
   "Compiled-graph recovery (round 15): when a loop actor of a "
   "compiled DAG dies, recompile its schedule onto the restarted "
   "replacement (bounded by the actors' max_task_retries budget) and "
   "resume — in-flight executions still fail with the actor-death "
   "error, but the graph accepts new executes instead of staying "
   "poisoned until teardown. Disabling restores permanent poisoning.")
_D("borrow_escrow_s", 600.0,
   "How long a result-embedded ref stays escrow-pinned in its owner "
   "process, bridging the gap between shipping a result and the "
   "consumer's register_borrow (reference: reference_count.h borrowing "
   "protocol, here time-bounded).")

_D("object_transfer_chunk_bytes", 1 << 20,
   "Inter-node object transfer chunk size (reference: ObjectBufferPool "
   "chunking, object_manager.h).")
_D("lease_idle_linger_s", 0.05,
   "How long an idle lease is cached for reuse before returning to the "
   "raylet (reference: idle lease cache in direct_task_transport).")
_D("pipeline_service_threshold_s", 0.03,
   "Deep lease pipelining only engages for workers whose observed "
   "push->reply time is under this; slower tasks parallelize via fresh "
   "leases and spillback.")
_D("log_monitor_interval_s", 0.3,
   "Worker log tail/publish interval (reference: log_monitor.py).")
_D("pip_install_timeout_s", 600.0,
   "Timeout for a runtime-env pip install.")
_D("borrow_commit_timeout_s", 35.0,
   "Deadline for registering retained arg borrows with owners at task "
   "completion (reference: borrowed-refs report in the task reply).")

# -- task-plane fast paths (round 8) -------------------------------------
_D("task_inline_execution", True,
   "Same-process inline execution of tiny tasks: when a task's options "
   "are pure defaults, its ObjectRef args are all locally resolved, and "
   "the function's observed exec-time EMA sits below "
   "task_inline_threshold_ms, run it on the caller thread instead of "
   "leasing a worker (reference: local-mode short circuit, promoted to "
   "a per-task dynamic decision). Disabling restores pure-remote "
   "submission for every task.")
_D("task_inline_threshold_ms", 1.0,
   "Exec-time EMA ceiling for inline execution, in milliseconds. The "
   "EMA starts unknown (first calls go remote and report exec_us in "
   "their replies), so a long or blocking task is never inlined on "
   "spec. Break-even on an N-core box is roughly "
   "per-task-overhead / (N - 1).")
_D("lease_batching", True,
   "Batch worker-lease grants: one request_worker_leases RPC asks the "
   "raylet for up to lease_batch_max workers for a submission burst, "
   "collapsing the per-task lease round trip (reference: the pipelined "
   "lease requests of direct_task_transport, batched).")
_D("lease_batch_max", 8,
   "Max leases requested in one batched lease RPC.")
_D("submit_ring", False,
   "Worker-direct dispatch rings (round 10): when a lease grant "
   "advertises ring capability and the leased worker is node-local, "
   "the driver and the WORKER process attach a dedicated SPSC shm "
   "ring pair — task-spec deltas ride the forward ring (zero "
   "syscalls per task steady-state; doorbell byte only on the "
   "empty->non-empty edge), replies (exec_us, attribution splits) "
   "ride the twin ring. The raylet only brokers the lease; it never "
   "sits on the per-task path (round 8's raylet-forwarded variant "
   "lost that hop's latency back). Off by default; the RPC push path "
   "is the byte-identical fallback for every condition a ring cannot "
   "carry (non-local, oversize, full, streaming, setup failure).")
_D("submit_ring_slots", 128,
   "Slot count of each submission/completion ring.")
_D("submit_ring_slot_bytes", 8192,
   "Slot payload capacity; a spec delta larger than this falls back "
   "to the RPC push path.")
_D("ring_backstop_poll_ms", 50.0,
   "Base period of the ring consumers' lost-doorbell backstop poll. "
   "Adaptive (ring.AdaptivePoll): holds this period while traffic "
   "flows, backs off to 250 ms after 20 consecutive idle polls, "
   "snaps back on traffic — the fixed 50 ms poll of round 8 both "
   "wasted wakeups at idle and capped worst-case latency under a "
   "lost doorbell.")
_D("lease_return_batching", True,
   "Batch worker-lease returns: one return_worker_leases RPC hands a "
   "burst's finished leases back to the raylet (mirror of the "
   "round-8 grant batch, coalesced through the same deferred-pump "
   "discipline). Disabling restores one return_worker RPC per lease.")

# -- caller-thread dispatch tier (round 16) ------------------------------
_D("task_caller_dispatch", True,
   "Caller-thread ring dispatch (round 16, the fifth dispatch tier): "
   "when a submit is ring-eligible against an already-leased, "
   "already-ringed worker whose spec template is registered, the "
   "CALLER thread encodes the template delta and publishes it onto "
   "the worker's forward ring directly — no loop wakeup, no "
   "coroutine. The SPSC single-producer invariant holds through ring "
   "ownership handoff (ring.ProducerLatch): the loop thread cedes a "
   "ring's producer side to the caller under the latch and reclaims "
   "it for fallback/teardown. Any miss (no ringed worker, template "
   "unregistered, unresolved deps, full ring past the bounded wait) "
   "falls through to the loop-hop submit queue byte-identically. "
   "Only meaningful with submit_ring on; disabling restores the "
   "round-10 loop-hop path exactly (the latch is never even taken).")
_D("caller_push_wait_ms", 5.0,
   "Bounded backpressure wait of a caller-thread enqueue against a "
   "FULL forward ring with completions in flight: slots free at the "
   "worker's service rate, so a short wait rides out a burst "
   "overrun instead of dumping the overflow onto the loop-hop path. "
   "Past the budget the submit falls back (counted under "
   "submit.caller_fallback). 0 = fall back immediately.")
_D("ring_busy_poll_us", 100,
   "Busy-poll handoff budget for ring consumers, in microseconds "
   "(round 16, ROADMAP 3c): after a non-empty drain the consumer "
   "spins up to this long for the next entry before handing back to "
   "epoll — under sustained traffic the producer's next publish "
   "lands inside the spin window and the dequeue side never pays "
   "the epoll-wakeup/OS-scheduling latency. Only engaged while "
   "traffic is flowing (a drain that found entries), so an idle "
   "ring costs nothing. 0 disables the spin entirely.")
_D("inline_cost_model_v2", True,
   "Arg-size-conditional inline cost model (round 16, ROADMAP 3b): "
   "per-fn exec EMAs are keyed by (fn, arg-size bucket) so a "
   "function that is tiny on small args but slow on big ones "
   "inlines exactly its small-arg shapes; an unknown bucket "
   "inherits eligibility downward from a known-tiny LARGER bucket "
   "(bigger args observed cheap implies smaller args are). "
   "Inlining also becomes scheduler-revocable under caller-thread "
   "dispatch pressure (see inline_revoke_pressure): when the caller "
   "thread is the ring producer for a hot burst, stealing it for "
   "inline execution starves the dispatch tier that keeps every "
   "worker fed. Disabling restores the round-8 single-scalar EMA.")
_D("inline_revoke_pressure", 200,
   "Caller-thread enqueues within one revoke window that revoke the "
   "inline tier (pressure signal: the caller thread is busy being a "
   "ring producer). Revocation lasts one window and re-arms while "
   "the pressure sustains; remote dispatch serves the revoked calls.")
_D("inline_revoke_window_ms", 100.0,
   "Sliding window (and revocation duration) for the caller-pressure "
   "inline revocation, in milliseconds.")

# -- flight recorder (round 12 observability) ----------------------------
_D("flight_recorder", True,
   "Per-process flight recorder (core/flight.py): a fixed-capacity "
   "ring of recent events (submit tiers, lease traffic, SPSC ring "
   "primitives, worker exec, engine steps, GC pauses, loop-lag "
   "heartbeats) plus the stall watchdog that snapshots the ring and "
   "an all-threads stack dump when an event loop blocks past "
   "stall_threshold_ms. Always-on by design (Dapper-style low-overhead "
   "recording; the perf guard pins overhead <=10% of tasks/s); "
   "disabling restores the zero-cost-off path at every call site.")
_D("flight_events", 4096,
   "Flight-recorder ring capacity (most recent N events kept).")
_D("flight_heartbeat_ms", 50.0,
   "Loop-lag watchdog heartbeat period: each watched event loop "
   "schedules a beat this often and records its own scheduling delay.")
_D("stall_threshold_ms", 100.0,
   "A watched loop's heartbeat going overdue past this opens a stall "
   "episode: all-threads stack dump captured mid-stall, ring snapshot "
   "+ lag measurement written as a JSON report under the session log "
   "dir, surfaced at GET /api/stalls.")

# -- metrics pipeline (round 17 observability) ---------------------------
_D("metrics_pipeline", True,
   "Pushed cluster metrics pipeline (core/metrics_ts.py): every process "
   "delta-encodes its metrics-registry snapshots into a bounded ring and "
   "ships them to its raylet with the existing report_metrics push; the "
   "raylet folds all worker batches plus its own runtime gauges into ONE "
   "coalesced payload piggybacked on the existing GCS heartbeat — fleet "
   "cost O(nodes), not O(processes). Zero-cost-off like the flight "
   "recorder: disabling restores the bespoke per-raylet poll path.")
_D("metrics_ts_ring", 128,
   "Per-process pending-batch ring capacity (unacked capture intervals "
   "retained across raylet hiccups before the oldest are dropped).")
_D("metrics_retention_points", 512,
   "GCS retention ring: data points kept per series (at the default "
   "2 s capture interval this is ~17 min of history per series).")
_D("metrics_max_series", 2000,
   "GCS series-cardinality cap; pushes for new series past the cap are "
   "counted as dropped instead of registered (label explosions degrade "
   "to a visible counter, not unbounded memory).")
_D("metrics_poll_fallback", False,
   "Use the legacy per-raylet get_metrics poll path for dashboard "
   "/metrics and autoscaler gauge reads instead of the GCS fold. "
   "Kept for one release as an escape hatch; delete with it.")
_D("slo_eval_period_ms", 1000,
   "GCS SLO burn-rate evaluation period (multi-window state machine "
   "over the retention store; rides the health-check loop).")
_D("timeline_max_events", 20000,
   "Bounded-payload cap for GET /api/timeline: at most this many trace "
   "events (most recent kept) are shipped per response; metadata "
   "events are exempt. Override per-request with max_events=.")

# -- tensor plane --------------------------------------------------------
_D("tpu_slice_gang_scheduling", True,
   "Treat a TPU slice as an atomic gang for placement-group scheduling.")
_D("collective_timeout_s", 300.0, "Out-of-graph collective op timeout.")
_D("gcs_wal_compact_bytes", 4 * 1024 * 1024,
   "GCS write-ahead-log size that triggers snapshot compaction.")
_D("object_pull_budget_bytes", 256 * 1024 * 1024,
   "Byte budget for concurrent inbound object transfers "
   "(reference: pull_manager.h admission control).")
_D("object_push_concurrency", 8,
   "Max concurrent outbound object-chunk serves per raylet "
   "(reference: push_manager.h bounded in-flight pushes).")

_config = Config()


def ray_config() -> Config:
    return _config
