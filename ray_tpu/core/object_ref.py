"""ObjectRef: the user-facing handle to an object in the distributed store.

Reference equivalent: `python/ray/_raylet.pyx` ObjectRef + the ownership model
of `src/ray/core_worker/reference_count.h` — each ref knows its owner; local
Python refcount drives release (`__del__` -> runtime.remove_local_reference);
serializing a ref inside a task argument or object value registers a borrow.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Optional

from ray_tpu.core.ids import ObjectID

_thread_local = threading.local()


@contextlib.contextmanager
def _serialization_context(ref_hook: Optional[Callable[[Any], None]]):
    prev = getattr(_thread_local, "ref_hook", None)
    _thread_local.ref_hook = ref_hook
    try:
        yield
    finally:
        _thread_local.ref_hook = prev


class ObjectRef:
    __slots__ = ("_id", "_owner", "_runtime", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: Optional[bytes] = None,
                 runtime=None, skip_adding_local_ref: bool = False):
        self._id = object_id
        self._owner = owner  # opaque owner address (worker id bytes / addr tuple)
        self._runtime = runtime
        if runtime is not None and not skip_adding_local_ref:
            runtime.add_local_reference(object_id)

    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self):
        return self._id.task_id()

    @property
    def owner_address(self):
        return self._owner

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self.hex()})"

    def __del__(self):
        rt = self._runtime
        if rt is not None:
            try:
                # Finalizers must not take runtime locks (GC can fire
                # them while those locks are held): prefer the deferred
                # lock-free release path when the runtime has one.
                release = getattr(rt, "deferred_release", None) \
                    or rt.remove_local_reference
                release(self._id)
            except Exception:
                pass

    def future(self):
        """A concurrent.futures.Future resolving to the object's value."""
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _fill():
            from ray_tpu.core.worker import get as _get
            try:
                fut.set_result(_get(self))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=_fill, daemon=True).start()
        return fut

    def __await__(self):
        """Await support inside asyncio actors / drivers."""
        import asyncio
        return asyncio.wrap_future(self.future()).__await__()

    def __reduce__(self):
        hook = getattr(_thread_local, "ref_hook", None)
        if hook is not None:
            hook(self)
        return (_rebuild_object_ref, (self._id.binary(), self._owner))


def _rebuild_object_ref(binary: bytes, owner):
    from ray_tpu.core.worker import current_runtime

    rt = current_runtime(or_none=True)
    ref = ObjectRef(ObjectID(binary), owner, rt, skip_adding_local_ref=True)
    if rt is not None:
        rt.on_ref_deserialized(ref)
    hook = getattr(_thread_local, "ref_hook", None)
    if hook is not None:
        hook(ref)
    return ref
