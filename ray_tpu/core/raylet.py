"""Raylet: per-node scheduler daemon + object-store host.

Reference equivalent: `src/ray/raylet/` — `NodeManager` (worker leasing
`node_manager.cc:1767`, scheduling via `ClusterTaskManager`/
`LocalTaskManager`), `WorkerPool` (`worker_pool.h:156`), and the in-process
plasma store. The hybrid scheduling policy (pack locally until a utilization
threshold, then spread; `scheduling/policy/hybrid_scheduling_policy.h:50`)
drives spillback exactly like the reference: a lease reply may redirect the
client to another node, which re-requests there.
"""

from __future__ import annotations

import asyncio
import logging
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from ray_tpu.core.config import ray_config
from ray_tpu.core.gcs.client import GcsClient
from ray_tpu.core.object_store import NativeObjectStore, make_store
from ray_tpu.core.rpc import RpcClient, RpcServer, ServerConnection

logger = logging.getLogger(__name__)


class _Worker:
    def __init__(self, worker_id: str, proc: subprocess.Popen):
        self.worker_id = worker_id
        self.proc = proc
        self.address: Optional[str] = None
        self.state = "starting"  # starting | idle | leased | actor | dead
        self.lease_id: Optional[str] = None
        self.ready = asyncio.Event()
        self.actor_id: Optional[str] = None
        self.actor_job_id: Optional[str] = None
        self.actor_detached = False
        self.held: Dict[str, float] = {}  # resources held by active lease
        self.bundle_key: Optional[str] = None  # PG bundle the lease drew from
        self.chip_ids: List[int] = []  # TPU chips granted to this lease
        self.granted_at = 0.0  # lease grant time (OOM policy: newest dies)
        self.log_path: Optional[str] = None
        self.log_offset = 0  # how far the log monitor has shipped
        self.lease_job_id: Optional[str] = None  # job of the active lease
        self.blocked = False  # task blocked in get(): CPU released
        # A node-local driver attached a direct dispatch ring to this
        # worker (round 10): pinned against idle recycling until the
        # driver detaches — a returned worker must never carry a stale
        # ring into another lease.
        self.ring_attached = False


class _Bundle:
    """One reserved placement-group bundle on this node (reference:
    `src/ray/raylet/placement_group_resource_manager.h` — prepared bundles
    hold node resources; commit makes them leasable; return releases)."""

    def __init__(self, resources: Dict[str, float], chips: List[int]):
        self.total = dict(resources)
        self.available = dict(resources)
        self.chips = list(chips)  # reserved, currently-unleased chip ids
        self.committed = False
        self.removed = False
        self.prepared_at = time.monotonic()
        self.committed_at = 0.0  # set by handle_commit_bundle

    def in_use(self) -> Dict[str, float]:
        return {k: self.total[k] - self.available.get(k, 0.0)
                for k in self.total
                if self.total[k] - self.available.get(k, 0.0) > 1e-9}


class NodeLedger:
    """Per-node resource accounting + placement-group 2PC + the
    spillback policy — the scheduling brain of a raylet, factored out of
    the process machinery (workers, object store, sockets) so
    `core/simcluster.py` can run a hundred of these in one process
    against a real GcsServer and exercise the REAL paths a 100-node
    failure hits.

    Consumers provide: `node_id`, `resources_total`,
    `resources_available`, `_bundles` ({key: _Bundle}), `_chips_free`
    (list of free TPU chip ids), `_cluster_view` ({node_id: node info}),
    and `_gcs` (a GcsClient) for bundle reconciliation."""

    # throttles _maybe_reconcile_bundles; instance attr once it runs
    _last_bundle_reconcile = 0.0

    def _fits(self, avail: Dict[str, float],
              demand: Dict[str, float]) -> bool:
        return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())

    def _acquire(self, demand: Dict[str, float]) -> None:
        for k, v in demand.items():
            self.resources_available[k] = self.resources_available.get(
                k, 0.0) - v

    def _release(self, demand: Dict[str, float]) -> None:
        for k, v in demand.items():
            self.resources_available[k] = min(
                self.resources_available.get(k, 0.0) + v,
                self.resources_total.get(k, v))

    def _pick_spillback(self, demand: Dict[str, float]) -> Optional[str]:
        """Best remote node that can host the demand now (spread by most
        available, the scorer's tie-break in the reference)."""
        best, best_score = None, -1.0
        for node_id, info in self._cluster_view.items():
            if node_id == self.node_id or not info.get("alive"):
                continue
            avail = info.get("resources_available", {})
            if not self._fits(avail, demand):
                continue
            score = sum(avail.get(k, 0.0) for k in ("CPU", "TPU"))
            if score > best_score:
                best, best_score = info["address"], score
        return best

    def _feasible_locally(self, demand: Dict[str, float]) -> bool:
        return self._fits(self.resources_total, demand)

    def _maybe_spillback(self, demand: Dict[str, float],
                         spillback_count: int) -> Optional[str]:
        """Hybrid policy (hybrid_scheduling_policy.h): pack locally
        while below the spread threshold; above it — or when local
        can't fit — spill to a viable remote. The spillback chain is
        bounded so two saturated raylets with stale views of each
        other can't ping-pong a lease forever. One helper shared by
        the single and batched lease handlers, so the policy cannot
        diverge between them."""
        if spillback_count >= 2:
            return None
        local_fits = self._fits(self.resources_available, demand)
        utilization = 1.0 - (
            self.resources_available.get("CPU", 0.0)
            / max(self.resources_total.get("CPU", 1.0), 1e-9))
        if (not local_fits or utilization
                > ray_config().scheduler_spread_threshold):
            return self._pick_spillback(demand)
        return None

    # ------------------------------------------------------------------
    # placement-group bundles: 2PC reserve/commit/return (reference:
    # node_manager.cc:1821 HandlePrepareBundleResources, :1837
    # HandleCommitBundleResources + placement_group_resource_manager.h)
    # ------------------------------------------------------------------
    async def handle_prepare_bundle(self, conn: ServerConnection, *,
                                    pg_id: str, bundle_index: int,
                                    resources: Dict[str, float]
                                    ) -> Dict[str, Any]:
        key = f"{pg_id}:{bundle_index}"
        if key in self._bundles and not self._bundles[key].removed:
            return {"ok": True}  # idempotent re-prepare
        demand = {k: float(v) for k, v in resources.items() if v}
        if not self._fits(self.resources_available, demand):
            return {"ok": False,
                    "reason": f"insufficient resources for bundle {key}: "
                              f"need {demand}, have "
                              f"{self.resources_available}"}
        self._acquire(demand)
        n_chips = int(demand.get("TPU", 0))
        chips, self._chips_free[:] = (self._chips_free[:n_chips],
                                      self._chips_free[n_chips:])
        self._bundles[key] = _Bundle(demand, chips)
        return {"ok": True}

    async def handle_commit_bundle(self, conn: ServerConnection, *,
                                   pg_id: str, bundle_index: int) -> bool:
        b = self._bundles.get(f"{pg_id}:{bundle_index}")
        if b is None or b.removed:
            return False
        b.committed = True
        b.committed_at = time.monotonic()
        return True

    async def handle_return_bundle(self, conn: ServerConnection, *,
                                   pg_id: str, bundle_index: int) -> bool:
        return self._return_bundle(f"{pg_id}:{bundle_index}")

    def _return_bundle(self, key: str) -> bool:
        b = self._bundles.get(key)
        if b is None or b.removed:
            return False
        # Unused share back to the pool now; b.total shrinks to the in-use
        # share, which drains back as each outstanding lease ends
        # (_release_lease_resources) — empty total deletes the entry.
        b.removed = True
        self._release(b.available)
        self._chips_free.extend(b.chips)
        b.total = b.in_use()
        b.available = {}
        b.chips = []
        if not b.total:
            del self._bundles[key]
        return True

    def _reap_stale_prepares(self) -> None:
        """Drop prepared-but-never-committed bundles (owner died between
        the 2PC phases) so their reservations don't leak."""
        cutoff = time.monotonic() - 30.0
        for key, b in list(self._bundles.items()):
            if not b.committed and not b.removed and b.prepared_at < cutoff:
                logger.warning("returning stale uncommitted bundle %s", key)
                self._return_bundle(key)

    async def _maybe_reconcile_bundles(self) -> None:
        """Return committed bundles whose placement group the GCS no
        longer stands behind — the cluster-wide rollback that a crash
        anywhere in the 2PC (owner mid-commit, GCS mid-CAS, another
        raylet mid-prepare) cannot perform itself. _reap_stale_prepares
        covers the reserve phase; this covers the commit phase:

        - group REMOVED / INFEASIBLE / unknown -> the reservation is a
          leak, return it now;
        - group still not CREATED `pg_stuck_commit_s` after our commit
          -> the owner died between commit and the CREATED CAS, return.

        Throttled to one GCS round trip per `pg_reconcile_interval_s`;
        a GCS outage skips the pass (no false rollbacks on 'unknown
        because unreachable')."""
        committed = {key.split(":", 1)[0]
                     for key, b in self._bundles.items()
                     if b.committed and not b.removed}
        if not committed:
            return
        cfg = ray_config()
        now = time.monotonic()
        if now - self._last_bundle_reconcile < cfg.pg_reconcile_interval_s:
            return
        self._last_bundle_reconcile = now
        for pg_id in committed:
            try:
                info = await self._gcs.get_placement_group(pg_id)
            except Exception:
                return  # control plane unreachable: judge nothing
            state = (info or {}).get("state")
            if state == "CREATED":
                # The group stands — but only behind the bundles its
                # location table names. A commit that landed here during
                # a crashed GCS reschedule pass whose final CAS chose a
                # DIFFERENT node is an orphan reservation: nothing will
                # ever lease or return it.
                locs = (info or {}).get("bundle_locations") or []
                for key, b in list(self._bundles.items()):
                    if (not key.startswith(pg_id + ":") or not b.committed
                            or b.removed):
                        continue
                    try:
                        idx = int(key.rsplit(":", 1)[1])
                    except ValueError:
                        continue
                    if (idx < len(locs)
                            and locs[idx].get("node_id") != self.node_id
                            and now - getattr(b, "committed_at", now)
                            >= cfg.pg_stuck_commit_s):
                        # The commit-age grace mirrors the PENDING
                        # branch: a FRESH mislocated commit is most
                        # likely an in-flight reschedule pass that
                        # prepared+committed here while our CREATED
                        # read was already in flight (stale snapshot)
                        # — returning it would strand the location
                        # table the pass is about to write. A genuine
                        # crash orphan persists past the window and
                        # still comes back.
                        logger.warning(
                            "returning bundle %s committed here but "
                            "located on %s (rescheduled elsewhere)",
                            key, locs[idx].get("node_id"))
                        from ray_tpu.core import flight

                        if flight.enabled:
                            flight.instant("pg", "pg.rollback", arg=key)
                        self._return_bundle(key)
                continue
            if state == "RESCHEDULING":
                # A member node died and the GCS is re-placing the LOST
                # bundles; surviving reservations (ours) must hold — a
                # rollback here would be the capacity the group still
                # legitimately owns. The rescheduler's terminal CAS
                # (back to CREATED) re-enables the location check above.
                continue
            if state == "PENDING":
                if any(now - getattr(b, "committed_at", now)
                       < cfg.pg_stuck_commit_s
                       for key, b in self._bundles.items()
                       if key.startswith(pg_id + ":") and b.committed
                       and not b.removed):
                    continue  # owner may still be driving the 2PC
                # Expire the group ATOMICALLY before touching the
                # ledger: a slow-but-live owner may be racing us toward
                # its CREATED CAS, and returning the bundle first would
                # manufacture a half-reserved CREATED group. Whoever
                # wins the PENDING CAS defines the outcome — if the
                # owner just won, our CAS misses and we keep the
                # reservation; if we win, the owner's CREATED CAS
                # misses and it rolls back cleanly.
                try:
                    won = await self._gcs.update_placement_group(
                        pg_id, {"state": "INFEASIBLE",
                                "detail": "committed bundle expired "
                                          "waiting for CREATED "
                                          f"(> {cfg.pg_stuck_commit_s}s)"},
                        expect_state="PENDING")
                except Exception:
                    return  # control plane unreachable: judge nothing
                if not won:
                    continue  # owner terminated it; re-judge next pass
            for key, b in list(self._bundles.items()):
                if (key.startswith(pg_id + ":") and b.committed
                        and not b.removed):
                    logger.warning(
                        "returning orphaned committed bundle %s "
                        "(group state=%s)", key, state)
                    from ray_tpu.core import flight

                    if flight.enabled:
                        flight.instant("pg", "pg.rollback", arg=key)
                    self._return_bundle(key)


class _PendingLease:
    def __init__(self, demand: Dict[str, float], is_actor: bool,
                 scheduling_key: str,
                 bundle_key: Optional[str] = None,
                 request_id: Optional[str] = None,
                 spillback_count: int = 0,
                 job_id: Optional[str] = None):
        self.demand = demand
        self.is_actor = is_actor
        self.scheduling_key = scheduling_key
        self.bundle_key = bundle_key
        self.request_id = request_id
        self.spillback_count = spillback_count
        self.job_id = job_id
        self.conn: Optional[ServerConnection] = None
        self.created_at = time.monotonic()
        self.future: asyncio.Future = asyncio.get_event_loop().create_future()


class _PullManager:
    """Admission control for inbound object transfers (reference:
    `object_manager/pull_manager.h:52` — pulls activate under a byte
    budget, the rest queue). Smallest-first wake order: a giant transfer
    must not head-of-line-block the small objects a blocked `get` needs.
    """

    def __init__(self, budget_bytes: int):
        import heapq as _hq  # noqa: F401  (documents the waiter heap)

        self.budget = max(1, int(budget_bytes))
        self.in_use = 0
        self._waiters: list = []   # heap of (size, seq, Event)
        self._seq = 0
        # local_reads counts node-local resolutions that bypassed
        # admission entirely: the byte budget exists to pace inbound
        # REMOTE transfers, and a local shm read must never queue behind
        # them (nor charge the budget) — pinned by
        # tests/test_unit_pull_manager.py.
        self.stats = {"admitted": 0, "queued": 0, "peak_bytes": 0,
                      "active": 0, "local_reads": 0}

    async def admit(self, size: int) -> int:
        """Blocks until `size` bytes of transfer budget are granted.
        Returns the granted size (a single object larger than the whole
        budget is clamped: it transfers alone, not never)."""
        import heapq

        size = min(int(size), self.budget)
        # Purge cancelled waiters first: with nothing in flight there is
        # no future release() to sweep them, and a live heap of only
        # dead entries must not push new admits onto the queue forever.
        while self._waiters and not self._waiters[0][3][0]:
            heapq.heappop(self._waiters)
        if not self._waiters and self.in_use + size <= self.budget:
            self.in_use += size
        else:
            ev = asyncio.Event()
            # Mutable liveness flag: a cancelled waiter marks itself
            # dead so the wake loop skips it WITHOUT charging in_use —
            # a leaked charge here permanently shrinks the pull budget
            # (ADVICE r5 low).
            entry = (size, self._seq + 1, ev, [True])
            self._seq += 1
            heapq.heappush(self._waiters, entry)
            self.stats["queued"] += 1
            try:
                await ev.wait()
            except asyncio.CancelledError:
                if ev.is_set():
                    # Granted between the wake and this resumption: the
                    # bytes were already charged — return them (and wake
                    # anyone they now fit).
                    self._return_bytes(size)
                else:
                    entry[3][0] = False  # still queued: mark dead
                raise
        self.stats["admitted"] += 1
        self.stats["active"] += 1
        self.stats["peak_bytes"] = max(self.stats["peak_bytes"],
                                       self.in_use)
        return size

    def _return_bytes(self, size: int) -> None:
        import heapq

        self.in_use -= size
        while self._waiters:
            wsize, _, ev, alive = self._waiters[0]
            if not alive[0]:
                heapq.heappop(self._waiters)  # cancelled: drop, no charge
                continue
            if self.in_use + wsize > self.budget:
                break
            heapq.heappop(self._waiters)
            self.in_use += wsize
            ev.set()

    def release(self, size: int) -> None:
        self.stats["active"] -= 1
        self._return_bytes(size)


class Raylet(NodeLedger):
    def __init__(self, *, node_id: str, gcs_address: str,
                 resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 object_store_memory: Optional[int] = None,
                 is_head: bool = False):
        self.node_id = node_id
        self.gcs_address = gcs_address
        self.is_head = is_head
        self.labels = labels or {}
        self.resources_total = dict(resources)
        self.resources_available = dict(resources)
        self._rpc = RpcServer(self, host, port)
        self._gcs = GcsClient(gcs_address)
        self.store = make_store(
            object_store_memory or ray_config().object_store_memory_bytes,
            node_id=node_id)
        self._workers: Dict[str, _Worker] = {}
        self._idle: List[_Worker] = []
        self._pending: List[_PendingLease] = []
        # PG bundles reserved on this node, keyed "pg_id:bundle_index".
        self._bundles: Dict[str, _Bundle] = {}
        # Per-instance TPU chip ids (reference: resource_instance_set.h —
        # fractional TPU demands don't get chip isolation).
        self._chips_free: List[int] = list(
            range(int(resources.get("TPU", 0))))
        self._next_lease = 0
        self._cluster_view: Dict[str, Dict[str, Any]] = {}
        self._raylet_clients: Dict[str, RpcClient] = {}
        self._worker_clients: Dict[str, RpcClient] = {}
        self._tasks: List[asyncio.Task] = []
        self._monitors: Dict[str, asyncio.Task] = {}
        # worker_id -> (monotonic push time, app-metric snapshot)
        self._worker_metrics: Dict[str, tuple] = {}
        # lease request_id -> [(lease_id, worker_id), ...], for cancel-
        # after-grant (a client that timed out must not leak the
        # worker); list-valued since one batched request can grant
        # several leases under the same request_id.
        self._recent_grants: Dict[str, list] = {}
        # live lease_id -> (worker_id, granting connection): a client
        # that dies (not merely times out) can never use or return its
        # grants, so disconnect reclaims them.
        self._lease_conns: Dict[str, tuple] = {}
        # At-least-once protection for the lease plane (round 15 chaos):
        # a duplicated/retried request_worker_lease(s) must be served
        # the ORIGINAL grant reply, never a second worker. Grant replies
        # cache by request_id (spillback/error replies are not cached —
        # re-deciding them acquires nothing and a cached spillback
        # could pin a client to a dead verdict forever); concurrent
        # duplicates share the in-flight future.
        self._lease_reply_cache: Dict[str, Dict[str, Any]] = {}
        self._lease_inflight: Dict[str, asyncio.Future] = {}
        # request_ids the client cancelled: a cancel can land BETWEEN
        # the grant (recorded in _recent_grants, future resolved) and
        # the handler coroutine resuming to cache its reply — caching
        # then would serve a later duplicate a grant whose workers the
        # cancel already reclaimed (and possibly re-leased).
        self._cancelled_lease_requests: Dict[str, None] = {}
        self._stopping = False
        # worker_id -> why the raylet killed it ("oom"); lets the task
        # submitter surface a typed retriable OutOfMemoryError instead of
        # a generic crash (reference: worker_killing_policy.h + the
        # OOM-kill task-failure reason in node_manager.cc).
        self._death_causes: Dict[str, str] = {}
        # Object-manager flow control (reference: pull_manager.h
        # admission under a byte budget; push_manager.h bounded
        # concurrent outbound chunks).
        self._pulls = _PullManager(ray_config().object_pull_budget_bytes)
        self._inflight_pulls: Dict[str, asyncio.Future] = {}
        # Extra flight-record sources on this node beyond spawned
        # workers: DRIVER processes register their RPC address here so
        # the dashboard's merged timeline/stall views cover the submit
        # side too (pruned when a scrape finds the process gone).
        self._flight_sources: Dict[str, float] = {}
        self._push_sem: Optional[asyncio.Semaphore] = None
        self._push_waiters = 0
        # Metrics pipeline (round 17): workers' delta batches queue here
        # (already worker/role-labeled) until the next heartbeat folds
        # them — with the raylet's own runtime gauges — into the ONE
        # coalesced `metrics=` payload piggybacked on that heartbeat.
        # Bounded like the per-process ring; cleared only on GCS ack.
        from ray_tpu.core import metrics_ts

        self._metrics_pending: List[Dict[str, Any]] = []
        self._ts_recorder = metrics_ts.Recorder(
            capacity=ray_config().metrics_ts_ring)
        self._last_ts_capture = 0.0
        self._metrics_pushes = 0       # heartbeats that carried metrics
        self._metrics_hb_intervals = 0  # heartbeat-loop iterations

    @property
    def address(self) -> str:
        return self._rpc.address

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    async def start(self) -> None:
        await self._rpc.start()
        # Flight recorder (round 12): GC pauses + loop lag on the
        # raylet's own event loop become attributable events; its
        # dump_flight_record handler fans out to the node's workers.
        from ray_tpu.core import flight

        if not ray_config().flight_recorder:
            flight.enabled = False
        if flight.enabled:
            flight.configure(
                capacity=ray_config().flight_events,
                stall_threshold_ms=ray_config().stall_threshold_ms,
                heartbeat_ms=ray_config().flight_heartbeat_ms)
            flight.set_role("raylet", node_id=self.node_id)
            flight.install_gc_hook()
            self._flight_watch = flight.watch_loop(
                asyncio.get_running_loop(), name="raylet-loop")
        else:
            self._flight_watch = None
        await self._gcs.connect()
        await self._register_with_gcs()
        await self._gcs.subscribe("node", self._on_node_update)
        await self._gcs.subscribe("job", self._on_job_update)
        self._tasks.append(asyncio.ensure_future(self._heartbeat_loop()))
        if ray_config().memory_monitor_refresh_ms > 0:
            self._tasks.append(asyncio.ensure_future(
                self._memory_monitor_loop()))
        self._tasks.append(asyncio.ensure_future(self._log_monitor_loop()))
        # Prestart a few workers so first-task latency is registration-bound,
        # not fork/exec-bound (reference: PrestartWorkers,
        # node_manager.cc:1782).
        for _ in range(min(int(self.resources_total.get("CPU", 1)), 4)):
            self._spawn_worker()
        logger.info("raylet %s listening on %s", self.node_id[:8],
                    self.address)

    async def stop(self) -> None:
        # Gate worker (re)spawning first: a leased worker dying mid-stop
        # otherwise triggers _try_dispatch -> _spawn_worker, and the fresh
        # worker outlives us stuck in a connect-retry loop (orphan).
        self._stopping = True
        if getattr(self, "_flight_watch", None) is not None:
            from ray_tpu.core import flight

            flight.unwatch_loop(self._flight_watch)
        for t in self._tasks + list(self._monitors.values()):
            t.cancel()
        for w in self._workers.values():
            if w.proc.poll() is None:
                w.proc.terminate()
        # One shared grace window for the whole pool: the supervisor
        # SIGKILLs *us* after ~3 s, and any worker still alive at that
        # point would be orphaned — so escalate to SIGKILL well inside
        # that budget rather than waiting per worker.
        deadline = time.monotonic() + 1.5
        for w in self._workers.values():
            try:
                w.proc.wait(timeout=max(0.05, deadline - time.monotonic()))
            except Exception:
                w.proc.kill()
        self.store.shutdown()
        await self._rpc.stop()
        await self._gcs.close()
        # Final sweep: anything that slipped in between the first loop and
        # the RPC server going down dies hard.
        for w in self._workers.values():
            if w.proc.poll() is None:
                w.proc.kill()

    async def _register_with_gcs(self) -> None:
        reply = await self._gcs.register_node(
            node_id=self.node_id, address=self.address,
            object_store_address=self.address,
            resources=self.resources_total, labels=self.labels,
            is_head=self.is_head)
        if (reply or {}).get("was_dead"):
            # The cluster declared us dead (transient partition) and has
            # already restarted our actors / reconstructed our objects
            # elsewhere. Surviving actor workers here are stale replicas
            # holding chips and CPUs — reap them before resuming.
            logger.warning("re-registered after being declared dead; "
                           "reaping stale actor workers")
            for worker in list(self._workers.values()):
                if worker.actor_id and worker.proc.poll() is None:
                    worker.proc.terminate()

    def _fold_metrics_batch(self) -> Optional[list]:
        """The node's coalesced pipeline payload for this heartbeat:
        the raylet's own runtime gauges (captured at the report
        interval, delta-encoded through the same Recorder workers use)
        plus every queued worker batch. None = nothing to push."""
        from ray_tpu.core import metrics_ts

        if not (metrics_ts.enabled and ray_config().metrics_pipeline):
            return None
        now = time.monotonic()
        if (now - self._last_ts_capture
                >= ray_config().metrics_report_interval_ms / 1000.0):
            self._last_ts_capture = now
            try:
                self._ts_recorder.capture(self._runtime_metrics())
            except Exception:
                logger.warning("runtime metrics capture failed",
                               exc_info=True)
        own = self._ts_recorder.pending()
        if not own and not self._metrics_pending:
            return None
        batch = [{"t": e["t"],
                  "series": [[it[0], it[1], dict(it[2], role="raylet")]
                             + list(it[3:]) for it in e["series"]]}
                 for e in own]
        batch.extend(self._metrics_pending)
        # Remember what was shipped so only THAT is acked — workers may
        # append more while the heartbeat RPC is in flight.
        self._metrics_sent = (len(own), len(self._metrics_pending))
        return batch

    def _ack_metrics_batch(self) -> None:
        n_own, n_workers = getattr(self, "_metrics_sent", (0, 0))
        self._ts_recorder.ack(n_own)
        del self._metrics_pending[:n_workers]
        self._metrics_pushes += 1

    async def _heartbeat_loop(self) -> None:
        period = ray_config().raylet_heartbeat_period_ms / 1000.0
        last_view = 0.0
        while True:
            try:
                metrics_batch = self._fold_metrics_batch()
                self._metrics_hb_intervals += 1
                # Batched worker state (ROADMAP 4d): the whole worker
                # table rides the node heartbeat — one RPC per raylet
                # tick, never one per worker — so at N=1000 the GCS
                # dispatch rate stays O(nodes), not O(workers), and
                # worker churn stays off the HA quorum write path.
                worker_batch = [
                    {"worker_id": w.worker_id, "state": w.state,
                     "actor_id": w.actor_id, "lease_id": w.lease_id}
                    for w in self._workers.values()
                    if w.state != "dead"]
                ok = await self._gcs.heartbeat(
                    self.node_id, self.resources_available,
                    load={"pending": len(self._pending),
                          # Demand shapes drive the autoscaler's
                          # bin-packing (reference: load metrics'
                          # resource_load_by_shape).
                          "pending_demands": [dict(p.demand) for p in
                                              self._pending[:100]]},
                    metrics=metrics_batch,
                    workers=worker_batch)
                if ok is True and metrics_batch:
                    # Clear-on-ack: a failed/unrecognized heartbeat
                    # leaves the batch queued for the next interval.
                    self._ack_metrics_batch()
                if ok is False:
                    # GCS restarted (nodes aren't persisted) or declared
                    # us dead: re-register so scheduling resumes (GCS FT
                    # re-registration contract).
                    logger.info("GCS does not recognize this node; "
                                "re-registering")
                    await self._register_with_gcs()
                # Cluster-view refresh is throttled SEPARATELY from the
                # liveness heartbeat: fetching the full node table per
                # beat is O(N^2) records/s across the fleet and was the
                # GCS dispatch wall at 1000 simulated nodes (PROFILE
                # round 11). Spillback/dead-address consumers tolerate
                # a stale view — their retry discipline re-resolves.
                now = time.monotonic()
                if (now - last_view
                        >= ray_config().cluster_view_refresh_ms / 1000.0):
                    self._cluster_view = {
                        n["node_id"]: n
                        for n in await self._gcs.get_nodes()}
                    last_view = now
            except Exception:
                logger.warning("heartbeat to GCS failed", exc_info=True)
            self._reap_stale_prepares()
            try:
                await self._maybe_reconcile_bundles()
            except Exception:
                logger.warning("bundle reconcile failed", exc_info=True)
            self._spill_infeasible_pending()
            await asyncio.sleep(period)

    # -- OOM defense (reference: memory_monitor.h:52 +
    # worker_killing_policy.h:34) ---------------------------------------
    def _oom_candidates(self):
        from ray_tpu.core.memory_monitor import WorkerCandidate

        out = []
        for w in self._workers.values():
            if w.proc.poll() is not None or w.state not in ("leased",
                                                            "actor"):
                continue
            conn = None
            if w.lease_id is not None:
                pair = self._lease_conns.get(w.lease_id)
                conn = pair[1].conn_id if pair else None
            out.append(WorkerCandidate(
                worker_id=w.worker_id, pid=w.proc.pid,
                task_id=w.actor_id or w.lease_id,
                owner_address=(f"actor:{w.actor_id}" if w.actor_id
                               else f"conn:{conn}"),
                granted_at=w.granted_at,
                # Plain leased tasks are retriable (the submitter's
                # retry loop re-runs them); actors restart through
                # their own max_restarts machinery — last resort.
                retriable=w.actor_id is None))
        return out

    async def _memory_monitor_loop(self) -> None:
        from ray_tpu.core.memory_monitor import MemoryMonitor

        cfg = ray_config()
        monitor = MemoryMonitor(cfg.memory_usage_threshold,
                                self._oom_candidates)
        period = cfg.memory_monitor_refresh_ms / 1000.0
        while True:
            await asyncio.sleep(period)
            try:
                victim = monitor.tick()
            except Exception:
                logger.warning("memory monitor tick failed",
                               exc_info=True)
                continue
            if victim is None:
                continue
            worker = self._workers.get(victim.worker_id)
            if worker is not None and worker.proc.poll() is None:
                self._death_causes[worker.worker_id] = "oom"
                while len(self._death_causes) > 256:
                    self._death_causes.pop(next(iter(self._death_causes)))
                worker.proc.kill()  # _monitor_worker reclaims the lease

    async def handle_worker_death_cause(self, conn: ServerConnection, *,
                                        worker_id: str) -> Optional[str]:
        return self._death_causes.get(worker_id)

    # -- worker log streaming (reference: _private/log_monitor.py:103
    # tails per-worker files and publishes over GCS pubsub; drivers
    # print via _private/worker.py:812) ---------------------------------
    def _collect_new_log_lines(self) -> List[Dict[str, Any]]:
        entries = []
        for w in self._workers.values():
            if not w.log_path:
                continue
            try:
                size = os.path.getsize(w.log_path)
                if size <= w.log_offset:
                    continue
                with open(w.log_path, "rb") as f:
                    f.seek(w.log_offset)
                    chunk = f.read(min(size - w.log_offset, 1 << 20))
            except OSError:
                continue
            # Ship whole lines only; a partial trailing line waits for
            # its newline (next tick). A full 1 MiB chunk with no newline
            # is a pathological line: ship it truncated rather than
            # re-reading the same megabyte forever.
            cut = chunk.rfind(b"\n")
            if cut < 0:
                if len(chunk) < (1 << 20):
                    continue
                cut = len(chunk) - 1
            w.log_offset += cut + 1
            lines = chunk[:cut].decode("utf-8", "replace").splitlines()
            if len(lines) > 200:
                dropped = len(lines) - 200
                lines = [f"... [{dropped} lines truncated by the log "
                         f"monitor]"] + lines[-200:]
            if lines:
                entries.append({
                    "worker_id": w.worker_id, "pid": w.proc.pid,
                    "actor_id": w.actor_id,
                    # Tag with the job the worker serves so a driver
                    # only prints ITS workers (cross-driver isolation).
                    "job_id": w.actor_job_id or w.lease_job_id,
                    "lines": lines,
                })
        return entries

    async def handle_get_worker_logs(self, conn: ServerConnection, *,
                                     worker: Optional[str] = None,
                                     tail_bytes: int = 16384
                                     ) -> List[Dict[str, Any]]:
        """Log aggregation read path (dashboard `/api/logs`): the tail
        of each worker's log file on THIS node, newest bytes first cut
        to whole lines. `worker` filters by worker-id prefix. Distinct
        from the streaming monitor: this reads on demand from offset
        zero of the tail, so lines already shipped to drivers are still
        inspectable."""
        out: List[Dict[str, Any]] = []
        budget = max(1024, min(int(tail_bytes), 1 << 20))
        for w in list(self._workers.values()):
            if worker and not w.worker_id.startswith(worker):
                continue
            if not w.log_path:
                continue
            try:
                size = os.path.getsize(w.log_path)
                with open(w.log_path, "rb") as f:
                    f.seek(max(0, size - budget))
                    chunk = f.read(budget)
            except OSError:
                continue
            if size > budget:
                # Drop the partial first line of a mid-file seek.
                cut = chunk.find(b"\n")
                chunk = chunk[cut + 1:] if cut >= 0 else chunk
            out.append({
                "node_id": self.node_id,
                "worker_id": w.worker_id,
                "pid": w.proc.pid,
                "actor_id": w.actor_id,
                "job_id": w.actor_job_id or w.lease_job_id,
                "path": w.log_path,
                "lines": chunk.decode("utf-8", "replace").splitlines(),
            })
        return out

    async def handle_register_flight_source(
            self, conn: ServerConnection, *, address: str) -> bool:
        """A driver on this node announces its RPC address so
        `dump_flight_record` fans out to it too — workers are known
        from registration, but drivers otherwise never appear in the
        merged timeline (and a driver-loop stall is exactly the kind
        of episode the dashboard must show)."""
        self._flight_sources[address] = time.monotonic()
        return True

    async def handle_dump_flight_record(
            self, conn: ServerConnection, *,
            window_s: Optional[float] = None,
            include_events: bool = True) -> Dict[str, Any]:
        """Node-level flight-record collection (dashboard
        `/api/timeline` + `/api/stalls`, mirror of `get_worker_logs`):
        this raylet's own ring plus, over the same RPC name, every
        live worker's and registered driver's — concurrent fan-out
        with a short per-process timeout, so one wedged process (the
        very thing being debugged) cannot stall the endpoint for the
        rest of the node."""
        from ray_tpu.core import flight

        records: List[Dict[str, Any]] = [
            flight.dump(window_s=window_s,
                        include_events=include_events)]

        async def one(address: str, prune: bool = False):
            try:
                client = await self._worker_client(address)
                return await client.call(
                    "dump_flight_record", window_s=window_s,
                    include_events=include_events, timeout=5.0)
            except Exception:  # noqa: BLE001 — dead/wedged process
                if prune:
                    self._flight_sources.pop(address, None)
                return None

        targets = [one(w.address) for w in self._workers.values()
                   if w.address and w.proc.poll() is None]
        targets += [one(addr, prune=True)
                    for addr in list(self._flight_sources)]
        results = await asyncio.gather(*targets)
        records.extend(r for r in results if isinstance(r, dict))
        return {"node_id": self.node_id, "records": records}

    async def _log_monitor_loop(self) -> None:
        interval = ray_config().log_monitor_interval_s
        while True:
            await asyncio.sleep(interval)
            try:
                entries = self._collect_new_log_lines()
                if entries:
                    await self._gcs.publish(
                        "worker_logs",
                        {"node_id": self.node_id, "entries": entries})
            except Exception:
                logger.debug("log monitor tick failed", exc_info=True)

    # A lease queued this long on a locally-feasible-but-busy node gets
    # re-spilled to a remote with room (reference: the cluster task
    # manager re-evaluates queued work against the cluster view; without
    # this, an unlucky spillback distribution strands a lease behind a
    # full node while a sibling node sits idle).
    QUEUE_RESPILL_AFTER_S = 2.0

    def _spill_infeasible_pending(self) -> None:
        """Queued leases this node can never satisfy get redirected once
        the refreshed cluster view shows a viable remote; feasible ones
        that have waited past QUEUE_RESPILL_AFTER_S re-spill too; others
        wait, with a periodic diagnostic (reference: the cluster task
        manager's 'cannot be scheduled' warning)."""
        now = time.monotonic()
        for pending in list(self._pending):
            if pending.bundle_key is not None:
                continue
            if self._feasible_locally(pending.demand):
                if pending.spillback_count >= 2:
                    # Anti-ping-pong: a busy-node lease that already
                    # bounced twice settles where it is. (Locally
                    # INFEASIBLE leases are exempt — this node can never
                    # run them, so redirecting is their only way out.)
                    continue
                if now - pending.created_at < self.QUEUE_RESPILL_AFTER_S:
                    continue
                if self._fits(self.resources_available, pending.demand):
                    # Resources are free — we're only waiting on a worker
                    # to finish cold-spawning; re-spilling would strand
                    # it and bounce the lease around the cluster.
                    continue
            remote = self._pick_spillback(pending.demand)
            if remote is not None and not pending.future.done():
                self._pending.remove(pending)
                pending.future.set_result({"spillback": remote})
            elif now - getattr(pending, "last_warn", 0.0) > 10.0:
                pending.last_warn = now
                logger.warning(
                    "lease demand %s cannot be scheduled: no node in the "
                    "cluster has these resources (waiting for the cluster "
                    "to change)", pending.demand)

    def _on_node_update(self, data) -> None:
        if not data.get("alive"):
            from ray_tpu.core import flight

            if flight.enabled:
                # Mirrors the GCS-side node.dead event into a process
                # the dashboard's timeline fan-out actually scrapes.
                flight.instant("node", "node.dead",
                               arg=(data.get("node_id") or "")[:8])
            self._cluster_view.pop(data.get("node_id"), None)

    def _on_job_update(self, data) -> None:
        """Job finished: reap local non-detached actor workers of that
        job (reference: GcsActorManager::OnJobFinished ->
        KillActor on the owning node)."""
        if not data.get("finished"):
            return
        job_id = data.get("job_id")
        for worker in list(self._workers.values()):
            if (worker.actor_id and worker.actor_job_id == job_id
                    and not worker.actor_detached
                    and worker.proc.poll() is None):
                logger.info("reaping actor worker %s (job %s finished)",
                            worker.worker_id[:8], (job_id or "")[:8])
                worker.proc.terminate()

    # ------------------------------------------------------------------
    # worker pool (reference: worker_pool.h)
    # ------------------------------------------------------------------
    def _spawn_worker(self) -> Optional[_Worker]:
        if self._stopping:
            return None
        import uuid

        worker_id = uuid.uuid4().hex
        env = dict(os.environ)
        env["RAY_TPU_NODE_ID"] = self.node_id
        # Unbuffered stdio: a task's print() must reach the log file (and
        # the driver, via the log monitor) while the task runs, not when
        # the worker exits.
        env["PYTHONUNBUFFERED"] = "1"
        cmd = [sys.executable, "-m", "ray_tpu.core.worker_main",
               "--raylet", self.address, "--gcs", self.gcs_address,
               "--worker-id", worker_id, "--node-id", self.node_id]
        # Workers ALWAYS log to a file: the log monitor tails these and
        # streams lines to drivers (reference: log_monitor.py:103).
        log_dir = os.environ.get("RAY_TPU_LOG_DIR")
        if not log_dir:
            log_dir = f"/tmp/ray_tpu_worker_logs_{self.node_id[:8]}"
            os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"worker-{worker_id[:8]}.log")
        out = open(log_path, "ab")
        proc = subprocess.Popen(cmd, env=env, stdout=out, stderr=out)
        out.close()  # the child holds the fd; the tailer reopens by path
        worker = _Worker(worker_id, proc)
        worker.log_path = log_path
        self._workers[worker_id] = worker
        self._monitors[worker_id] = asyncio.ensure_future(
            self._monitor_worker(worker))
        return worker

    async def _monitor_worker(self, worker: _Worker) -> None:
        while worker.proc.poll() is None:
            await asyncio.sleep(0.2)
        code = worker.proc.returncode
        if worker.state != "dead":
            worker.state = "dead"
            if worker in self._idle:
                self._idle.remove(worker)
            if worker.held:
                self._release_lease_resources(worker)
                self._try_dispatch()
            if worker.actor_id:
                try:
                    await self._gcs.update_actor(worker.actor_id, {
                        "state": "DEAD",
                        "death_cause": f"worker exited with code {code}",
                    })
                except Exception:
                    pass
            logger.info("worker %s exited with code %s",
                        worker.worker_id[:8], code)

    async def handle_register_worker(self, conn: ServerConnection, *,
                                     worker_id: str, address: str) -> bool:
        worker = self._workers.get(worker_id)
        if worker is None:
            return False
        worker.address = address
        worker.state = "idle"
        worker.ready.set()
        self._idle.append(worker)
        conn.metadata["worker_id"] = worker_id
        self._try_dispatch()
        return True

    # ------------------------------------------------------------------
    # leasing + scheduling (reference: node_manager.cc:1767 +
    # cluster_task_manager.h:70 + hybrid_scheduling_policy.h:50)
    # ------------------------------------------------------------------
    async def handle_request_worker_lease(
            self, conn: ServerConnection, *,
            req: Optional[dict] = None,
            resources: Optional[Dict[str, float]] = None,
            scheduling_key: str = "", is_actor: bool = False,
            spillback_count: int = 0,
            bundle: Optional[List[Any]] = None,
            request_id: Optional[str] = None,
            job_id: Optional[str] = None) -> Dict[str, Any]:
        if req is not None:
            # Typed wire path (core/wire.py LeaseRequest) — validated
            # decode; the flat-kwarg form stays for in-process callers.
            from ray_tpu.core.wire import from_wire

            lr = from_wire(req, expect="LeaseRequest")
            resources, scheduling_key = lr.resources, lr.scheduling_key
            is_actor, spillback_count = lr.is_actor, lr.spillback_count
            bundle, request_id = lr.bundle, lr.request_id
            job_id = lr.job_id
        return await self._deduped_lease_reply(
            request_id,
            lambda: self._lease_single(
                conn, resources=resources, scheduling_key=scheduling_key,
                is_actor=is_actor, spillback_count=spillback_count,
                bundle=bundle, request_id=request_id, job_id=job_id))

    async def _deduped_lease_reply(self, request_id: Optional[str],
                                   factory) -> Dict[str, Any]:
        """At-least-once lease dispatch: a duplicate delivery (network
        retry, fault-injected redelivery) of a request_id whose grant
        already happened gets the CACHED reply; one racing the original
        awaits the same in-flight future. Without this, each duplicate
        of a batched lease request grants a fresh worker set that no
        client will ever use or return."""
        if not request_id:
            return await factory()
        cached = self._lease_reply_cache.get(request_id)
        if cached is not None:
            return cached
        inflight = self._lease_inflight.get(request_id)
        if inflight is not None:
            return await asyncio.shield(inflight)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._lease_inflight[request_id] = fut
        try:
            reply = await factory()
            if ((reply.get("granted") or reply.get("grants"))
                    and request_id not in self._cancelled_lease_requests):
                self._lease_reply_cache[request_id] = reply
                while len(self._lease_reply_cache) > 512:
                    self._lease_reply_cache.pop(
                        next(iter(self._lease_reply_cache)))
            if not fut.done():
                fut.set_result(reply)
            return reply
        except BaseException as e:
            if not fut.done():
                fut.set_exception(e)
                # A shielded duplicate may never retrieve it.
                try:
                    fut.exception()
                except Exception:
                    pass
            raise
        finally:
            self._lease_inflight.pop(request_id, None)

    async def _lease_single(
            self, conn: ServerConnection, *,
            resources: Dict[str, float], scheduling_key: str,
            is_actor: bool, spillback_count: int,
            bundle: Optional[List[Any]], request_id: Optional[str],
            job_id: Optional[str]) -> Dict[str, Any]:
        demand = {k: float(v) for k, v in resources.items() if v}
        if logger.isEnabledFor(logging.DEBUG):
            logger.debug(
                "lease request %s actor=%s spill=%d avail=%s idle=%d "
                "pending=%d", demand, is_actor, spillback_count,
                {k: round(v, 1)
                 for k, v in self.resources_available.items()
                 if k in ("CPU", "TPU")},
                len(self._idle), len(self._pending))
        if bundle is not None:
            # Leases against a PG bundle are pinned to this node: no
            # spillback, fail fast if the bundle is gone or can't fit.
            key = f"{bundle[0]}:{bundle[1]}"
            b = self._bundles.get(key)
            if b is None or b.removed:
                return {"error": "bundle_missing",
                        "detail": f"bundle {key} not reserved on this node"}
            if not self._fits(b.total, demand):
                return {"error": "infeasible",
                        "detail": f"demand {demand} exceeds bundle total "
                                  f"{b.total}"}
            pending = _PendingLease(demand, is_actor, scheduling_key,
                                    bundle_key=key, request_id=request_id,
                                    spillback_count=spillback_count,
                                    job_id=job_id)
            pending.conn = conn
            self._pending.append(pending)
            self._try_dispatch()
            return await pending.future
        remote = self._maybe_spillback(demand, spillback_count)
        if remote is not None:
            return {"spillback": remote}
        # Locally-infeasible demands queue rather than fail (reference:
        # infeasible tasks wait in the cluster task manager until the
        # cluster changes — e.g. the node with that resource is still
        # registering); the heartbeat loop re-evaluates them for spillback.
        pending = _PendingLease(demand, is_actor, scheduling_key,
                                request_id=request_id,
                                spillback_count=spillback_count,
                                job_id=job_id)
        pending.conn = conn
        self._pending.append(pending)
        self._try_dispatch()
        return await pending.future

    async def handle_request_worker_leases(
            self, conn: ServerConnection, *,
            req: dict) -> Dict[str, Any]:
        """Batched lease grants (round 8): one RPC asks for up to
        `req.count` workers. Everything immediately grantable (idle
        worker + resources, through the SAME `_try_dispatch` machinery
        single leases use) returns at once as a partial grant — the
        client re-pumps for the shortfall; when nothing is grantable
        now, workers are prestarted for the whole burst width and the
        request degrades to the single-lease semantics (queueing,
        hybrid-policy spillback), so contention behavior matches the
        unbatched path — which queued one pending per task and thereby
        spawned the burst's workers in parallel."""
        from ray_tpu.core.wire import from_wire

        lr = from_wire(req, expect="LeaseRequest")
        return await self._deduped_lease_reply(
            lr.request_id, lambda: self._lease_batch(conn, lr))

    async def _lease_batch(self, conn: ServerConnection,
                           lr) -> Dict[str, Any]:
        count = max(1, int(lr.get("count") or 1))
        demand = {k: float(v) for k, v in lr.resources.items() if v}
        # Hybrid-policy parity with the single-lease path: a node past
        # the spread threshold (or that can't fit the demand) spills
        # the whole batch rather than packing onto a local idle worker
        # the unbatched path would have sent away.
        if lr.bundle is None:
            remote = self._maybe_spillback(demand, lr.spillback_count)
            if remote is not None:
                return {"spillback": remote}
        grants: List[Dict[str, Any]] = []
        if lr.bundle is None:
            while len(grants) < count:
                granted = self._try_grant_now(
                    demand, lr.is_actor, lr.scheduling_key, conn,
                    lr.request_id, lr.job_id)
                if granted is None:
                    break
                grants.append(granted)
        if grants:
            return {"grants": grants}
        # Dry node with FREE resources (the shortage is worker
        # processes, not CPUs): prestart workers for the whole burst
        # before degrading to one queued single lease — the probe only
        # ever exposed a pending depth of 1 to _try_dispatch's spawn
        # loop, so without this an N-task cold burst would spawn its
        # workers serially, one per grant round trip (the unbatched
        # path queued N pendings and spawned N at once). When resources
        # are the constraint, spawning would only stack idle processes.
        if (lr.bundle is None
                and self._fits(self.resources_available, demand)):
            starting = sum(1 for w in self._workers.values()
                           if w.state == "starting")
            for _ in range(count - starting):
                if not self._can_start_worker(for_actor=lr.is_actor):
                    break
                self._spawn_worker()
        # Degrade to single-lease semantics — straight to the inner
        # path: this call is already inside the batch's dedup scope.
        return await self._lease_single(
            conn, resources=lr.resources,
            scheduling_key=lr.scheduling_key, is_actor=lr.is_actor,
            spillback_count=lr.spillback_count, bundle=lr.bundle,
            request_id=lr.request_id, job_id=lr.job_id)

    def _try_grant_now(self, demand: Dict[str, float], is_actor: bool,
                       scheduling_key: str, conn, request_id, job_id
                       ) -> Optional[Dict[str, Any]]:
        """One immediate grant through `_try_dispatch`, or None without
        queueing anything (the batch handler withdraws the probe)."""
        pending = _PendingLease(demand, is_actor, scheduling_key,
                                request_id=request_id, job_id=job_id)
        pending.conn = conn
        self._pending.append(pending)
        self._try_dispatch()
        if pending.future.done():
            reply = pending.future.result()
            granted = reply.get("granted")
            if granted is not None:
                return granted
            return None
        try:
            self._pending.remove(pending)
        except ValueError:
            pass
        pending.future.cancel()
        return None

    # ------------------------------------------------------------------
    # metrics (reference: stats/metric_defs.h runtime metrics + the
    # per-node metrics agent, _private/metrics_agent.py)
    # ------------------------------------------------------------------
    async def handle_report_metrics(self, conn: ServerConnection, *,
                                    worker_id: str, snapshot: list,
                                    ts_batch: Optional[list] = None) -> bool:
        """A worker/driver process pushes its app-metric snapshot (and,
        round 17, its delta-encoded time-series batch — queued here
        until the next GCS heartbeat folds the whole node)."""
        self._worker_metrics[worker_id] = (time.monotonic(), snapshot)
        if ts_batch:
            role = ("driver" if worker_id.startswith("driver-")
                    else "worker")
            wid8 = worker_id[:8]
            for entry in ts_batch:
                self._metrics_pending.append({
                    "t": entry.get("t"),
                    "series": [
                        [it[0], it[1],
                         dict(it[2], worker_id=wid8, role=role)]
                        + list(it[3:])
                        for it in entry.get("series", ())]})
            # Bounded like every other ring: a GCS outage must not grow
            # raylet memory without limit. Oldest entries go first.
            cap = max(1, ray_config().metrics_ts_ring) * 4
            overflow = len(self._metrics_pending) - cap
            if overflow > 0:
                del self._metrics_pending[:overflow]
        return True

    def _runtime_metrics(self) -> list:
        """The raylet's own runtime gauges, registry-snapshot shaped
        (shared by the legacy get_metrics scrape and the pushed
        pipeline's per-interval capture)."""
        stats = self.store.stats()
        runtime = [{
            "name": f"ray_tpu_{key}", "type": "gauge", "help": help_,
            "samples": [{"tags": {}, "value": float(value)}],
        } for key, value, help_ in [
            ("object_store_used_bytes", stats.get("used", 0),
             "Bytes resident in the node object store"),
            ("object_store_capacity_bytes", stats.get("capacity", 0),
             "Node object store capacity"),
            ("object_store_num_objects", stats.get("num_objects", 0),
             "Objects tracked by the node store"),
            ("object_store_num_spilled", stats.get("num_spilled", 0),
             "Objects currently spilled to disk"),
            ("raylet_workers", len(self._workers), "Worker processes"),
            ("raylet_idle_workers", len(self._idle),
             "Idle cached workers"),
            ("raylet_pending_leases", len(self._pending),
             "Queued lease requests"),
        ]]
        for res, avail in self.resources_available.items():
            runtime.append({
                "name": "ray_tpu_resource_available", "type": "gauge",
                "help": "Schedulable resource availability",
                "samples": [{"tags": {"resource": res},
                             "value": float(avail)}]})
        return runtime

    async def handle_get_metrics(self, conn: ServerConnection) -> list:
        """Node-wide snapshot: raylet runtime gauges + every live
        process's pushed app metrics. The legacy poll path — the
        dashboard and autoscaler now read the GCS fold instead (round
        17); kept behind `metrics_poll_fallback` for one release."""
        runtime = self._runtime_metrics()
        from ray_tpu.util.metrics import merge_snapshots

        # Stale = missed ~3 push intervals (dead worker); prune, don't
        # just filter, so churned workers can't grow memory unboundedly.
        cutoff = time.monotonic() - max(
            60.0, 3 * ray_config().metrics_report_interval_ms / 1000.0)
        for wid, (ts, _) in list(self._worker_metrics.items()):
            if ts < cutoff:
                del self._worker_metrics[wid]
        per_source = [({"node_id": self.node_id[:8]}, runtime)] + [
            ({"node_id": self.node_id[:8], "worker_id": wid[:8]}, snap)
            for wid, (ts, snap) in self._worker_metrics.items()]
        return merge_snapshots(per_source)

    async def handle_metrics_push_stats(self, conn: ServerConnection
                                        ) -> Dict[str, Any]:
        """Structural accounting for the perf guard: pushes (heartbeats
        that carried a metrics payload) must never exceed heartbeat
        intervals — i.e. one coalesced push RPC per node per interval."""
        return {"node_id": self.node_id,
                "pushes": self._metrics_pushes,
                "intervals": self._metrics_hb_intervals,
                "pending": len(self._metrics_pending),
                "recorder_dropped": self._ts_recorder.dropped}

    async def handle_object_store_stats(self, conn: ServerConnection
                                        ) -> Dict[str, Any]:
        """Plasma inventory for `ray_tpu memory` / state API
        list_objects."""
        return {"node_id": self.node_id, "used": self.store.used,
                "capacity": self.store.capacity,
                "objects": self.store.object_inventory()}

    def _lease_source(self, pending: "_PendingLease"
                      ) -> Optional[Dict[str, float]]:
        """The resource pool this lease draws from: a PG bundle's reserved
        resources, or the node's free pool. None = can't run now."""
        if pending.bundle_key is not None:
            b = self._bundles.get(pending.bundle_key)
            if b is None or b.removed:
                if not pending.future.done():
                    pending.future.set_result({
                        "error": "bundle_missing",
                        "detail": f"bundle {pending.bundle_key} was removed"})
                self._pending.remove(pending)
                return None
            return b.available if self._fits(b.available,
                                             pending.demand) else None
        return (self.resources_available
                if self._fits(self.resources_available, pending.demand)
                else None)

    def _take_chips(self, pending: "_PendingLease") -> List[int]:
        """Assign whole-chip TPU instance ids for the lease (reference:
        tpu.py:214 TPU_VISIBLE_CHIPS isolation; fractional demand → none)."""
        n = int(pending.demand.get("TPU", 0))
        if n <= 0:
            return []
        if pending.bundle_key is not None:
            b = self._bundles[pending.bundle_key]
            pool = b.chips
        else:
            pool = self._chips_free
        taken, pool[:] = pool[:n], pool[n:]
        return taken

    def _try_dispatch(self) -> None:
        if self._stopping:
            return
        made_progress = True
        while made_progress and self._pending:
            made_progress = False
            for pending in list(self._pending):
                source = self._lease_source(pending)
                if source is None:
                    continue
                worker = self._get_idle_worker()
                if worker is None:
                    # Spawn enough workers for everything runnable now —
                    # startup is the latency, so batch it (reference:
                    # PrestartWorkers on the lease path).
                    starting = sum(1 for w in self._workers.values()
                                   if w.state == "starting")
                    want_actor = any(p.is_actor for p in self._pending)
                    for _ in range(len(self._pending) - starting):
                        if not self._can_start_worker(
                                for_actor=want_actor):
                            break
                        self._spawn_worker()
                    break
                self._pending.remove(pending)
                chips = self._take_chips(pending)
                if pending.bundle_key is not None:
                    b = self._bundles[pending.bundle_key]
                    for k, v in pending.demand.items():
                        b.available[k] = b.available.get(k, 0.0) - v
                else:
                    self._acquire(pending.demand)
                self._next_lease += 1
                lease_id = f"{self.node_id[:8]}-{self._next_lease}"
                worker.state = "actor" if pending.is_actor else "leased"
                worker.lease_id = lease_id
                worker.granted_at = time.monotonic()
                worker.lease_job_id = pending.job_id
                worker.held = dict(pending.demand)
                worker.bundle_key = pending.bundle_key
                worker.chip_ids = chips
                self._lease_conns[lease_id] = (worker.worker_id,
                                               pending.conn)
                if pending.request_id is not None:
                    self._recent_grants.setdefault(
                        pending.request_id, []).append(
                            (lease_id, worker.worker_id))
                    while len(self._recent_grants) > 256:
                        self._recent_grants.pop(
                            next(iter(self._recent_grants)))
                if not pending.future.done():
                    pending.future.set_result({
                        "granted": {
                            "worker_id": worker.worker_id,
                            "worker_address": worker.address,
                            "lease_id": lease_id,
                            "node_id": self.node_id,
                            "resources": pending.demand,
                            "bundle": pending.bundle_key,
                            "chip_ids": chips,
                            # Worker-direct dispatch rings (round 10):
                            # the grant advertises that a NODE-LOCAL
                            # driver may attach a driver<->worker ring
                            # pair for this lease. Chip-holding and
                            # actor leases are excluded (chip workers
                            # retire at lease end; actors use their own
                            # transport).
                            "ring_capable": (not pending.is_actor
                                             and not chips),
                        }})
                made_progress = True

    def _get_idle_worker(self) -> Optional[_Worker]:
        while self._idle:
            worker = self._idle.pop(0)
            if worker.state == "idle" and worker.proc.poll() is None:
                return worker
        return None

    def _can_start_worker(self, for_actor: bool = False) -> bool:
        """The soft limit caps the TASK worker pool; actors hold
        dedicated workers for their lifetime and must not be starved by
        it (reference: worker_pool.h — the cap applies to pooled idle
        workers, dedicated actor workers allocate past it). Actor
        spawns are still bounded against runaways."""
        limit = ray_config().num_workers_soft_limit or int(
            self.resources_total.get("CPU", 4)) + 2
        if for_actor:
            limit = max(limit * 8, 64)
        alive = sum(1 for w in self._workers.values() if w.state != "dead")
        return alive < limit

    # -- blocked-task CPU release (reference: node_manager.cc
    # HandleNotifyDirectCallTaskBlocked/Unblocked — a task blocked in
    # ray.get releases its CPU so downstream tasks can schedule;
    # without this, N consumers blocked on N producers deadlock a node)
    def _blocked_cpu_pool(self, w: _Worker) -> Optional[Dict[str, float]]:
        """Where a blocked worker's CPU goes back to: its PG bundle's
        available set when leased from one (and the bundle still lives),
        else the node pool."""
        if w.bundle_key is not None:
            b = self._bundles.get(w.bundle_key)
            if b is None or b.removed:
                return None
            return b.available
        return self.resources_available

    async def handle_worker_blocked(self, conn: ServerConnection, *,
                                    worker_id: str) -> bool:
        w = self._workers.get(worker_id)
        if (w is not None and not w.blocked
                and w.state in ("leased", "actor")
                and w.held.get("CPU")):
            pool = self._blocked_cpu_pool(w)
            if pool is not None:
                w.blocked = True
                pool["CPU"] = pool.get("CPU", 0.0) + w.held["CPU"]
                self._try_dispatch()
        return True

    async def handle_worker_unblocked(self, conn: ServerConnection, *,
                                      worker_id: str) -> bool:
        w = self._workers.get(worker_id)
        if w is not None and w.blocked:
            w.blocked = False
            pool = self._blocked_cpu_pool(w)
            if pool is not None:
                # May transiently oversubscribe (go negative) — new
                # leases stop until something frees, as the reference.
                pool["CPU"] = pool.get("CPU", 0.0) - w.held.get("CPU",
                                                               0.0)
        return True

    def _release_lease_resources(self, worker: _Worker) -> None:
        if worker.blocked:
            # The blocked release already returned the CPU to its pool;
            # re-take it first so the normal release below is exact.
            worker.blocked = False
            pool = self._blocked_cpu_pool(worker)
            if pool is not None:
                pool["CPU"] = pool.get("CPU", 0.0) - worker.held.get(
                    "CPU", 0.0)
        return self._release_lease_resources_inner(worker)

    def _release_lease_resources_inner(self, worker: _Worker) -> None:
        """Return a lease's resources + chips to where they came from: the
        PG bundle if it's still live, else the node pool (a removed bundle's
        in-use share flows back to the pool as its leases end)."""
        b = (self._bundles.get(worker.bundle_key)
             if worker.bundle_key else None)
        if b is not None and not b.removed:
            for k, v in worker.held.items():
                b.available[k] = min(b.available.get(k, 0.0) + v,
                                     b.total.get(k, v))
            b.chips.extend(worker.chip_ids)
        else:
            self._release(worker.held)
            self._chips_free.extend(worker.chip_ids)
            if b is not None:
                # Removed bundle draining: shrink its in-use record and
                # drop the entry once the last lease ends.
                for k, v in worker.held.items():
                    b.total[k] = b.total.get(k, 0.0) - v
                    if b.total[k] <= 1e-9:
                        del b.total[k]
                if not b.total:
                    self._bundles.pop(worker.bundle_key, None)
        worker.held = {}
        worker.chip_ids = []
        worker.bundle_key = None

    async def handle_cancel_lease_request(self, conn: ServerConnection, *,
                                          request_id: str) -> bool:
        """A client gave up on a lease (timeout): drop it from the queue,
        or — if it was granted in the meantime — return the worker so the
        abandoned grant doesn't leak its resources."""
        # A duplicate delivery arriving after the cancel must not be
        # served the cached (now-reclaimed) grants — and a grant whose
        # handler has not yet RESUMED to cache its reply must find the
        # cancellation when it does (the cache-then-cancel race).
        self._lease_reply_cache.pop(request_id, None)
        self._cancelled_lease_requests[request_id] = None
        while len(self._cancelled_lease_requests) > 512:
            self._cancelled_lease_requests.pop(
                next(iter(self._cancelled_lease_requests)))
        for pending in self._pending:
            if pending.request_id == request_id:
                self._pending.remove(pending)
                if not pending.future.done():
                    pending.future.cancel()
                return True
        grants = self._recent_grants.pop(request_id, None)
        if grants:
            for lease_id, worker_id in grants:
                await self.handle_return_worker(
                    conn, lease_id=lease_id, worker_id=worker_id)
            return True
        return False

    async def handle_return_worker(self, conn: ServerConnection, *,
                                   lease_id: str, worker_id: str,
                                   resources: Optional[Dict[str, float]]
                                   = None, dead: bool = False) -> bool:
        self._return_worker_one(lease_id, worker_id, dead)
        self._try_dispatch()
        return True

    async def handle_return_worker_leases(self, conn: ServerConnection, *,
                                          returns: List[Dict[str, Any]]
                                          ) -> bool:
        """Batched lease returns (round 10, ROADMAP 4c): one RPC hands
        back a burst's finished leases — the mirror of the round-8
        grant batch. Each entry recycles through the same single-return
        path; dispatch runs once for the whole batch."""
        for item in returns or ():
            self._return_worker_one(item.get("lease_id"),
                                    item.get("worker_id"),
                                    bool(item.get("dead")))
        self._try_dispatch()
        return True

    def _return_worker_one(self, lease_id: Optional[str],
                           worker_id: Optional[str], dead: bool) -> None:
        self._lease_conns.pop(lease_id, None)
        worker = self._workers.get(worker_id)
        if worker is not None and worker.lease_id == lease_id:
            # A worker that held TPU chips cannot be reused: libtpu pins
            # chip visibility at first jax init, so a recycled process
            # would silently compute on its OLD chips while the raylet
            # leases them to someone else. Retire it instead.
            had_chips = bool(worker.chip_ids)
            if worker.ring_attached:
                # The lease came back while a dispatch ring is still
                # attached (the driver died, or its detach was lost):
                # the worker's consumer aliases segments that driver
                # owns and will unlink — never recycle it into another
                # lease; retire it instead.
                worker.ring_attached = False
                dead = True
            # The raylet's own bookkeeping is authoritative for what this
            # lease holds — not the client's view.
            self._release_lease_resources(worker)
            worker.lease_id = None
            if dead or had_chips or worker.proc.poll() is not None:
                worker.state = "dead"
                if worker.proc.poll() is None:
                    worker.proc.terminate()
            else:
                worker.state = "idle"
                worker.actor_id = None
                self._idle.append(worker)

    # -- worker-direct dispatch rings (round 10; core/ring.py) ---------
    # The raylet is OFF the per-task path: drivers attach ring pairs
    # straight to the workers they lease. Its only ring duties are the
    # capability bit on grants (_try_dispatch) and this pin/unpin, which
    # keeps a still-ringed worker out of the idle pool (the driver-side
    # pipeline counter pins the LEASE while slots are in flight; this
    # covers the recycle-after-return edge).
    async def handle_worker_ring_attached(self, conn: ServerConnection, *,
                                          worker_id: str) -> bool:
        w = self._workers.get(worker_id)
        if w is not None:
            w.ring_attached = True
            # Pin/unpin instants bracket the worker's ring-attached
            # span in the merged timeline: a worker that stays pinned
            # after its lease returned (leak) or ping-pongs pin/unpin
            # per burst (churn) is visible at a glance.
            from ray_tpu.core import flight

            if flight.enabled:
                flight.instant("ring", "pin", arg=worker_id[:8])
        return True

    async def handle_worker_ring_detached(self, conn: ServerConnection, *,
                                          worker_id: str) -> bool:
        w = self._workers.get(worker_id)
        if w is not None:
            w.ring_attached = False
            from ray_tpu.core import flight

            if flight.enabled:
                flight.instant("ring", "unpin", arg=worker_id[:8])
        return True

    async def handle_mark_actor_worker(self, conn: ServerConnection, *,
                                       worker_id: str, actor_id: str,
                                       release: Optional[Dict[str, float]]
                                       = None,
                                       job_id: Optional[str] = None,
                                       detached: bool = False) -> bool:
        """Record the actor on its worker; `release` downgrades the lease to
        the actor's running demand (placement CPU released after __init__)."""
        worker = self._workers.get(worker_id)
        if worker is not None:
            # An actor worker's lifetime is governed by actor semantics
            # (GCS liveness, max_restarts, detached), NOT by its creation
            # lease's connection — exempt it from dead-client reclaim.
            if worker.lease_id is not None:
                self._lease_conns.pop(worker.lease_id, None)
            worker.actor_id = actor_id
            worker.actor_job_id = job_id
            worker.actor_detached = detached
            if release:
                b = (self._bundles.get(worker.bundle_key)
                     if worker.bundle_key else None)
                if b is not None and not b.removed:
                    for k, v in release.items():
                        b.available[k] = min(b.available.get(k, 0.0) + v,
                                             b.total.get(k, v))
                else:
                    self._release(release)
                for k, v in release.items():
                    worker.held[k] = worker.held.get(k, 0.0) - v
                    if worker.held[k] <= 1e-9:
                        del worker.held[k]
                self._try_dispatch()
        return True

    # ------------------------------------------------------------------
    # object store RPCs (reference: plasma protocol + object_manager)
    # ------------------------------------------------------------------
    async def _store_io(self, fn, *args):
        """Run a store op that may do disk I/O (spill victims on create,
        restore on info/read — native store) off the event loop so a
        multi-GB spill can't stall heartbeats and every other RPC. The
        C++ store is internally locked; the Python store is not
        thread-safe, so it stays on-loop (it never touches disk)."""
        if isinstance(self.store, NativeObjectStore):
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(None, fn, *args)
        return fn(*args)

    async def handle_create_object(self, conn: ServerConnection, *,
                                   oid: str, size: int) -> str:
        return await self._store_io(self.store.create, oid, size)

    async def handle_seal_object(self, conn: ServerConnection, *,
                                 oid: str) -> bool:
        # Sealing is a fire-and-forget notify on the put hot path, so a
        # failure cannot surface at the caller — make it loud here and
        # drop the unsealed entry so consumers fail fast (object-lost ->
        # lineage) instead of polling an object that will never seal.
        try:
            self.store.seal(oid)
        except Exception as e:  # noqa: BLE001
            logger.error("seal_object(%s) failed: %s; dropping entry",
                         oid[:16], e)
            try:
                self.store.delete(oid)
            except Exception:
                pass
            return False
        return True

    async def handle_object_info(self, conn: ServerConnection, *,
                                 oid: str) -> Optional[Dict[str, Any]]:
        info = await self._store_io(self.store.info, oid)
        if info is None:
            return None
        name, size = info
        return {"shm_name": name, "size": size}

    async def handle_read_object(self, conn: ServerConnection, *,
                                 oid: str) -> Optional[bytes]:
        """Remote raylet pull (data-plane; single frame, small objects)."""
        if not self.store.contains(oid):
            return None
        try:
            return await self._store_io(self.store.read_bytes, oid)
        except KeyError:
            # Evicted since contains(), or a spilled copy failed to
            # restore: "no longer a holder", the puller tries elsewhere.
            return None

    async def handle_object_meta(self, conn: ServerConnection, *,
                                 oid: str) -> Optional[Dict[str, int]]:
        size = self.store.size_of(oid)
        if size is None:
            return None
        return {"size": size}

    def _push_gate(self) -> asyncio.Semaphore:
        """Push-side backpressure (reference: push_manager.h:30 bounded
        in-flight pushes): at most `object_push_concurrency` chunk serves
        run at once, so an N-way broadcast queues here instead of
        thrashing the store threadpool and starving the lease plane."""
        if self._push_sem is None:
            self._push_sem = asyncio.Semaphore(
                ray_config().object_push_concurrency)
        return self._push_sem

    async def handle_read_object_chunk(self, conn: ServerConnection, *,
                                       oid: str, offset: int,
                                       length: int) -> Optional[bytes]:
        """One chunk of a large object (reference: object_manager.h
        chunked transfer). Returns None if the object vanished."""
        if not self.store.contains(oid):
            return None
        gate = self._push_gate()
        self._push_waiters += 1
        try:
            await gate.acquire()
        finally:
            self._push_waiters -= 1
        try:
            return await self._store_io(
                self.store.read_range, oid, offset, length)
        except KeyError:
            return None
        finally:
            gate.release()

    # Large objects stream in 1 MiB frames so a multi-GB transfer neither
    # doubles peak memory nor monopolizes either event loop.
    @property
    def TRANSFER_CHUNK(self) -> int:
        return ray_config().object_transfer_chunk_bytes

    async def _pull_from_holder(self, remote, oid: str) -> bool:
        """Copy `oid` from a remote raylet into the local store, deduped
        (concurrent pulls of one object share a single transfer) and
        admission-controlled (pull_manager byte budget). Returns False if
        the holder no longer has it."""
        inflight = self._inflight_pulls.get(oid)
        if inflight is not None:
            return await asyncio.shield(inflight)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._inflight_pulls[oid] = fut
        try:
            ok = await self._pull_from_holder_inner(remote, oid)
            fut.set_result(ok)
            return ok
        except BaseException as e:
            fut.set_exception(e)
            # A shielded waiter may never await the future after its own
            # cancellation; mark retrieved so asyncio doesn't log
            # "exception was never retrieved".
            try:
                fut.exception()
            except Exception:
                pass
            raise
        finally:
            self._inflight_pulls.pop(oid, None)

    async def _pull_from_holder_inner(self, remote, oid: str) -> bool:
        meta = await remote.call("object_meta", oid=oid, timeout=30.0)
        if meta is None:
            return False
        size = meta["size"]
        if size <= self.TRANSFER_CHUNK:
            data = await remote.call("read_object", oid=oid, timeout=60.0)
            if data is None:
                return False
            await self._store_io(self.store.put_bytes, oid, data)
            return True
        if self.store.contains(oid):
            return True
        granted = await self._pulls.admit(size)
        try:
            try:
                await self._store_io(self.store.create, oid, size)
            except FileExistsError:
                # A concurrent pull sealed it between contains() and here.
                return self.store.contains(oid)
            try:
                for offset in range(0, size, self.TRANSFER_CHUNK):
                    chunk = await remote.call(
                        "read_object_chunk", oid=oid, offset=offset,
                        length=self.TRANSFER_CHUNK, timeout=60.0)
                    if chunk is None:
                        raise KeyError(f"{oid[:8]} evicted mid-transfer")
                    await self._store_io(
                        self.store.write_range, oid, offset, chunk)
                self.store.seal(oid)
            except BaseException:
                # Only roll back an entry WE still own unsealed — a
                # concurrent pull may have sealed it and handed readers
                # the mapping (contains() == sealed).
                if not self.store.contains(oid):
                    self.store.delete(oid)
                raise
            return True
        finally:
            self._pulls.release(granted)

    async def handle_put_object(self, conn: ServerConnection, *,
                                oid: str, data: bytes) -> bool:
        await self._store_io(self.store.put_bytes, oid, data)
        return True

    async def handle_delete_objects(self, conn: ServerConnection, *,
                                    oids: List[str]) -> int:
        # Off-loop: native erase() waits out any in-flight restore's
        # disk read before removing the entry.
        n = 0
        for oid in oids:
            if await self._store_io(self.store.delete, oid):
                n += 1
        return n

    async def on_client_disconnect(self, conn: ServerConnection) -> None:
        """Drop queued lease requests from a vanished client so a later
        grant doesn't strand a worker + its resources, and reclaim
        leases it was already granted (a dead client can never use or
        return them)."""
        for pending in [p for p in self._pending if p.conn is conn]:
            self._pending.remove(pending)
            if not pending.future.done():
                pending.future.cancel()
        for lease_id, (worker_id, owner_conn) in list(
                self._lease_conns.items()):
            if owner_conn is not conn:
                continue
            worker = self._workers.get(worker_id)
            if worker is not None and (worker.actor_id
                                       or worker.state == "actor"):
                # Actor lifetimes are actor-managed, never conn-managed.
                self._lease_conns.pop(lease_id, None)
                continue
            # dead=True: the worker may be mid-task for the dead
            # client; terminating is the only safe reset.
            await self.handle_return_worker(
                conn, lease_id=lease_id, worker_id=worker_id, dead=True)

    async def handle_pull_object(self, conn: ServerConnection, *, oid: str,
                                 owner_address: Optional[str],
                                 pull_timeout: Optional[float] = 30.0
                                 ) -> Optional[Dict[str, Any]]:
        """Ensure `oid` is in the local store; returns shm info, inline
        payload, or None. Resolution order: local store -> owner's location
        directory (ownership-based object directory,
        `ownership_based_object_directory.h`) -> remote raylet fetch.

        pull_timeout=None blocks until the object materializes (a blocking
        `ray.get` with no user timeout must not be capped server-side)."""
        deadline = (None if pull_timeout is None
                    else time.monotonic() + pull_timeout)
        owner_unreachable_since: Optional[float] = None
        while deadline is None or time.monotonic() < deadline:
            info = await self._store_io(self.store.info, oid)
            if info is not None:
                # Local hit: never touches pull admission — the budget
                # paces inbound remote transfers only (_pull_from_holder
                # charges it; this path must not).
                self._pulls.stats["local_reads"] += 1
                return {"shm_name": info[0], "size": info[1]}
            if owner_address:
                try:
                    owner = await self._worker_client(owner_address)
                    loc = await owner.call("get_object_locations", oid=oid,
                                           timeout=10.0)
                except Exception as e:
                    # An unreachable owner is transient (restarting GCS,
                    # blip) until it has stayed unreachable for the
                    # grace window — then it is DEAD and the borrower's
                    # get must fail loudly as OwnerDiedError, not hang
                    # in this loop or mislabel the loss as a generic
                    # ObjectLostError (reference: ownership model,
                    # OBJECT_UNRECOVERABLE_OWNER_DIED).
                    now = time.monotonic()
                    if owner_unreachable_since is None:
                        owner_unreachable_since = now
                    if (now - owner_unreachable_since
                            >= ray_config().owner_unreachable_grace_s):
                        return {"error": f"owner unreachable: {e}",
                                "owner_dead": True}
                    await asyncio.sleep(
                        ray_config().object_timeout_ms / 1000.0)
                    continue
                owner_unreachable_since = None
                if loc is None:
                    return {"error": "owner does not know this object"}
                if loc.get("inline") is not None:
                    return {"inline": loc["inline"]}
                for node_addr in loc.get("nodes", []):
                    if node_addr == self.address:
                        # We're listed as a holder but store.info() came up
                        # empty above: our copy was evicted. Prune it so
                        # the owner can recover instead of us spinning on
                        # a stale self-location.
                        try:
                            await owner.notify("prune_object_location",
                                               oid=oid, node=node_addr)
                        except Exception:
                            pass
                        continue
                    try:
                        remote = await self._raylet_client(node_addr)
                        fetched = await self._pull_from_holder(remote, oid)
                    except Exception:
                        # Unreachable holder: if the cluster has declared
                        # its node dead, prune the location so the owner
                        # can start lineage reconstruction; otherwise treat
                        # it as transient and retry.
                        if self._address_is_dead(node_addr):
                            try:
                                await owner.notify("prune_object_location",
                                                   oid=oid, node=node_addr)
                            except Exception:
                                pass
                        continue
                    if fetched:
                        info = await self._store_io(self.store.info, oid)
                        if info is not None:
                            return {"shm_name": info[0], "size": info[1]}
                        continue  # evicted between pull and info: re-resolve
                    # The node answered but no longer holds the object
                    # (LRU-evicted/deleted): tell the owner to prune this
                    # stale location so future pulls skip it.
                    try:
                        await owner.notify("prune_object_location",
                                           oid=oid, node=node_addr)
                    except Exception:
                        pass
                if not loc.get("pending") and not loc.get("nodes"):
                    # No copies and the owner is not currently producing
                    # one. Ask the owner to RECOVER it (lineage
                    # re-execution) before declaring the loss final —
                    # relying on the prune notify alone races this
                    # loop's next locations query against the owner's
                    # reconstruction trigger and failed borrower gets
                    # that lineage could have saved. `recovering=False`
                    # is authoritative: unretained lineage or exhausted
                    # budget, the typed loss stands.
                    try:
                        r = await owner.call("reconstruct_object",
                                             oid=oid, timeout=10.0)
                    except Exception:
                        # Transient owner blip: re-enter the loop; the
                        # owner-unreachable grace above judges real
                        # owner death.
                        await asyncio.sleep(
                            ray_config().object_timeout_ms / 1000.0)
                        continue
                    if r and r.get("recovering"):
                        await asyncio.sleep(
                            ray_config().object_timeout_ms / 1000.0)
                        continue
                    return {"error": "no reachable copy"}
            await asyncio.sleep(ray_config().object_timeout_ms / 1000.0)
        return {"error": "timeout"}

    def _address_is_dead(self, address: str) -> bool:
        """True when the GCS view says no alive node serves `address`."""
        alive = {info.get("address") for info in self._cluster_view.values()
                 if info.get("alive", True)}
        return bool(alive) and address not in alive

    async def _raylet_client(self, address: str) -> RpcClient:
        client = self._raylet_clients.get(address)
        if client is None or not client.connected:
            client = RpcClient(address)
            await client.connect(timeout=5.0)
            self._raylet_clients[address] = client
        return client

    async def _worker_client(self, address: str) -> RpcClient:
        client = self._worker_clients.get(address)
        if client is None or not client.connected:
            client = RpcClient(address)
            await client.connect(timeout=5.0)
            self._worker_clients[address] = client
        return client

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    async def handle_node_stats(self, conn: ServerConnection
                                ) -> Dict[str, Any]:
        return {
            "node_id": self.node_id,
            "resources_total": self.resources_total,
            "resources_available": self.resources_available,
            "num_workers": len([w for w in self._workers.values()
                                if w.state != "dead"]),
            "pending_leases": len(self._pending),
            "workers": [
                {"id": w.worker_id[:8], "state": w.state,
                 "lease_id": w.lease_id, "held": dict(w.held),
                 "actor": w.actor_id, "alive": w.proc.poll() is None}
                for w in self._workers.values()],
            "bundles": {k: {"total": b.total, "available": b.available,
                            "committed": b.committed}
                        for k, b in self._bundles.items() if not b.removed},
            "store": self.store.stats(),
            "object_manager": {
                **self._pulls.stats,
                "budget_bytes": self._pulls.budget,
                "in_use_bytes": self._pulls.in_use,
                "inflight_pulls": len(self._inflight_pulls),
                "push_waiters": self._push_waiters,
            },
        }

    async def handle_ping(self, conn: ServerConnection) -> str:
        return "pong"


def main() -> None:
    import argparse
    import json

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--object-store-memory", type=int, default=0)
    parser.add_argument("--head", action="store_true")
    args = parser.parse_args()

    logging.basicConfig(level=logging.INFO)

    async def run():
        import signal

        from ray_tpu.parallel.tpu import slice_info

        raylet = Raylet(
            node_id=args.node_id, gcs_address=args.gcs,
            resources=json.loads(args.resources),
            labels=slice_info() or {},
            object_store_memory=args.object_store_memory or None,
            is_head=args.head, port=args.port)
        await raylet.start()
        print(f"RAYLET_ADDRESS={raylet.address}", flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        # Clean shutdown: kill the worker pool before exiting, so no
        # orphan workers outlive the node.
        await raylet.stop()

    asyncio.run(run())


if __name__ == "__main__":
    main()
