"""Local-mode runtime: the full task/actor/object API inside one process.

Reference equivalent: `src/ray/core_worker/core_worker.cc:3015` local mode —
used for debugging and unit tests. Unlike the reference (which executes
inline), tasks here run on an elastic thread pool so concurrency semantics
(wait, actor ordering, async actors, streaming generators, nested get) match
the cluster runtime. Values still round-trip through serialization so local
mode catches serialization bugs.
"""

from __future__ import annotations

import concurrent.futures
import inspect
import queue as queue_mod
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.generator import ObjectRefGenerator
from ray_tpu.core.ids import ActorID, JobID, ObjectID, TaskID, _Counter
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    RayTaskError,
    TaskCancelledError,
)
from ray_tpu.runtime_context import _reset_task_context, _set_task_context

_pool_local = threading.local()


class _ElasticPool:
    """Task thread pool that grows when a worker blocks in `get`.

    This is the local-mode analogue of the reference raylet starting extra
    workers when leased workers block on `ray.get` of not-yet-ready objects —
    it prevents nested-task deadlock at any dependency depth.
    """

    def __init__(self, size: int, max_size: int = 1024,
                 name: str = "task"):
        self._q: "queue_mod.SimpleQueue" = queue_mod.SimpleQueue()
        self._lock = threading.Lock()
        self._idle = 0
        self._nthreads = 0
        self._max = max_size
        self._shutdown = False
        self._name = name
        for _ in range(size):
            self._spawn()

    def _spawn(self) -> None:
        with self._lock:
            if self._nthreads >= self._max or self._shutdown:
                return
            self._nthreads += 1
        t = threading.Thread(
            target=self._loop, daemon=True,
            name=f"{self._name}-{self._nthreads}")
        t.start()

    def _loop(self) -> None:
        _pool_local.pool = self
        while True:
            with self._lock:
                self._idle += 1
            item = self._q.get()
            with self._lock:
                self._idle -= 1
            if item is None:
                return
            fut, fn = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fn()
                fut.set_result(None)
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

    def submit(self, fn) -> concurrent.futures.Future:
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._q.put((fut, fn))
        return fut

    def notify_blocked(self) -> None:
        """Called when a pool thread is about to block; keep one spare."""
        with self._lock:
            need = self._idle == 0 and not self._shutdown
        if need:
            self._spawn()

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            n = self._nthreads
        for _ in range(n):
            self._q.put(None)


class _LocalActor:
    def __init__(self, actor_id: ActorID, cls: type, instance: Any,
                 max_concurrency: int, is_async: bool):
        self.actor_id = actor_id
        self.cls = cls
        self.instance = instance
        self.alive = True
        self.death_cause: Optional[BaseException] = None
        self.is_async = is_async
        if is_async:
            import asyncio
            self.loop = asyncio.new_event_loop()
            self.thread = threading.Thread(
                target=self.loop.run_forever, daemon=True)
            self.thread.start()
        else:
            self.loop = None
        # Async actors also get a bounded pool: it runs the bridging wait on
        # each coroutine so max_concurrency actually bounds in-flight calls.
        self.executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_concurrency,
            thread_name_prefix=f"actor-{cls.__name__}")


class LocalModeRuntime:
    """Implements the Runtime interface entirely in-process."""

    is_local_mode = True

    def __init__(self, num_cpus: Optional[int] = None,
                 namespace: Optional[str] = None, **_: Any):
        import os
        self.job_id = JobID.from_int(1)
        self.namespace = namespace or "default"
        n = num_cpus or os.cpu_count() or 4
        self._pool = _ElasticPool(max(n, 4))
        self._objects: Dict[ObjectID, concurrent.futures.Future] = {}
        self._objects_lock = threading.Lock()
        self._refcounts: Dict[ObjectID, int] = {}
        self._actors: Dict[ActorID, _LocalActor] = {}
        self._named_actors: Dict[Tuple[str, str], ActorID] = {}
        self._actor_meta: Dict[ActorID, Tuple[str, dict]] = {}
        self._put_counter = _Counter()
        self._task_futures: Dict[TaskID, concurrent.futures.Future] = {}
        self._task_returns: Dict[TaskID, List[ObjectID]] = {}
        self._kv: Dict[bytes, bytes] = {}
        self._num_cpus = n

    # -- reference counting ----------------------------------------------
    # Local refcounts drive release of stored values, the in-process
    # analogue of reference_count.h. A count reaching zero frees the bytes.
    def add_local_reference(self, object_id: ObjectID) -> None:
        with self._objects_lock:
            self._refcounts[object_id] = self._refcounts.get(object_id, 0) + 1

    def remove_local_reference(self, object_id: ObjectID) -> None:
        with self._objects_lock:
            n = self._refcounts.get(object_id, 0) - 1
            if n > 0:
                self._refcounts[object_id] = n
            else:
                self._refcounts.pop(object_id, None)
                fut = self._objects.get(object_id)
                # Only free resolved objects; in-flight task stores recreate
                # the entry (bounded by in-flight tasks, cleaned at shutdown).
                if fut is not None and fut.done():
                    del self._objects[object_id]

    def on_ref_deserialized(self, ref: ObjectRef) -> None:
        self.add_local_reference(ref.id())

    # -- objects ---------------------------------------------------------
    def _store(self, object_id: ObjectID, value: Any,
               is_error: bool = False) -> None:
        fut = self._object_future(object_id)
        try:
            so = (serialization.serialize_error(value) if is_error
                  else serialization.serialize(value))
            fut.set_result(so.to_bytes())
        except concurrent.futures.InvalidStateError:
            pass

    def _object_future(self, object_id: ObjectID) -> concurrent.futures.Future:
        with self._objects_lock:
            fut = self._objects.get(object_id)
            if fut is None:
                fut = concurrent.futures.Future()
                self._objects[object_id] = fut
            return fut

    def put(self, value: Any) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("Calling put() on an ObjectRef is not allowed.")
        task_id = TaskID.for_task(self.job_id)
        object_id = ObjectID.for_put(task_id, self._put_counter.next())
        self._store(object_id, value)
        return ObjectRef(object_id, runtime=self)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, (ObjectRef, ObjectRefGenerator))
        if not single and not hasattr(refs, "__iter__"):
            raise TypeError(
                "get() expects an ObjectRef or a list of ObjectRefs, got "
                f"{type(refs).__name__}")
        ref_list = [refs] if single else list(refs)
        deadline = None if timeout is None else time.monotonic() + timeout
        values: List[Any] = []
        for ref in ref_list:
            if isinstance(ref, ObjectRefGenerator):
                raise TypeError("Cannot get() an ObjectRefGenerator; iterate it.")
            if not isinstance(ref, ObjectRef):
                raise TypeError(
                    f"get() expects ObjectRef(s), got {type(ref).__name__}")
            fut = self._object_future(ref.id())
            if not fut.done():
                pool = getattr(_pool_local, "pool", None)
                if pool is not None:
                    pool.notify_blocked()
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            try:
                data = fut.result(timeout=remaining)
            except concurrent.futures.TimeoutError:
                raise GetTimeoutError(
                    f"Get timed out after {timeout}s waiting for {ref}")
            values.append(serialization.deserialize(data))
        return values[0] if single else values

    def wait(self, refs, num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True):
        if isinstance(refs, ObjectRef):
            raise TypeError("wait() expects a list of ObjectRefs")
        refs = list(refs)
        if len(set(refs)) != len(refs):
            raise ValueError("wait() got duplicate ObjectRefs")
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds the number of refs")
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[ObjectRef] = []
        pending = list(refs)
        while len(ready) < num_returns:
            progressed = False
            for ref in list(pending):
                if self._object_future(ref.id()).done():
                    ready.append(ref)
                    pending.remove(ref)
                    progressed = True
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            if not progressed:
                time.sleep(0.001)
        # Reference contract: at most num_returns in ready.
        if len(ready) > num_returns:
            extra = ready[num_returns:]
            ready = ready[:num_returns]
            pending = extra + pending
        return ready, pending

    # -- tasks -----------------------------------------------------------
    def _resolve_args(self, args, kwargs):
        def resolve(v):
            return self.get(v) if isinstance(v, ObjectRef) else v

        return ([resolve(a) for a in args],
                {k: resolve(v) for k, v in kwargs.items()})

    def _make_return_refs(self, task_id: TaskID, n: int) -> List[ObjectRef]:
        return [ObjectRef(ObjectID.for_return(task_id, i + 1), runtime=self)
                for i in range(n)]

    def _store_returns(self, task_id: TaskID, num_returns: int, result) -> None:
        if num_returns == 0:
            return
        if num_returns == 1:
            self._store(ObjectID.for_return(task_id, 1), result)
        else:
            if not isinstance(result, (tuple, list)) or len(result) != num_returns:
                err = ValueError(
                    f"Task declared num_returns={num_returns} but returned "
                    f"{type(result).__name__} of length "
                    f"{len(result) if hasattr(result, '__len__') else 'n/a'}")
                for i in range(num_returns):
                    self._store(ObjectID.for_return(task_id, i + 1),
                                RayTaskError.from_exception("task", err),
                                is_error=True)
                return
            for i, v in enumerate(result):
                self._store(ObjectID.for_return(task_id, i + 1), v)

    def _store_error(self, task_id: TaskID, num_returns: int,
                     name: str, exc: BaseException) -> None:
        wrapped = (exc if isinstance(exc, (RayTaskError, TaskCancelledError,
                                           ActorDiedError))
                   else RayTaskError.from_exception(name, exc))
        for i in range(max(num_returns, 1)):
            self._store(ObjectID.for_return(task_id, i + 1), wrapped,
                        is_error=True)

    def _run_streaming_body(self, task_id: TaskID, name: str,
                            gen: ObjectRefGenerator, produce,
                            **ctx_kwargs) -> None:
        token = _set_task_context(task_id=task_id, **ctx_kwargs)
        try:
            idx = 0
            for item in produce():
                idx += 1
                oid = ObjectID.for_return(task_id, idx)
                self._store(oid, item)
                gen._push(ObjectRef(oid, runtime=self))
            gen._finish()
        except BaseException as e:  # noqa: BLE001
            gen._finish(RayTaskError.from_exception(name, e)
                        if not isinstance(e, RayTaskError) else e)
        finally:
            _reset_task_context(token)

    def submit_task(self, remote_function, opts, args, kwargs):
        task_id = TaskID.for_task(self.job_id)
        fn = remote_function._function
        name = remote_function._function_name

        if opts.num_returns in ("streaming", "dynamic"):
            gen = ObjectRefGenerator()

            def produce():
                rargs, rkwargs = self._resolve_args(args, kwargs)
                return fn(*rargs, **rkwargs)

            self._task_futures[task_id] = self._pool.submit(
                lambda: self._run_streaming_body(task_id, name, gen, produce))
            return gen

        num_returns = opts.num_returns

        def run():
            from ray_tpu.core.task_events import task_event_buffer

            token = _set_task_context(task_id=task_id)
            buf = task_event_buffer()
            job = self.job_id.hex()
            buf.record(task_id.hex(), name, "RUNNING", job_id=job,
                       node_id="local", worker_id="local")
            ok = False
            try:
                rargs, rkwargs = self._resolve_args(args, kwargs)
                result = fn(*rargs, **rkwargs)
                self._store_returns(task_id, num_returns, result)
                ok = True
            except BaseException as e:  # noqa: BLE001
                self._store_error(task_id, num_returns, name, e)
            finally:
                buf.record(task_id.hex(), name,
                           "FINISHED" if ok else "FAILED", job_id=job,
                           node_id="local", worker_id="local")
                _reset_task_context(token)

        from ray_tpu.core.task_events import task_event_buffer

        task_event_buffer().record(
            task_id.hex(), name, "SUBMITTED", job_id=self.job_id.hex(),
            node_id="local", worker_id="local")
        self._task_futures[task_id] = self._pool.submit(run)
        refs = self._make_return_refs(task_id, max(num_returns, 1))
        self._task_returns[task_id] = [r.id() for r in refs]
        if num_returns == 0:
            return None
        return refs[0] if num_returns == 1 else refs

    # -- placement groups (single node: reservation is a table entry) ----
    def create_placement_group(self, bundles, strategy="PACK", name="",
                               target_node_ids=None) -> str:
        from ray_tpu.core.ids import PlacementGroupID
        from ray_tpu.core.pg_scheduler import validate_pg_args

        validate_pg_args(bundles, strategy)
        pg_id = PlacementGroupID.of(self.job_id).hex()
        if not hasattr(self, "_placement_groups"):
            self._placement_groups = {}
        self._placement_groups[pg_id] = {
            "pg_id": pg_id, "bundles": [dict(b) for b in bundles],
            "strategy": strategy, "name": name, "state": "CREATED",
            "bundle_locations": [{"node_id": "local", "address": "local"}
                                 for _ in bundles],
        }
        return pg_id

    def placement_group_wait(self, pg_id, timeout=None) -> bool:
        info = getattr(self, "_placement_groups", {}).get(pg_id)
        return bool(info and info["state"] == "CREATED")

    def remove_placement_group(self, pg_id) -> None:
        info = getattr(self, "_placement_groups", {}).get(pg_id)
        if info is not None:
            info["state"] = "REMOVED"

    def placement_group_table(self, pg_id=None):
        table = getattr(self, "_placement_groups", {})
        return table.get(pg_id) if pg_id is not None else dict(table)

    def cancel(self, ref: ObjectRef, force: bool = False,
               recursive: bool = True) -> None:
        task_id = ref.id().task_id()
        fut = self._task_futures.get(task_id)
        if fut is not None and fut.cancel():
            # Resolve every sibling return ref, not just the one passed in.
            for oid in self._task_returns.get(task_id, [ref.id()]):
                self._store(oid, TaskCancelledError(task_id), is_error=True)

    # -- actors ----------------------------------------------------------
    def create_actor(self, actor_class, opts, args, kwargs):
        from ray_tpu.core.actor import ActorHandle

        actor_id = ActorID.of(self.job_id)
        cls = actor_class._cls
        key = None
        if opts.name:
            key = (self.namespace if opts.namespace is None else opts.namespace,
                   opts.name)
            if key in self._named_actors:
                raise ValueError(
                    f"Actor with name '{opts.name}' already exists in "
                    f"namespace '{key[0]}'")

        meta = actor_class.method_meta()
        is_async = any(m.get("is_async") for m in meta.values())
        max_concurrency = opts.max_concurrency or (100 if is_async else 1)

        rargs, rkwargs = self._resolve_args(args, kwargs)
        instance = cls(*rargs, **rkwargs)
        actor = _LocalActor(actor_id, cls, instance, max_concurrency, is_async)
        self._actors[actor_id] = actor
        self._actor_meta[actor_id] = (cls.__name__, meta)
        if key is not None:
            # Register only after __init__ succeeded so a failing constructor
            # doesn't leak the name.
            self._named_actors[key] = actor_id
        return ActorHandle(actor_id, cls.__name__, meta, runtime=self)

    def submit_actor_task(self, handle, method_name, opts, args, kwargs):
        actor = self._actors.get(handle._ray_actor_id)
        task_id = TaskID.for_actor_task(handle._ray_actor_id)
        num_returns = opts.num_returns
        streaming = num_returns in ("streaming", "dynamic")

        if actor is None or not actor.alive:
            err = ActorDiedError(handle._ray_actor_id)
            if streaming:
                gen = ObjectRefGenerator()
                gen._finish(err)
                return gen
            refs = self._make_return_refs(task_id, max(num_returns, 1))
            for r in refs:
                self._store(r.id(), err, is_error=True)
            if num_returns == 0:
                return None
            return refs[0] if num_returns == 1 else refs

        name = f"{actor.cls.__name__}.{method_name}"

        def call_method():
            """Invoke the method; bridge coroutines / async gens to the
            actor's event loop. Context is set inside the coroutine (each
            asyncio task gets its own contextvars copy)."""
            import asyncio

            rargs, rkwargs = self._resolve_args(args, kwargs)
            if method_name == "__ray_call__":
                fn, rargs = rargs[0], rargs[1:]
                result = fn(actor.instance, *rargs, **rkwargs)
            else:
                method = getattr(actor.instance, method_name)
                result = method(*rargs, **rkwargs)
            if inspect.iscoroutine(result):
                async def with_ctx():
                    token = _set_task_context(
                        task_id=task_id, actor_id=actor.actor_id,
                        actor_handle=handle)
                    try:
                        return await result
                    finally:
                        _reset_task_context(token)

                return asyncio.run_coroutine_threadsafe(
                    with_ctx(), actor.loop).result()
            if inspect.isasyncgen(result):
                return _sync_iter_async_gen(result, actor.loop)
            return result

        if streaming:
            gen = ObjectRefGenerator()

            actor.executor.submit(
                lambda: self._run_streaming_body(
                    task_id, name, gen, call_method,
                    actor_id=actor.actor_id, actor_handle=handle))
            return gen

        def run():
            token = _set_task_context(task_id=task_id,
                                      actor_id=actor.actor_id,
                                      actor_handle=handle)
            try:
                self._store_returns(task_id, num_returns, call_method())
            except BaseException as e:  # noqa: BLE001
                self._store_error(task_id, num_returns, name, e)
            finally:
                _reset_task_context(token)

        actor.executor.submit(run)
        if num_returns == 0:
            return None
        refs = self._make_return_refs(task_id, max(num_returns, 1))
        return refs[0] if num_returns == 1 else refs

    def kill_actor(self, handle, no_restart: bool = True) -> None:
        actor = self._actors.get(handle._ray_actor_id)
        if actor is not None:
            actor.alive = False
            for key, aid in list(self._named_actors.items()):
                if aid == handle._ray_actor_id:
                    del self._named_actors[key]

    def get_actor(self, name: str, namespace: Optional[str] = None):
        from ray_tpu.core.actor import ActorHandle

        key = (namespace or self.namespace, name)
        actor_id = self._named_actors.get(key)
        if actor_id is None:
            raise ValueError(f"Failed to look up actor with name '{name}'")
        class_name, meta = self._actor_meta[actor_id]
        return ActorHandle(actor_id, class_name, meta, runtime=self)

    # -- cluster introspection -------------------------------------------
    def nodes(self) -> List[dict]:
        import os
        return [{
            "NodeID": "local",
            "Alive": True,
            "Resources": self.cluster_resources(),
            "NodeManagerHostname": os.uname().nodename,
            "IsHeadNode": True,
        }]

    def cluster_resources(self) -> Dict[str, float]:
        res = {"CPU": float(self._num_cpus), "memory": 1e9,
               "object_store_memory": 1e9}
        try:
            from ray_tpu.parallel.tpu import local_tpu_resources
            res.update(local_tpu_resources())
        except Exception:
            pass
        return res

    def available_resources(self) -> Dict[str, float]:
        return self.cluster_resources()

    def task_events(self, job_id: Optional[str] = None):
        from ray_tpu.core.task_events import task_event_buffer

        return task_event_buffer().snapshot(job_id)

    def timeline(self, filename: Optional[str] = None):
        """Chrome-trace export of the in-process task events."""
        from ray_tpu.core.task_events import (events_to_chrome_trace,
                                              write_trace)

        trace = events_to_chrome_trace(
            self.task_events(self.job_id.hex()))
        return write_trace(trace, filename)

    # -- internal kv (reference: GcsKvManager) ---------------------------
    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True) -> bool:
        if not overwrite and key in self._kv:
            return False
        self._kv[key] = value
        return True

    def kv_get(self, key: bytes) -> Optional[bytes]:
        return self._kv.get(key)

    def kv_del(self, key: bytes) -> None:
        self._kv.pop(key, None)

    def kv_keys(self, prefix: bytes) -> List[bytes]:
        return [k for k in self._kv if k.startswith(prefix)]

    def shutdown(self) -> None:
        self._pool.shutdown()
        for actor in self._actors.values():
            actor.alive = False
            if actor.executor:
                actor.executor.shutdown(wait=False, cancel_futures=True)
            if actor.loop:
                actor.loop.call_soon_threadsafe(actor.loop.stop)
        self._actors.clear()
        self._objects.clear()
        self._refcounts.clear()


def _sync_iter_async_gen(agen, loop):
    """Drain an async generator from a sync thread via its event loop."""
    import asyncio

    while True:
        try:
            yield asyncio.run_coroutine_threadsafe(
                agen.__anext__(), loop).result()
        except StopAsyncIteration:
            return
