"""Actor API: `@remote class`, `.remote()` creation, handles, method calls.

Reference equivalent: `python/ray/actor.py` — `ActorClass` (`:425`),
`ActorClass.remote` (`:565`), `ActorHandle` (`:1067`) with method proxies; GCS
owns the actor lifecycle (`gcs_actor_manager.h:251-280`).
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Dict, Optional

from ray_tpu.core.ids import ActorID
from ray_tpu.core.options import ActorOptions, TaskOptions, actor_options, task_options


class ActorMethod:
    """Bound method proxy on a handle: `handle.f.remote(...)`."""

    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: Any = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def remote(self, *args, **kwargs):
        opts = task_options({"num_returns": self._num_returns})
        return self._handle._submit(self._method_name, args, kwargs, opts)

    def options(self, **updates):
        from ray_tpu.core.options import OptionsProxy
        base = task_options({"num_returns": self._num_returns})
        opts = task_options(updates, base=base)
        handle, name = self._handle, self._method_name
        def _bind(args, kwargs):
            from ray_tpu.dag import ClassMethodNode
            return ClassMethodNode(handle, name, args, kwargs, options=opts)

        return OptionsProxy(
            submit=lambda args, kwargs: handle._submit(name, args, kwargs,
                                                       opts),
            bind=_bind)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassMethodNode
        opts = task_options({"num_returns": self._num_returns})
        return ClassMethodNode(self._handle, self._method_name, args, kwargs,
                               options=opts)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly; "
            "use '.remote()'."
        )


class ActorHandle:
    """Serializable reference to a live actor."""

    def __init__(self, actor_id: ActorID, class_name: str,
                 method_meta: Dict[str, Any], runtime=None):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_meta = method_meta
        self._runtime = runtime

    @property
    def _ray_actor_id(self) -> ActorID:
        return self._actor_id

    def _actor_runtime(self):
        if self._runtime is None:
            from ray_tpu.core.worker import current_runtime
            self._runtime = current_runtime()
        return self._runtime

    def _submit(self, method_name: str, args, kwargs, opts: TaskOptions):
        return self._actor_runtime().submit_actor_task(
            self, method_name, opts, args, kwargs)

    def __getattr__(self, name: str):
        if name == "__ray_call__":
            # Run an arbitrary function against the live actor instance:
            # handle.__ray_call__.remote(fn, *args) -> fn(instance, *args)
            # (reference: actor.py __ray_call__ system method).
            return ActorMethod(self, "__ray_call__", 1)
        if name.startswith("_"):
            raise AttributeError(name)
        meta = self._method_meta
        if meta and name not in meta:
            raise AttributeError(
                f"Actor class '{self._class_name}' has no method '{name}'")
        num_returns = (meta or {}).get(name, {}).get("num_returns", 1)
        return ActorMethod(self, name, num_returns)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()})"

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return (isinstance(other, ActorHandle)
                and other._actor_id == self._actor_id)

    def __reduce__(self):
        return (_rebuild_actor_handle,
                (self._actor_id, self._class_name, self._method_meta))


def _rebuild_actor_handle(actor_id, class_name, method_meta):
    return ActorHandle(actor_id, class_name, method_meta)


class ActorClass:
    def __init__(self, cls: type, options_dict: Dict[str, Any]):
        self._cls = cls
        self._default_options = actor_options(options_dict)
        functools.update_wrapper(self, cls, updated=[])

    @property
    def _class_name(self) -> str:
        return self._cls.__name__

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._class_name}' cannot be instantiated "
            "directly. Use 'cls.remote()'."
        )

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._remote(args, kwargs, self._default_options)

    def options(self, **updates):
        from ray_tpu.core.options import OptionsProxy
        new_opts = actor_options(updates, base=self._default_options)

        def _bind(args, kwargs):
            from ray_tpu.dag import ClassNode
            return ClassNode(self, args, kwargs, new_opts)

        return OptionsProxy(
            submit=lambda args, kwargs: self._remote(args, kwargs, new_opts),
            bind=_bind)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassNode
        return ClassNode(self, args, kwargs, self._default_options)

    def method_meta(self) -> Dict[str, Any]:
        meta: Dict[str, Any] = {}
        for name, member in inspect.getmembers(self._cls,
                                               predicate=callable):
            if name.startswith("__") and name != "__call__":
                continue
            meta[name] = {
                "num_returns": getattr(member, "_num_returns", 1),
                "concurrency_group": getattr(member,
                                             "_concurrency_group", None),
                "is_async": (inspect.iscoroutinefunction(member)
                             or inspect.isasyncgenfunction(member)),
                "is_generator": inspect.isgeneratorfunction(member)
                or inspect.isasyncgenfunction(member),
            }
        return meta

    def _remote(self, args, kwargs, opts: ActorOptions) -> ActorHandle:
        from ray_tpu.core.worker import current_runtime
        rt = current_runtime()
        return rt.create_actor(self, opts, args, kwargs)


def method(*, num_returns: Any = 1, concurrency_group: Optional[str] = None):
    """`@method(num_returns=n)` decorator on actor methods
    (reference: python/ray/actor.py `method`)."""

    def decorator(fn):
        fn._num_returns = num_returns
        fn._concurrency_group = concurrency_group
        return fn

    return decorator
