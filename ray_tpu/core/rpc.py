"""Async RPC layer: length-prefixed msgpack frames over TCP.

Reference equivalent: `src/ray/rpc/` (gRPC server/client wrappers,
`grpc_server.h`, `client_call.h`). The design keeps the same shape — named
services with handler methods, retryable clients, server push for pubsub —
on an asyncio transport chosen for zero codegen and low per-call overhead.

Frame: [u32 little-endian length][msgpack body]
Body (request):  {"i": req_id, "m": method, "a": args_dict}
Body (response): {"i": req_id, "ok": bool, "r": result | "e": error_str}
Body (push):     {"push": channel, "d": data}   (server -> client only)

Blob frames (bulk data plane, e.g. array-channel pushes): embedding a
multi-megabyte payload in the msgpack body costs one full copy at pack
time and another at unpack. A call made with `_blob=` instead ships the
payload OUT OF BAND, after the body, in the same frame:

    [u32 (BLOB_BIT | total)][u32 body_len][body][raw blob bytes]

The body carries `_bk`, the argument name the blob binds to; read_frame
reads the blob into one dedicated buffer and attaches it to the decoded
args untouched, so the receiver can build zero-copy views (np.frombuffer,
dlpack) directly over the wire buffer. BLOB_BIT is bit 31 of the length
word (MAX_FRAME < 2^29 keeps it unambiguous).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import struct
import sys
import threading
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

import msgpack

from ray_tpu.core import attribution

logger = logging.getLogger(__name__)


def _faults_enabled() -> bool:
    """True only when core/faults.py is loaded AND armed — the hot path
    pays a dict lookup, never an import, when fault injection is off."""
    faults = sys.modules.get("ray_tpu.core.faults")
    return faults is not None and faults.enabled

_LEN = struct.Struct("<I")
MAX_FRAME = 512 * 1024 * 1024
BLOB_BIT = 0x8000_0000


def pack(obj: Any) -> bytes:
    body = msgpack.packb(obj, use_bin_type=True)
    return _LEN.pack(len(body)) + body


def pack_blob_frames(obj: Any, blob_key: str, chunks) -> list:
    """A request frame whose bulk payload rides out of band: returns a
    chunk list for the transport (never joined — a join IS the copy this
    path exists to skip). `obj["a"][blob_key]` must be absent; the reader
    re-attaches the blob under that name."""
    body = msgpack.packb(dict(obj, _bk=blob_key), use_bin_type=True)
    blob_len = sum(len(c) for c in chunks)
    total = _LEN.size + len(body) + blob_len
    if total > MAX_FRAME:
        raise ConnectionError(f"frame too large: {total}")
    return [_LEN.pack(BLOB_BIT | total) + _LEN.pack(len(body)) + body,
            *chunks]


async def read_frame(reader: asyncio.StreamReader) -> Any:
    header = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(header)
    if length & BLOB_BIT:
        length &= ~BLOB_BIT
        if length > MAX_FRAME:
            raise ConnectionError(f"frame too large: {length}")
        (body_len,) = _LEN.unpack(await reader.readexactly(_LEN.size))
        if body_len > length - _LEN.size:
            # body_len is wire-supplied: bound it by the (already capped)
            # total, or a corrupt peer could demand a multi-GiB read.
            raise ConnectionError(
                f"blob frame body_len {body_len} exceeds frame {length}")
        body = await reader.readexactly(body_len)
        # The blob lands in ONE dedicated buffer and is handed to the
        # handler as-is: np.frombuffer/memoryview over it is zero-copy.
        blob = await reader.readexactly(length - _LEN.size - body_len)
        msg = msgpack.unpackb(body, raw=False)
        bk = msg.pop("_bk", None)
        if bk is not None:
            msg.setdefault("a", {})[bk] = blob
        return msg
    if length > MAX_FRAME:
        raise ConnectionError(f"frame too large: {length}")
    body = await reader.readexactly(length)
    return msgpack.unpackb(body, raw=False)


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class _BatchedWriter:
    """Coalesces frames queued within one event-loop tick into a single
    transport write — without taxing lone frames.

    On virtualized hosts a socket send costs 0.1-1 ms of syscall time, so
    per-frame writes dominate the task hot loop (measured: ~0.8 ms/write
    on the dev box, 1 write per push_task). The first frame of a loop tick
    is written immediately (a sequential request/reply exchange never waits
    for the next tick — deferring every frame cost ~0.2 ms of round-trip
    p50); frames that follow within the same tick buffer and go out in one
    coalesced send at tick end. Ordering holds because every sender runs on
    the loop thread and the buffer drains before newer immediate writes."""

    __slots__ = ("_writer", "_loop", "_buf", "_scheduled", "_hot",
                 "on_write_error")

    # Above this much unflushed transport buffer, senders pause on drain
    # (backpressure for bulk transfers sharing the connection).
    DRAIN_THRESHOLD = 4 * 1024 * 1024

    def __init__(self, writer: asyncio.StreamWriter,
                 loop: asyncio.AbstractEventLoop):
        self._writer = writer
        self._loop = loop
        self._buf: list = []
        self._scheduled = False
        self._hot = False          # a write already happened this tick
        self.on_write_error = None

    def send(self, frame: bytes) -> None:
        if not self._hot and not self._buf:
            # First frame this tick: write now, mark the tick hot so a
            # burst that follows coalesces instead of paying one syscall
            # per frame.
            self._hot = True
            self._loop.call_soon(self._cool)
            self._write(frame)
            return
        self._buf.append(frame)
        if not self._scheduled:
            self._scheduled = True
            self._loop.call_soon(self.flush)

    def send_frames(self, chunks: list) -> None:
        """Write one logical frame given as a chunk list (blob frames).

        Bypasses coalescing — the payload is bulk by construction — but
        drains any buffered frames first so ordering holds. Each chunk is
        written separately: the transport keeps a reference, so a
        multi-megabyte array buffer is never joined into a fresh bytes
        object on the way out."""
        self.flush()
        for c in chunks:
            self._write(c)
        self._hot = True
        self._loop.call_soon(self._cool)

    def _cool(self) -> None:
        self._hot = False

    def flush(self) -> None:
        self._scheduled = False
        if not self._buf:
            return
        data = self._buf[0] if len(self._buf) == 1 else b"".join(self._buf)
        self._buf.clear()
        self._write(data)

    def _write(self, data: bytes) -> None:
        if attribution.enabled:
            import time as _time

            t0 = _time.perf_counter()
            try:
                self._write_inner(data)
            finally:
                attribution.record("rpc.frame_write",
                                   _time.perf_counter() - t0)
            return
        self._write_inner(data)

    def _write_inner(self, data: bytes) -> None:
        try:
            if (self._writer.transport is not None
                    and self._writer.transport.is_closing()):
                raise ConnectionResetError("transport closing")
            self._writer.write(data)
        except Exception:
            cb = self.on_write_error
            if cb is not None:
                try:
                    cb()
                except Exception:
                    pass

    async def drain_if_needed(self) -> None:
        transport = self._writer.transport
        if (transport is not None and not transport.is_closing()
                and transport.get_write_buffer_size() > self.DRAIN_THRESHOLD):
            try:
                await self._writer.drain()
            except Exception:
                pass


class RpcServer:
    """Serves handler methods named `handle_<method>`; handlers are
    `async def handle_x(self_conn, **args) -> result`."""

    def __init__(self, handlers: Any, host: str = "127.0.0.1",
                 port: int = 0):
        self._handlers = handlers
        self._host = host
        self._port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: Dict[int, "ServerConnection"] = {}
        self._next_conn_id = 0

    @property
    def port(self) -> int:
        return self._port

    @property
    def address(self) -> str:
        return f"{self._host}:{self._port}"

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connect, self._host, self._port)
        self._port = self._server.sockets[0].getsockname()[1]

    async def _on_connect(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        self._next_conn_id += 1
        conn = ServerConnection(self._next_conn_id, reader, writer,
                                self._handlers)
        self._conns[conn.conn_id] = conn
        try:
            await conn.serve()
        finally:
            self._conns.pop(conn.conn_id, None)
            on_disc = getattr(self._handlers, "on_client_disconnect", None)
            if on_disc is not None:
                try:
                    await on_disc(conn)
                except Exception:
                    logger.exception("disconnect handler failed")

    async def stop(self) -> None:
        # Close live connections BEFORE waiting on the listener: since
        # 3.12 `Server.wait_closed()` also waits for connection handlers,
        # so a handler blocked in read_frame would hang the stop forever.
        # The wait stays bounded as a backstop (gh-120866 class hangs).
        for conn in list(self._conns.values()):
            conn.close()
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except (asyncio.TimeoutError, TimeoutError):
                pass


class ServerConnection:
    """One client connection on the server side; supports push()."""

    def __init__(self, conn_id: int, reader, writer, handlers):
        self.conn_id = conn_id
        self._reader = reader
        self._writer = writer
        self._handlers = handlers
        self._batch = _BatchedWriter(writer, asyncio.get_running_loop())
        self.metadata: Dict[str, Any] = {}  # handler-attached state
        self.closed = False

        def _mark_closed():
            self.closed = True

        self._batch.on_write_error = _mark_closed

    async def serve(self) -> None:
        try:
            while True:
                msg = await read_frame(self._reader)
                asyncio.ensure_future(self._dispatch(msg))
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self.closed = True

    async def _dispatch(self, msg: Dict[str, Any]) -> None:
        req_id, method = msg.get("i"), msg.get("m")
        if method == "__schema__":
            # Built-in schema handshake (core/wire.py): reply with our
            # digest; the CLIENT decides compatibility so old servers
            # never have to know new messages. A client that also SENDS
            # its digest lets this side verify symmetry and unlock the
            # fast-path decode (wire.from_wire_fast) for the connection:
            # both encoders proven identical means per-field validation
            # on every message buys nothing.
            from ray_tpu.core.wire import (SchemaMismatchError,
                                           check_digest, schema_digest)

            peer = (msg.get("a") or {}).get("digest")
            if peer:
                try:
                    check_digest(peer)
                    self.metadata["wire_fast"] = True
                except SchemaMismatchError:
                    # The client will see the same mismatch from our
                    # digest and fail its connect; until then every
                    # decode on this conn stays validated.
                    self.metadata["wire_fast"] = False
            await self._reply(req_id, ok=True, result=schema_digest())
            return
        handler = getattr(self._handlers, f"handle_{method}", None)
        if handler is None:
            await self._reply(req_id, ok=False,
                              error=f"no such method: {method}")
            return
        gate = getattr(self._handlers, "check_dispatch", None)
        if gate is not None:
            # Handler-level admission gate (e.g. a GCS follower replica
            # redirecting mutations to the leader). Raising here surfaces
            # as the same typed error string a handler exception would,
            # so clients need no new wire machinery to see it.
            try:
                gate(method)
            except Exception as e:  # noqa: BLE001
                await self._reply(req_id, ok=False,
                                  error=f"{type(e).__name__}: {e}")
                return
        if _faults_enabled():
            # Deterministic fault injection (core/faults.py): a drop rule
            # swallows the request here — the client sees a timeout /
            # ConnectionLost exactly as if the frame died on the wire; a
            # duplicate rule dispatches the handler a second time with
            # its reply discarded (at-least-once delivery). The
            # duplicate runs CONCURRENTLY, as real redelivery would — an
            # inline await of a handler that parks (e.g. a queued lease)
            # would stall the genuine dispatch behind it.
            from ray_tpu.core import faults

            try:
                duplicate = await faults.on_server_dispatch(method)
            except faults.FaultInjected:
                return

            if duplicate:
                async def _dup():
                    try:
                        await handler(self, **(msg.get("a") or {}))
                    except Exception:
                        logger.debug("duplicated handler %s failed",
                                     method, exc_info=True)

                asyncio.ensure_future(_dup())
        try:
            result = await handler(self, **(msg.get("a") or {}))
            await self._reply(req_id, ok=True, result=result)
        except Exception as e:  # noqa: BLE001
            logger.debug("handler %s failed", method, exc_info=True)
            await self._reply(req_id, ok=False,
                              error=f"{type(e).__name__}: {e}")

    async def _reply(self, req_id, ok: bool, result=None, error=None):
        if req_id is None or self.closed:
            return
        body = {"i": req_id, "ok": ok}
        if ok:
            body["r"] = result
        else:
            body["e"] = error
        await self._send(body)

    async def push(self, channel: str, data: Any) -> None:
        await self._send({"push": channel, "d": data})

    async def _send(self, body) -> None:
        if self.closed:
            return
        try:
            self._batch.send(pack(body))
            await self._batch.drain_if_needed()
        except (ConnectionError, OSError):
            self.closed = True

    def close(self) -> None:
        self.closed = True
        try:
            self._batch.flush()
        except Exception:
            pass
        try:
            self._writer.close()
        except Exception:
            pass


class RpcClient:
    """Async client with request-response and push-subscription support."""

    def __init__(self, address: str, handshake: bool = True):
        host, port = address.rsplit(":", 1)
        self._host, self._port = host, int(port)
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._batch: Optional[_BatchedWriter] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._push_handlers: Dict[str, Callable[[Any], Any]] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._handshake = handshake
        self.connected = False

    @property
    def address(self) -> str:
        return f"{self._host}:{self._port}"

    async def connect(self, timeout: float = 10.0,
                      retry_interval: float = 0.1) -> None:
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        last_err: Optional[Exception] = None
        while loop.time() < deadline:
            try:
                self._reader, self._writer = await asyncio.open_connection(
                    self._host, self._port)
                self._batch = _BatchedWriter(self._writer, loop)
                self._reader_task = asyncio.ensure_future(self._read_loop())
                self.connected = True
                if self._handshake:
                    # Version handshake: reject an incompatible peer NOW
                    # with a typed error instead of corrupting a protocol
                    # exchange later (core/wire.py evolution rules).
                    from ray_tpu.core.wire import (SchemaMismatchError,
                                                   check_digest,
                                                   schema_digest)

                    try:
                        # Send our digest too: a server that verifies it
                        # unlocks the post-handshake fast-path decode
                        # for this connection (see ServerConnection).
                        digest = await self.call(
                            "__schema__", digest=schema_digest(),
                            timeout=max(5.0, timeout))
                    except ConnectionLost:
                        raise          # peer died mid-handshake
                    except (asyncio.TimeoutError, TimeoutError):
                        await self.close()
                        raise ConnectionLost(
                            f"{self.address}: schema handshake timed out")
                    except RpcError:
                        # Pre-handshake server ("no such method"): treat
                        # as schema-less rather than unreachable.
                        digest = None
                    try:
                        check_digest(digest or {})
                    except SchemaMismatchError:
                        await self.close()  # don't leak a half-open client
                        raise
                return
            except OSError as e:
                last_err = e
                await asyncio.sleep(retry_interval)
        raise ConnectionLost(
            f"could not connect to {self.address}: {last_err}")

    async def _read_loop(self) -> None:
        try:
            while True:
                msg = await read_frame(self._reader)
                if "push" in msg:
                    handler = self._push_handlers.get(msg["push"])
                    if handler is not None:
                        try:
                            res = handler(msg.get("d"))
                            if asyncio.iscoroutine(res):
                                asyncio.ensure_future(res)
                        except Exception:
                            logger.exception("push handler failed")
                    continue
                fut = self._pending.pop(msg.get("i"), None)
                if fut is not None and not fut.done():
                    if msg.get("ok"):
                        fut.set_result(msg.get("r"))
                    else:
                        fut.set_exception(RpcError(msg.get("e")))
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            self.connected = False
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionLost(str(e)))
            self._pending.clear()

    def on_push(self, channel: str, handler: Callable[[Any], Any]) -> None:
        self._push_handlers[channel] = handler

    async def call(self, method: str, timeout: Optional[float] = 60.0,
                   _blob: Optional[list] = None, _blob_key: str = "data",
                   **args: Any) -> Any:
        """One request/response round trip. `_blob` (a list of buffer
        chunks) ships out of band after the msgpack body and re-attaches
        at the receiver as args[_blob_key] — the bulk data plane path
        (see module docstring)."""
        if not self.connected:
            raise ConnectionLost(f"not connected to {self.address}")
        if _faults_enabled():
            # Client-side injection point (core/faults.py): drops raise
            # ConnectionLost, delays sleep before the frame is written.
            from ray_tpu.core import faults

            await faults.on_client_call(self.address, method)
        self._next_id += 1
        req_id = self._next_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        body = {"i": req_id, "m": method, "a": args}
        if _blob is None:
            self._batch.send(pack(body))
        else:
            self._batch.send_frames(
                pack_blob_frames(body, _blob_key, _blob))
        await self._batch.drain_if_needed()
        if timeout is None:
            return await fut
        return await asyncio.wait_for(fut, timeout)

    async def notify(self, method: str, **args: Any) -> None:
        """Fire-and-forget (no response expected)."""
        if not self.connected:
            raise ConnectionLost(f"not connected to {self.address}")
        self._batch.send(pack({"i": None, "m": method, "a": args}))
        await self._batch.drain_if_needed()

    async def close(self) -> None:
        self.connected = False
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._batch is not None:
            self._batch.flush()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass


class EventLoopThread:
    """A dedicated asyncio loop on a daemon thread — the process's RPC
    engine (analogue of the reference's io_service threads)."""

    def __init__(self, name: str = "rpc-loop"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=name)
        self._thread.start()

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro: Awaitable, timeout: Optional[float] = None) -> Any:
        """Run a coroutine on the loop from a sync thread, blocking.

        On timeout the in-flight coroutine is cancelled so it does not keep
        running orphaned on the loop."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        try:
            return fut.result(timeout)
        except concurrent.futures.TimeoutError:
            fut.cancel()
            raise

    def spawn(self, coro: Awaitable) -> None:
        asyncio.run_coroutine_threadsafe(coro, self.loop)

    def call_soon(self, fn: Callable[[], Any]) -> None:
        """Schedule a plain callable on the loop from any thread."""
        self.loop.call_soon_threadsafe(fn)

    def stop(self, drain_timeout: float = 2.0) -> None:
        """Cancel every task still pending on the loop and let it unwind
        before stopping — otherwise asyncio logs "Task was destroyed but it
        is pending" for each orphaned background coroutine (lease fetches,
        idle-linger timers) on interpreter exit."""

        async def _drain():
            tasks = [t for t in asyncio.all_tasks()
                     if t is not asyncio.current_task()]
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

        try:
            fut = asyncio.run_coroutine_threadsafe(_drain(), self.loop)
            fut.result(drain_timeout)
        except Exception:
            pass
        self.loop.call_soon_threadsafe(self.loop.stop)
