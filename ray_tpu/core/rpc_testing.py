"""In-process loopback fakes for the RPC layer — the fast unit tier.

Reference equivalent: `src/mock/ray/rpc/` — gmock transports that let
core-protocol logic (leasing, retry, decode) run in microseconds with no
sockets or processes. Here the same job is done by driving the REAL
`ServerConnection` dispatch machinery over fake asyncio streams:

- `make_server_connection(handlers)` builds a genuine
  `rpc.ServerConnection` whose writer records frames instead of hitting
  a socket, so handshake/dispatch/reply code paths are the production
  ones, not re-implementations;
- `LoopbackClient` is an `RpcClient`-shaped caller that delivers
  requests straight into that connection and decodes the recorded reply
  frame, round-tripping every payload through msgpack so wire typing
  (tuples->lists, bytes vs str) is faithful to the TCP transport.

Used by `tests/test_unit_*.py` (`-m unit`): seconds-fast, zero cluster
processes.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Optional

import msgpack

from ray_tpu.core.rpc import _LEN, RpcError, ServerConnection


class FakeTransport:
    def __init__(self):
        self._closing = False

    def is_closing(self) -> bool:
        return self._closing

    def get_write_buffer_size(self) -> int:
        return 0


class FakeWriter:
    """StreamWriter stand-in: frames land in `self.frames`."""

    def __init__(self):
        self.transport = FakeTransport()
        self.frames: list = []

    def write(self, data: bytes) -> None:
        self.frames.append(data)

    def close(self) -> None:
        self.transport._closing = True

    async def drain(self) -> None:
        return None


def make_server_connection(handlers: Any) -> ServerConnection:
    """A real ServerConnection over fake streams (must run inside an
    event loop — ServerConnection binds the running loop)."""
    return ServerConnection(1, None, FakeWriter(), handlers)


def _decode_frames(writer: FakeWriter) -> list:
    """Split the recorded byte stream back into msgpack bodies."""
    data = b"".join(writer.frames)
    writer.frames.clear()
    out = []
    while data:
        (length,) = _LEN.unpack(data[:_LEN.size])
        body = data[_LEN.size:_LEN.size + length]
        out.append(msgpack.unpackb(body, raw=False))
        data = data[_LEN.size + length:]
    return out


class LoopbackClient:
    """RpcClient-compatible caller bound to an in-process connection.

    `handshake=True` performs the same `__schema__` digest exchange a
    TCP client does at connect — through the REAL server dispatch — so
    post-handshake state (`conn.metadata['wire_fast']`) is produced by
    production code, and a digest mismatch raises the same typed
    `SchemaMismatchError` the socket path raises.
    """

    def __init__(self, handlers: Any):
        self.handlers = handlers
        self.conn: Optional[ServerConnection] = None
        self.connected = False
        self._next_id = 0
        self._push_handlers: Dict[str, Any] = {}

    def on_push(self, channel: str, handler: Any) -> None:
        """Mirror of RpcClient.on_push: server pushes recorded into the
        fake writer are routed to `handler` as they are decoded (used by
        pubsub-consuming callers, e.g. a GcsClient bound to a loopback
        channel in core/simcluster.py)."""
        self._push_handlers[channel] = handler

    async def connect(self, handshake: bool = True,
                      digest: Optional[Dict[str, int]] = None) -> None:
        from ray_tpu.core.wire import check_digest, schema_digest

        self.conn = make_server_connection(self.handlers)
        self.connected = True
        if handshake:
            # Client side of the handshake (mirrors RpcClient.connect):
            # send our digest, validate the server's.
            server_digest = await self.call(
                "__schema__", digest=digest or schema_digest())
            check_digest(server_digest or {})

    async def _roundtrip(self, body: Dict[str, Any]) -> Any:
        # Wire fidelity: everything the transport would serialize is
        # msgpack round-tripped, so handlers see list-not-tuple, bytes
        # vs str, etc., exactly as over TCP.
        body = msgpack.unpackb(
            msgpack.packb(body, use_bin_type=True), raw=False)
        await self.conn._dispatch(body)
        self.conn._batch.flush()
        replies = _decode_frames(self.conn._writer)
        out = None
        for r in replies:
            if "push" in r:
                # Route server pushes (pubsub deliveries) like the TCP
                # client's read loop does instead of dropping them on
                # the floor of the fake writer.
                handler = self._push_handlers.get(r["push"])
                if handler is not None:
                    res = handler(r.get("d"))
                    if asyncio.iscoroutine(res):
                        await res
            elif r.get("i") == body.get("i"):
                out = r
        return out

    async def call(self, method: str, timeout: Optional[float] = 60.0,
                   **args: Any) -> Any:
        if not self.connected:
            raise RpcError("loopback client not connected")
        self._next_id += 1
        reply = await self._roundtrip(
            {"i": self._next_id, "m": method, "a": args})
        if reply is None:
            raise RpcError(f"no reply for {method}")
        if not reply.get("ok"):
            raise RpcError(reply.get("e"))
        return reply.get("r")

    async def notify(self, method: str, **args: Any) -> None:
        if not self.connected:
            raise RpcError("loopback client not connected")
        await self._roundtrip({"i": None, "m": method, "a": args})

    async def close(self) -> None:
        self.connected = False
