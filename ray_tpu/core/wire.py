"""Typed, versioned wire schema for the core control-plane protocols.

Reference equivalent: the protobuf schema layer
(`src/ray/protobuf/common.proto` TaskSpec, `gcs_service.proto:63-703`
table RPCs, `core_worker.proto:422` PushTask). The reference gets message
typing, versioning, and decode validation from protoc; here the same
guarantees come from a registry of msgpack-shaped dataclasses:

- every core message declares its fields and types once (`@wire_message`);
- `to_wire` stamps the message name + schema version into the payload;
- `from_wire` validates the version and every field's presence and type,
  raising *typed* errors (`WireDecodeError` / `SchemaMismatchError`) so a
  malformed or mixed-version peer produces a diagnosable failure instead
  of a KeyError five frames deep in a handler;
- `schema_digest()` is exchanged in a connection handshake (rpc.py) so
  incompatible peers are rejected at connect time, not mid-protocol.

Pickle never appears at this layer: it is reserved for *user* payloads
(function args/returns), which ride inside `bytes` fields of these typed
envelopes.

Evolution rules (the proto2-ish contract):
- adding an optional field (with default) is compatible — old peers omit
  it, new peers fill the default on decode;
- unknown fields from a NEWER minor revision are ignored on decode;
- removing or re-typing a field requires a version bump, which the
  handshake turns into an explicit `SchemaMismatchError`.
"""

import dataclasses
import typing
from typing import Any, Dict, Optional

from ray_tpu.exceptions import RayError


class WireError(RayError):
    """Base for wire-schema failures."""


class WireDecodeError(WireError):
    """Payload failed schema validation (missing/mistyped/unknown)."""


class SchemaMismatchError(WireError):
    """Peer speaks an incompatible schema version."""


_REGISTRY: Dict[str, tuple] = {}   # name -> (cls, version, field specs)

# Wire-type predicates. Containers are validated shallowly (their element
# types are dynamic in msgpack anyway); `Any` skips the check.
_CHECKS = {
    int: lambda v: isinstance(v, int) and not isinstance(v, bool),
    float: lambda v: isinstance(v, (int, float))
    and not isinstance(v, bool),
    str: lambda v: isinstance(v, str),
    bytes: lambda v: isinstance(v, (bytes, bytearray)),
    bool: lambda v: isinstance(v, bool),
    dict: lambda v: isinstance(v, dict),
    list: lambda v: isinstance(v, (list, tuple)),
}


def _spec_of(hint) -> tuple:
    """(predicate, optional) for a type hint."""
    origin = typing.get_origin(hint)
    if origin is typing.Union:
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        if len(args) == 1:
            pred, _ = _spec_of(args[0])
            return pred, True
        return None, True
    if origin in (dict, list, tuple):
        hint = dict if origin is dict else list
    if hint is Any:
        return None, True
    return _CHECKS.get(hint), False


def wire_message(name: str, version: int = 1):
    """Register a dataclass as a wire message.

    The class gains Mapping-style access (`msg["field"]`, `msg.get`) so
    protocol handlers written against dict payloads keep working on typed
    messages unchanged.
    """

    def deco(cls):
        cls = dataclasses.dataclass(cls)
        hints = typing.get_type_hints(cls)
        specs = []
        for f in dataclasses.fields(cls):
            pred, optional = _spec_of(hints[f.name])
            required = (f.default is dataclasses.MISSING
                        and f.default_factory is dataclasses.MISSING)
            specs.append((f.name, pred, optional, required))
        cls._wire_name = name
        cls._wire_version = version
        cls._wire_specs = specs
        # Precomputed tables for the post-handshake fast decode
        # (from_wire_fast): static defaults, factory defaults (fresh
        # container per instance), and the required-field set checked
        # with one subset test instead of a per-field loop.
        cls._wire_defaults = {
            f.name: f.default for f in dataclasses.fields(cls)
            if f.default is not dataclasses.MISSING}
        cls._wire_factories = tuple(
            (f.name, f.default_factory) for f in dataclasses.fields(cls)
            if f.default_factory is not dataclasses.MISSING)
        cls._wire_required = frozenset(
            f.name for f in dataclasses.fields(cls)
            if f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING)

        def __getitem__(self, key):
            try:
                return getattr(self, key)
            except AttributeError:
                raise KeyError(key) from None

        def __setitem__(self, key, value):
            setattr(self, key, value)

        def get(self, key, default=None):
            return getattr(self, key, default)

        def __contains__(self, key):
            return hasattr(self, key)

        def as_dict(self):
            """Plain dict (incl. fields added post-decode), no envelope."""
            return {k: v for k, v in self.__dict__.items()
                    if not k.startswith("_wire")}

        def keys(self):
            return self.as_dict().keys()

        def replace(self, **kw):
            """Shallow copy with fields overridden (keeps extra
            post-decode attributes, unlike dataclasses.replace)."""
            import copy

            dup = copy.copy(self)
            for k, v in kw.items():
                setattr(dup, k, v)
            return dup

        cls.__getitem__ = __getitem__
        cls.__setitem__ = __setitem__
        cls.get = get
        cls.__contains__ = __contains__
        cls.as_dict = as_dict
        cls.keys = keys
        cls.replace = replace
        if name in _REGISTRY:
            raise ValueError(f"duplicate wire message {name!r}")
        _REGISTRY[name] = (cls, version)
        return cls

    return deco


def to_wire(msg) -> Dict[str, Any]:
    """Typed message -> msgpack-able dict with schema envelope."""
    name = getattr(msg, "_wire_name", None)
    if name is None:
        raise WireError(f"{type(msg).__name__} is not a wire message")
    d = {"_t": name, "_v": msg._wire_version}
    d.update(msg.as_dict())
    return d


def from_wire(payload: Any, expect: Optional[str] = None):
    """Validated decode. Raises WireDecodeError / SchemaMismatchError."""
    if not isinstance(payload, dict):
        raise WireDecodeError(
            f"wire payload must be a map, got {type(payload).__name__}")
    name = payload.get("_t")
    if not isinstance(name, str):
        raise WireDecodeError("payload missing message type tag '_t'")
    if expect is not None and name != expect:
        raise WireDecodeError(f"expected {expect!r}, got {name!r}")
    entry = _REGISTRY.get(name)
    if entry is None:
        raise WireDecodeError(f"unknown wire message type {name!r}")
    cls, version = entry
    v = payload.get("_v")
    if not isinstance(v, int):
        raise WireDecodeError(f"{name}: missing schema version '_v'")
    if v != version:
        # Single-integer versions are majors: a bump means fields were
        # removed or re-typed, so decoding across it is unsafe either way.
        raise SchemaMismatchError(
            f"{name}: peer schema v{v}, local v{version}")
    kwargs = {}
    for fname, pred, optional, required in cls._wire_specs:
        if fname in payload:
            val = payload[fname]
            if val is None:
                if not optional:
                    raise WireDecodeError(
                        f"{name}.{fname}: null not allowed")
            elif pred is not None and not pred(val):
                raise WireDecodeError(
                    f"{name}.{fname}: bad type {type(val).__name__}")
            kwargs[fname] = val
        elif required:
            raise WireDecodeError(f"{name}: missing field {fname!r}")
    # Unknown (newer-minor) fields are carried through, not dropped, so a
    # relay node doesn't silently strip data it doesn't understand.
    msg = cls(**kwargs)
    for k, val in payload.items():
        if k not in ("_t", "_v") and not hasattr(msg, k):
            object.__setattr__(msg, k, val)
    return msg


def from_wire_fast(payload: Any, expect: Optional[str] = None):
    """Post-handshake decode: skips per-field type validation.

    Safe ONLY after the connection's schema-digest handshake proved both
    ends encode every message identically (rpc.py `__schema__` exchange:
    the digest covers name->version for every registered message, so a
    payload produced by the peer's `to_wire` is structurally what our
    validated decoder would accept). The envelope (type tag, version,
    required-field presence) is still checked — one dict hit and one
    frozenset subset test — and ANY shortfall falls back to the validated
    `from_wire`, whose typed errors name the offending field. Measured
    ~5x cheaper than the validated decode on a 16-field TaskSpec.
    """
    if type(payload) is not dict:
        return from_wire(payload, expect)
    name = payload.get("_t")
    entry = _REGISTRY.get(name)
    if entry is None or (expect is not None and name != expect):
        return from_wire(payload, expect)   # typed error path
    cls, version = entry
    if (payload.get("_v") != version
            or not cls._wire_required <= payload.keys()):
        return from_wire(payload, expect)   # mismatch: validated decode
    msg = cls.__new__(cls)
    d = msg.__dict__
    if cls._wire_defaults:
        d.update(cls._wire_defaults)
    d.update(payload)
    del d["_t"], d["_v"]
    for fname, factory in cls._wire_factories:
        if fname not in d or d[fname] is None:
            d[fname] = factory()
    return msg


class SpecTemplate:
    """Template-spec encoding for repeated submissions of one function.

    Reference intuition: `direct_task_transport` resubmits the same
    TaskSpec protobuf shape thousands of times; only ids/args change.
    Here the invariant portion of a message's wire dict (fn_key, name,
    resources, retries, runtime_env, pg, owner, ...) is encoded ONCE from
    a fully-validated prototype; each call copies the dict and overwrites
    just the per-call fields. The copy preserves key order, so the bytes
    msgpack produces are identical to a full `to_wire` of an equivalent
    message (golden-tested in tests/test_unit_spec_template.py).

    Cache invalidation is by construction: the template cache key must
    include every invariant field (options/runtime-env changes produce a
    different key, hence a fresh validated prototype).
    """

    __slots__ = ("_base",)

    def __init__(self, prototype):
        self._base = to_wire(prototype)

    def encode(self, **per_call: Any) -> Dict[str, Any]:
        d = dict(self._base)
        for k, v in per_call.items():
            d[k] = v
        return d


def schema_digest() -> Dict[str, int]:
    """{message name: version} — exchanged in the connect handshake."""
    return {name: ver for name, (cls, ver) in _REGISTRY.items()}


def check_digest(peer: Dict[str, int]) -> None:
    """Raise SchemaMismatchError if any message BOTH sides know differs
    in version. One-sided messages are fine (feature skew, not schema
    skew: the peer simply never sends them)."""
    # Read the registry directly (not schema_digest()) so tests can fake
    # a peer by patching schema_digest without also changing "mine".
    mine = {name: ver for name, (_cls, ver) in _REGISTRY.items()}
    bad = {n: (v, mine[n]) for n, v in peer.items()
           if n in mine and mine[n] != v}
    if bad:
        detail = ", ".join(f"{n}: peer v{pv} != local v{lv}"
                           for n, (pv, lv) in sorted(bad.items()))
        raise SchemaMismatchError(f"incompatible wire schema ({detail})")


# ======================================================================
# Core protocol messages.
# ======================================================================

@wire_message("TaskSpec", version=1)
class TaskSpec:
    """A normal-task invocation (reference: common.proto TaskSpec +
    core_worker.proto PushTaskRequest)."""
    task_id: str
    job_id: str
    name: str
    fn_key: str
    args: bytes
    num_returns: int = 1
    arg_oids: list = dataclasses.field(default_factory=list)
    resources: Dict[str, float] = dataclasses.field(default_factory=dict)
    owner: Optional[str] = None
    streaming: bool = False
    max_retries: int = 0
    runtime_env: Optional[dict] = None
    pg: Optional[dict] = None          # {pg_id, bundle_index}
    visible_chips: Optional[list] = None
    trace_ctx: Optional[str] = None    # W3C traceparent (util/tracing.py)
    # Per-task cProfile opt-in (.options(_metadata={"profile": True}):
    # the worker wraps exec in cProfile and dumps pstats next to its
    # log). Optional-with-default: absent on the wire when unset.
    profile: Optional[bool] = None


@wire_message("ActorTaskSpec", version=1)
class ActorTaskSpec:
    """An actor-method invocation (reference: common.proto
    ActorTaskSpec)."""
    task_id: str
    job_id: str
    actor_id: str
    method: str
    name: str
    args: bytes
    seq: int
    num_returns: int = 1
    owner: Optional[str] = None
    streaming: bool = False
    concurrency_group: Optional[str] = None
    trace_ctx: Optional[str] = None    # W3C traceparent (util/tracing.py)


@wire_message("LeaseRequest", version=1)
class LeaseRequest:
    """Worker-lease request (reference: raylet.proto
    RequestWorkerLease)."""
    resources: Dict[str, float]
    job_id: Optional[str] = None
    request_id: Optional[str] = None
    scheduling_key: str = ""
    is_actor: bool = False
    spillback_count: int = 0
    bundle: Optional[list] = None      # (pg_id, bundle_index)
    # Batched grants (round 8): ask for up to `count` workers in one RPC
    # (request_worker_leases). Optional-with-default per the evolution
    # rules: old peers omit it, new peers fill 1 on decode.
    count: int = 1


@wire_message("LeaseReply", version=1)
class LeaseReply:
    """Lease reply: a granted worker, a spillback target, or a typed
    failure (reference: raylet.proto RequestWorkerLeaseReply)."""
    granted: Optional[dict] = None     # worker info (address, lease_id…)
    spillback: Optional[str] = None    # retry at this raylet instead
    error: Optional[str] = None
    detail: Optional[str] = None
    # Batched grants: list of worker-info dicts, possibly shorter than
    # the requested count (partial grant — the client re-pumps).
    grants: Optional[list] = None


@wire_message("ObjectRequest", version=1)
class ObjectRequest:
    """Object fetch/locate request (reference: object_manager.proto
    Pull/Push)."""
    oid: str
    owner_address: Optional[str] = None
    chunk_index: int = 0
    pull_timeout: Optional[float] = None


@wire_message("ObjectInfo", version=1)
class ObjectInfo:
    """Object metadata reply: location set + inline value or shm
    handle."""
    oid: str
    locations: list = dataclasses.field(default_factory=list)
    size: Optional[int] = None
    inline: Optional[bytes] = None
    shm_name: Optional[str] = None
    error: Optional[str] = None


@wire_message("ActorInfo", version=1)
class ActorInfo:
    """GCS actor-table record (reference: gcs.proto ActorTableData)."""
    actor_id: str
    state: str
    job_id: Optional[str] = None
    name: Optional[str] = None
    namespace: Optional[str] = None
    address: Optional[str] = None
    owner: Optional[str] = None
    class_name: Optional[str] = None
    max_restarts: int = 0
    max_task_retries: int = 0
    num_restarts: int = 0
    detached: bool = False
    death_cause: Optional[str] = None
    resources: Dict[str, float] = dataclasses.field(default_factory=dict)
    method_meta: Optional[dict] = None


@wire_message("JobInfo", version=1)
class JobInfo:
    """GCS job-table record (reference: gcs.proto JobTableData)."""
    job_id: str
    driver_pid: Optional[int] = None
    driver_address: Optional[str] = None
    namespace: Optional[str] = None
    sys_path: Optional[list] = None
    cwd: Optional[str] = None
    start_time: Optional[float] = None
    end_time: Optional[float] = None
    finished: bool = False
    entrypoint: Optional[str] = None
    metadata: Optional[dict] = None
    runtime_env: Optional[dict] = None


@wire_message("NodeInfo", version=1)
class NodeInfo:
    """GCS node registration (reference: gcs.proto GcsNodeInfo)."""
    node_id: str
    address: str
    object_store_address: Optional[str] = None
    resources: Dict[str, float] = dataclasses.field(default_factory=dict)
    labels: Optional[dict] = None
    is_head: bool = False


@wire_message("PubsubMessage", version=1)
class PubsubMessage:
    """One pubsub delivery (reference: pubsub.proto PubMessage)."""
    channel: str
    data: Any = None
    seq: Optional[int] = None
