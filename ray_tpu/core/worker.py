"""Global driver/worker state and the public module-level API.

Reference equivalent: `python/ray/_private/worker.py` — the `Worker` singleton
behind `ray.init` (`:1152`), `ray.get/put/wait`, `ray.kill`, etc.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional, Sequence, Union

from ray_tpu.core.object_ref import ObjectRef

_global_lock = threading.RLock()
_runtime = None


class _AutoInitError(RuntimeError):
    pass


def current_runtime(or_none: bool = False):
    global _runtime
    with _global_lock:
        if _runtime is None:
            if or_none:
                return None
            # Auto-init, like the reference's implicit ray.init() on first API use.
            init()
        return _runtime


def set_runtime(rt) -> None:
    global _runtime
    with _global_lock:
        _runtime = rt


def is_initialized() -> bool:
    return _runtime is not None


def _runtime_is_alive(rt) -> bool:
    """Probe a cached runtime before ignore_reinit_error reuses it.

    Two probe attempts before declaring death: a single short timeout
    would tear down a *healthy* cluster whose GCS is momentarily loaded
    (observed: heavy suites slow this box 30x), and teardown here is
    destructive — it kills the user's live actors.
    """
    if getattr(rt, "_shutdown", False):
        return False
    check = getattr(rt, "check_alive", None)
    if check is None:
        return True
    for attempt in range(2):
        try:
            if check():
                return True
        except Exception:
            pass
        if attempt == 0:
            # Back-to-back retries land in the same overload window;
            # give a momentarily-stalled GCS a beat to drain.
            import time
            time.sleep(1.0)
    return False


def init(address: Optional[str] = None, *,
         num_cpus: Optional[int] = None,
         num_gpus: Optional[int] = None,
         resources: Optional[dict] = None,
         local_mode: bool = False,
         namespace: Optional[str] = None,
         runtime_env: Optional[dict] = None,
         object_store_memory: Optional[int] = None,
         ignore_reinit_error: bool = False,
         include_dashboard: Optional[bool] = None,
         dashboard_port: Optional[int] = None,
         log_to_driver: bool = True,
         _system_config: Optional[dict] = None,
         **kwargs: Any):
    """Connect to (or start) a cluster. Reference: _private/worker.py:1152."""
    global _runtime
    with _global_lock:
        if _runtime is not None:
            if ignore_reinit_error:
                if _runtime_is_alive(_runtime):
                    return _runtime
                # The cached runtime is dead (its cluster was torn down or
                # the GCS is unreachable): reusing it would hand out stale
                # state — function caches, leaked leases — from a previous
                # session. Discard it and bring up a fresh one.
                try:
                    _runtime.shutdown()
                except Exception:
                    pass
                _runtime = None
            else:
                raise RuntimeError(
                    "ray_tpu.init() was already called. Pass "
                    "ignore_reinit_error=True to ignore.")
        from ray_tpu.core.config import ray_config
        ray_config().apply_system_config(_system_config)
        if not ray_config().flight_recorder:
            # _system_config lands only in THIS process; the recorder
            # flag must reach raylets/workers before they spawn, and
            # they read it from the inherited env (flight.disable sets
            # RAY_TPU_FLIGHT_RECORDER=0 — sticky for this process's
            # later children, like attribution's env flag).
            from ray_tpu.core import flight
            flight.disable()

        if address and address.startswith("ray://"):
            # Remote driver through the client proxy (reference:
            # python/ray/util/client — ray.init("ray://host:port")).
            from ray_tpu.util.client.runtime import ClientRuntime
            _runtime = ClientRuntime(address[len("ray://"):],
                                     namespace=namespace)
        elif local_mode:
            from ray_tpu.core.local_mode import LocalModeRuntime
            _runtime = LocalModeRuntime(num_cpus=num_cpus, namespace=namespace)
        else:
            try:
                from ray_tpu.core.cluster_runtime import ClusterRuntime
            except ImportError:
                # Cluster runtime not available in this build: degrade to the
                # in-process runtime (same API surface) with a warning.
                import warnings
                warnings.warn(
                    "cluster runtime unavailable; falling back to local mode",
                    stacklevel=2)
                from ray_tpu.core.local_mode import LocalModeRuntime
                _runtime = LocalModeRuntime(
                    num_cpus=num_cpus, namespace=namespace)
                return _runtime
            _runtime = ClusterRuntime.connect_or_start(
                address=address, num_cpus=num_cpus, num_gpus=num_gpus,
                resources=resources, namespace=namespace,
                object_store_memory=object_store_memory,
                runtime_env=runtime_env,
                include_dashboard=include_dashboard,
                dashboard_port=dashboard_port,
                log_to_driver=log_to_driver)
        return _runtime


def shutdown() -> None:
    global _runtime
    with _global_lock:
        if _runtime is not None:
            _runtime.shutdown()
            _runtime = None


def put(value: Any) -> ObjectRef:
    return current_runtime().put(value)


def get(object_refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    # Compiled-graph futures resolve through their channel, not the
    # object plane (reference: ray.get accepts CompiledDAGRef).
    if getattr(object_refs, "_is_compiled_dag_ref", False):
        return object_refs.get(timeout=timeout)
    if isinstance(object_refs, (list, tuple)) and any(
            getattr(r, "_is_compiled_dag_ref", False) for r in object_refs):
        return [get(r, timeout=timeout) for r in object_refs]
    return current_runtime().get(object_refs, timeout=timeout)


def wait(object_refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    return current_runtime().wait(
        object_refs, num_returns=num_returns, timeout=timeout,
        fetch_local=fetch_local)


def kill(actor, *, no_restart: bool = True) -> None:
    current_runtime().kill_actor(actor, no_restart=no_restart)


def cancel(object_ref: ObjectRef, *, force: bool = False,
           recursive: bool = True) -> None:
    current_runtime().cancel(object_ref, force=force, recursive=recursive)


def get_actor(name: str, namespace: Optional[str] = None):
    return current_runtime().get_actor(name, namespace=namespace)


def nodes() -> List[dict]:
    return current_runtime().nodes()


def cluster_resources() -> dict:
    return current_runtime().cluster_resources()


def available_resources() -> dict:
    return current_runtime().available_resources()


def timeline(filename: Optional[str] = None):
    rt = current_runtime()
    if hasattr(rt, "timeline"):
        return rt.timeline(filename)
    return []
