"""Shared-memory SPSC submission ring + doorbell (round 8).

Reference intuition: the zero-syscall submission queues of io_uring /
virtio — producer and consumer share a fixed-slot ring in mapped memory;
publishing an entry is a pair of plain stores, and the *only* syscall is
a doorbell written on the empty→non-empty edge to wake a sleeping
consumer. Here the rings carry task-spec deltas from a driver straight
to the *worker process* it leased (round 10: `cluster_runtime.
_worker_ring_enqueue` → the worker's `handle_attach_task_ring`
consumer), with a twin ring carrying replies — including `exec_us` and
the attribution split — back. The raylet only brokers the lease (its
grant advertises ring capability); it never sits on the per-task path,
which is what round 8's raylet-forwarded variant lost to direct TCP
push.

Layout of the shm segment (one ring per segment; reuses the raw
`shm_open+mmap` attach machinery of `object_store.attach_segment`, so
attaching costs no resource-tracker traffic):

    [0:8)    head  u64  — consumer cursor (slots consumed), consumer-written
    [8:16)   tail  u64  — producer cursor (slots published), producer-written
    [16:20)  nslots u32
    [20:24)  slot_bytes u32 (payload capacity per slot)
    [24:25)  closed u8 — either side sets it; the other observes
    [64:...) nslots slots of (u32 length + payload)

Single producer, single consumer, distinct processes. Cursors only ever
grow (mod 2^64); `tail - head` is the fill level. The producer writes
the slot payload *then* publishes by storing tail; the consumer reads
head's slot then releases it by storing head. CPython's struct stores
into the mmap are plain memory writes — on the cache-coherent hosts
this targets, publication order holds at the producer's bytecode
granularity (each interpreter step is far coarser than a store-buffer
drain).

Doorbell: a named FIFO next to the segment. The producer writes ONE
byte only when its push found the ring empty (`tail == head` before the
push); steady-state pushes into a non-empty ring are pure memory
writes — zero syscalls per task. The consumer registers the FIFO fd
with its event loop, drains the FIFO and then the ring on wakeup.
There is a textbook lost-wakeup window (consumer drains to empty while
the producer concurrently pushes and judges the ring non-empty from a
stale head); consumers close it with a coarse backstop poll rather
than a cross-process fence — a bounded blip on a nanosecond-wide race,
and the hot loop stays syscall-free. The poll is *adaptive*
(`AdaptivePoll`): it runs at `ring_backstop_poll_ms` while traffic
flows (bounding the worst-case latency of a lost doorbell), backs off
to `IDLE_POLL_S` after `IDLE_POLLS_TO_BACKOFF` consecutive empty
polls (an idle ring must not burn 20 wakeups/s forever), and snaps
back to the base period the moment a poll or doorbell finds traffic.
"""

from __future__ import annotations

import os
import struct
import threading
import time
from multiprocessing import shared_memory
from typing import List, Optional, Tuple

from ray_tpu.core import attribution, flight

_HDR = struct.Struct("<QQII")          # head, tail, nslots, slot_bytes
_LEN = struct.Struct("<I")
HEADER_BYTES = 64
_CLOSED_OFF = 24

# Consumers sleep at most this long before re-checking the ring even
# without a doorbell (lost-wakeup backstop; see module docstring).
# Kept as the blocking-helper default; the event-loop backstops pace
# themselves with AdaptivePoll below.
BACKSTOP_POLL_S = 0.05

# Adaptive-backstop bounds: after IDLE_POLLS_TO_BACKOFF consecutive
# empty polls the period backs off to IDLE_POLL_S; any traffic snaps it
# back to the configured base (ring_backstop_poll_ms).
IDLE_POLL_S = 0.25
IDLE_POLLS_TO_BACKOFF = 20


def backstop_poll_s() -> float:
    """Base backstop period from config (`ring_backstop_poll_ms`)."""
    from ray_tpu.core.config import ray_config

    return max(0.001, ray_config().ring_backstop_poll_ms / 1000.0)


class AdaptivePoll:
    """Backstop pacing for ring consumers (see module docstring): the
    fixed 50 ms poll of round 8 both wasted wakeups at idle and set the
    worst-case lost-doorbell latency. This keeps the base period while
    traffic flows and decays to `IDLE_POLL_S` once `observe()` reports
    `IDLE_POLLS_TO_BACKOFF` consecutive empty drains; any non-empty
    drain snaps the period back."""

    def __init__(self, base_s: Optional[float] = None):
        self.base_s = base_s if base_s is not None else backstop_poll_s()
        self._idle_polls = 0

    @property
    def interval(self) -> float:
        if self._idle_polls >= IDLE_POLLS_TO_BACKOFF:
            return max(IDLE_POLL_S, self.base_s)
        return self.base_s

    def observe(self, drained: int) -> None:
        """Report how many entries the poll (or a doorbell wakeup
        between polls) found."""
        if drained > 0:
            self._idle_polls = 0
        else:
            self._idle_polls += 1


class ProducerLatch:
    """Ownership handoff for a ring's producer side (round 16).

    The ring is SPSC: ONE producer may write tail. Caller-thread
    dispatch wants the submitting thread to push directly, but the
    driver loop thread still pushes on fallback paths and must reclaim
    the producer side for teardown. The latch serializes those roles:
    every push runs under `acquire(who)` / `release()`, and an owner
    change is an observable *handoff* (`ring.handoff` attribution +
    flight instant). The SPSC invariant is thus preserved by mutual
    exclusion — at any instant exactly one thread holds the producer
    side — while the handoff count keeps the tier honest about how
    often ownership actually migrates (a ping-ponging latch would eat
    the caller tier's win).

    Not a hot-path tax for flag-off deployments: the loop path only
    takes the latch when caller dispatch is enabled.
    """

    __slots__ = ("_lock", "_owner", "handoffs")

    def __init__(self):
        self._lock = threading.Lock()
        self._owner: Optional[str] = None
        self.handoffs = 0

    @property
    def owner(self) -> Optional[str]:
        return self._owner

    def acquire(self, who: str) -> None:
        self._lock.acquire()
        if self._owner != who:
            if self._owner is not None:
                self.handoffs += 1
                if attribution.enabled:
                    attribution.count("ring.handoff")
                if flight.enabled:
                    flight.instant("ring", "handoff",
                                   {"from": self._owner, "to": who})
            self._owner = who

    def release(self) -> None:
        self._lock.release()

    def __enter__(self):  # pragma: no cover - convenience only
        self.acquire("anon")
        return self

    def __exit__(self, *exc):  # pragma: no cover - convenience only
        self.release()


def busy_poll(end: "_Ring", budget_s: float) -> bool:
    """Spin on the ring cursors for up to `budget_s` waiting for it to
    turn non-empty (round 16 busy-poll handoff, ROADMAP 3c). Returns
    True the moment `tail != head`; False when the budget expires or
    the ring closed. Pure userspace loads — no syscalls — so the spin
    window hides exactly the epoll-wakeup latency it replaces. Callers
    gate it on traffic (only spin right after a non-empty drain) so an
    idle ring never burns a core."""
    if budget_s <= 0.0:
        return end.tail != end.head
    deadline = time.perf_counter() + budget_s
    spun = False
    while True:
        if end.closed:
            return False
        if end.tail != end.head:
            if spun:
                if attribution.enabled:
                    attribution.count("ring.busy_poll_hit")
            return True
        spun = True
        if time.perf_counter() >= deadline:
            return False


def ring_bytes(nslots: int, slot_bytes: int) -> int:
    return HEADER_BYTES + nslots * (_LEN.size + slot_bytes)


def create_ring(name_hint: str, nslots: int, slot_bytes: int
                ) -> Tuple[str, str]:
    """Create the shm segment + doorbell FIFO for one ring. Returns
    (segment_name, fifo_path). The creator owns both files' lifetime
    (`destroy_ring`)."""
    shm = shared_memory.SharedMemory(
        name=f"{name_hint}_{os.getpid()}_{os.urandom(4).hex()}",
        create=True, size=ring_bytes(nslots, slot_bytes))
    _HDR.pack_into(shm.buf, 0, 0, 0, nslots, slot_bytes)
    shm.buf[_CLOSED_OFF] = 0
    name = shm.name.lstrip("/")
    fifo = f"/tmp/{name}.fifo"
    os.mkfifo(fifo)
    # Keep only the name: both ends re-attach with the raw machinery
    # (object_store.attach_segment); this handle's resource-tracker
    # registration is dropped so a creator crash can't double-unlink.
    from ray_tpu.core.object_store import _untrack

    _untrack(shm)
    shm.close()
    return name, fifo


def destroy_ring(name: str, fifo: str) -> None:
    try:
        os.unlink(f"/dev/shm/{name}")
    except OSError:
        pass
    try:
        os.unlink(fifo)
    except OSError:
        pass


class _Ring:
    """Shared base: attach + cursor accessors."""

    def __init__(self, name: str, fifo: str):
        from ray_tpu.core.object_store import attach_segment

        self._seg = attach_segment(name)
        self.buf = self._seg.buf
        _h, _t, self.nslots, self.slot_bytes = _HDR.unpack_from(self.buf, 0)
        self.name = name
        self.fifo = fifo
        self._slot_stride = _LEN.size + self.slot_bytes

    # Cursors are u64 plain loads/stores on the mapped header.
    @property
    def head(self) -> int:
        return struct.unpack_from("<Q", self.buf, 0)[0]

    @head.setter
    def head(self, v: int) -> None:
        struct.pack_into("<Q", self.buf, 0, v)

    @property
    def tail(self) -> int:
        return struct.unpack_from("<Q", self.buf, 8)[0]

    @tail.setter
    def tail(self, v: int) -> None:
        struct.pack_into("<Q", self.buf, 8, v)

    @property
    def closed(self) -> bool:
        return bool(self.buf[_CLOSED_OFF])

    def mark_closed(self) -> None:
        try:
            self.buf[_CLOSED_OFF] = 1
        except (TypeError, ValueError):
            pass  # segment already torn down

    def _slot_off(self, cursor: int) -> int:
        return HEADER_BYTES + (cursor % self.nslots) * self._slot_stride

    def close(self) -> None:
        try:
            self._seg.close()
        except BufferError:
            pass  # a drained payload view still aliases the mapping


class RingWriter(_Ring):
    """Producer end. `push` is wait-free: a full ring returns False and
    the caller takes its fallback path (RPC push) instead of blocking."""

    def __init__(self, name: str, fifo: str):
        super().__init__(name, fifo)
        self._fifo_fd: Optional[int] = None
        # Honesty sentinel for the SPSC invariant: pushes overlapping in
        # time mean two producers raced past the ProducerLatch
        # discipline. Checked by the round-16 perf guard (must be 0).
        self._in_push = False
        self.producer_violations = 0

    def _doorbell(self) -> None:
        if self._fifo_fd is None:
            try:
                self._fifo_fd = os.open(self.fifo,
                                        os.O_WRONLY | os.O_NONBLOCK)
            except OSError:
                return  # no reader yet: its attach-time drain catches up
        try:
            os.write(self._fifo_fd, b"\x01")
        except (BlockingIOError, BrokenPipeError, OSError):
            pass  # FIFO full (reader behind but awake) or reader gone
        if attribution.enabled:
            attribution.count("ring.doorbell")
        if flight.enabled:
            flight.instant("ring", "doorbell")

    def push(self, payload: bytes) -> bool:
        """Publish one entry; False when the ring is full, closed, or
        the payload exceeds the slot capacity (caller falls back)."""
        n = len(payload)
        if n > self.slot_bytes or self.closed:
            return False
        if self._in_push:
            # Concurrent producer detected: the latch discipline was
            # violated. Count it (the perf guard asserts zero) but do
            # not crash the task plane over an observability check.
            self.producer_violations += 1
            if attribution.enabled:
                attribution.count("ring.producer_violation")
        self._in_push = True
        try:
            head, tail = self.head, self.tail
            if tail - head >= self.nslots:
                return False  # full: overflow is the caller's fallback
            off = self._slot_off(tail)
            _LEN.pack_into(self.buf, off, n)
            self.buf[off + _LEN.size:off + _LEN.size + n] = payload
            # Publish AFTER the payload lands: the consumer never reads
            # past tail, so a half-written slot is unreachable.
            self.tail = tail + 1
        finally:
            self._in_push = False
        if attribution.enabled:
            attribution.count("ring.enq")
        if flight.enabled:
            flight.instant("ring", "enq")
        if tail == head:
            self._doorbell()  # empty->non-empty edge only
        return True

    def close(self) -> None:
        self.mark_closed()
        if self._fifo_fd is not None:
            try:
                os.close(self._fifo_fd)
            except OSError:
                pass
            self._fifo_fd = None
        super().close()


class RingReader(_Ring):
    """Consumer end. Exposes the doorbell fd for event-loop
    registration; `drain()` empties the FIFO and the ring."""

    def __init__(self, name: str, fifo: str):
        super().__init__(name, fifo)
        # O_RDWR (not O_RDONLY): keeps a writer reference on the FIFO so
        # the producer's open never races EOF when re-opening, and a
        # nonblocking open succeeds with no producer present.
        self.doorbell_fd = os.open(fifo, os.O_RDWR | os.O_NONBLOCK)

    def clear_doorbell(self) -> None:
        try:
            while os.read(self.doorbell_fd, 4096):
                pass
        except (BlockingIOError, OSError):
            pass

    def pop(self) -> Optional[bytes]:
        """One entry (as immutable bytes — copied out so the slot can be
        reused immediately), or None when empty."""
        head = self.head
        if self.tail == head:
            return None
        off = self._slot_off(head)
        (n,) = _LEN.unpack_from(self.buf, off)
        payload = bytes(self.buf[off + _LEN.size:off + _LEN.size + n])
        self.head = head + 1  # release the slot after the copy
        if attribution.enabled:
            attribution.count("ring.deq")
        if flight.enabled:
            flight.instant("ring", "deq")
        return payload

    def drain(self) -> List[bytes]:
        self.clear_doorbell()
        out = []
        while True:
            item = self.pop()
            if item is None:
                return out
            out.append(item)

    def wait_nonempty(self, timeout: float) -> bool:
        """Blocking helper for threaded consumers (tests): True when an
        entry is available within `timeout`."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.tail != self.head:
                return True
            import select

            select.select([self.doorbell_fd], [], [],
                          min(BACKSTOP_POLL_S,
                              max(0.0, deadline - time.monotonic())))
        return self.tail != self.head

    def close(self) -> None:
        self.mark_closed()
        try:
            os.close(self.doorbell_fd)
        except OSError:
            pass
        super().close()
