"""ray_tpu.workflow — durable DAG execution with resume.

Reference equivalent: `python/ray/workflow/` (`workflow_executor.py` +
`workflow_storage.py`): run a lazy DAG where every step's result is
checkpointed to storage under a deterministic step id; re-running (or
`workflow.resume`) after a crash loads finished steps from storage and
executes only what's missing.

    import ray_tpu
    from ray_tpu import workflow

    @ray_tpu.remote
    def fetch(): ...
    @ray_tpu.remote
    def train(data): ...

    dag = train.bind(fetch.bind())
    workflow.run(dag, workflow_id="exp1")     # executes both steps
    workflow.resume("exp1")                   # replays from storage
"""

from __future__ import annotations

import hashlib
import os
import pickle
import time

import cloudpickle
from typing import Any, Dict, List, Optional

from ray_tpu.dag import DAGNode, FunctionNode, InputNode

_STORAGE_ENV = "RAY_TPU_WORKFLOW_STORAGE"
_DEFAULT_STORAGE = "/tmp/ray_tpu_workflows"

__all__ = ["run", "run_async", "resume", "get_status", "list_all",
           "delete"]


def _storage_root() -> str:
    return os.environ.get(_STORAGE_ENV, _DEFAULT_STORAGE)


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage_root(), workflow_id)


# ---------------------------------------------------------------------------
# step identity: deterministic from DAG topology
# ---------------------------------------------------------------------------
def _step_id(node: DAGNode, child_ids: List[str]) -> str:
    if isinstance(node, FunctionNode):
        name = node._remote_function._function_name
    else:
        name = type(node).__name__
    static_args = [repr(a) for a in node._bound_args
                   if not isinstance(a, DAGNode)]
    static_kwargs = [f"{k}={v!r}"
                     for k, v in sorted(node._bound_kwargs.items())
                     if not isinstance(v, DAGNode)]
    payload = "|".join([name, *static_args, *static_kwargs, *child_ids])
    digest = hashlib.sha1(payload.encode()).hexdigest()[:10]
    return f"{name}-{digest}"


class _DurableExecutor:
    def __init__(self, workflow_id: str, input_value: Any):
        self.workflow_id = workflow_id
        self.input_value = input_value
        self.dir = _wf_dir(workflow_id)
        os.makedirs(self.dir, exist_ok=True)
        self.executed: Dict[int, Any] = {}
        self.loaded_steps: List[str] = []
        self.ran_steps: List[str] = []

    def _ckpt_path(self, step_id: str) -> str:
        return os.path.join(self.dir, f"{step_id}.pkl")

    def execute(self, node: DAGNode):
        """Bottom-up: returns (step_id, concrete value)."""
        if id(node) in self.executed:
            return self.executed[id(node)]
        if isinstance(node, InputNode):
            out = ("input", self.input_value)
            self.executed[id(node)] = out
            return out

        resolved_args = []
        child_ids = []
        for arg in node._bound_args:
            if isinstance(arg, DAGNode):
                cid, val = self.execute(arg)
                child_ids.append(cid)
                resolved_args.append(val)
            else:
                resolved_args.append(arg)
        resolved_kwargs = {}
        for k, v in node._bound_kwargs.items():
            if isinstance(v, DAGNode):
                cid, val = self.execute(v)
                child_ids.append(cid)
                resolved_kwargs[k] = val
            else:
                resolved_kwargs[k] = v

        step_id = _step_id(node, child_ids)
        path = self._ckpt_path(step_id)
        if os.path.exists(path):
            with open(path, "rb") as f:
                value = pickle.load(f)
            self.loaded_steps.append(step_id)
        else:
            value = self._run_step(node, resolved_args, resolved_kwargs)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                pickle.dump(value, f)
            os.replace(tmp, path)
            self.ran_steps.append(step_id)
        out = (step_id, value)
        self.executed[id(node)] = out
        return out

    def _run_step(self, node: DAGNode, args, kwargs):
        import ray_tpu

        if isinstance(node, FunctionNode):
            ref = node._remote_function._remote(tuple(args), kwargs,
                                                node._options)
            return ray_tpu.get(ref)
        raise TypeError(
            f"workflow steps must be task nodes (f.bind(...)); got "
            f"{type(node).__name__} — actor nodes are not durable")

    def _write_meta(self, status: str, error: Optional[str] = None
                    ) -> None:
        meta = {"workflow_id": self.workflow_id, "status": status,
                "updated_at": time.time(), "error": error,
                "steps_loaded": self.loaded_steps,
                "steps_ran": self.ran_steps}
        tmp = os.path.join(self.dir, "meta.pkl.tmp")
        with open(tmp, "wb") as f:
            pickle.dump(meta, f)
        os.replace(tmp, os.path.join(self.dir, "meta.pkl"))


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        input_value: Any = None) -> Any:
    """Execute the DAG durably; returns the root value."""
    workflow_id = workflow_id or f"wf-{int(time.time() * 1000):x}"
    _store_spec(workflow_id, dag, input_value)
    ex = _DurableExecutor(workflow_id, input_value)
    ex._write_meta("RUNNING")
    try:
        _, value = ex.execute(dag)
    except BaseException as e:  # noqa: BLE001
        ex._write_meta("FAILED", error=repr(e))
        raise
    ex._write_meta("SUCCEEDED")
    return value


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              input_value: Any = None):
    """Run in a task; returns an ObjectRef of the root value."""
    import ray_tpu

    payload = cloudpickle.dumps((dag, workflow_id, input_value))

    def _driver(blob):
        d, wid, inp = pickle.loads(blob)
        return run(d, workflow_id=wid, input_value=inp)

    return ray_tpu.remote(_driver).remote(payload)


def resume(workflow_id: str, dag: Optional[DAGNode] = None,
           input_value: Any = None) -> Any:
    """Re-drive a workflow: checkpointed steps replay from storage.
    The reference persists the serialized DAG; here the spec is stored
    on first run so resume works without re-supplying it."""
    spec_path = os.path.join(_wf_dir(workflow_id), "dag.pkl")
    if dag is None:
        if not os.path.exists(spec_path):
            raise KeyError(
                f"workflow {workflow_id!r} has no stored DAG; pass dag=")
        with open(spec_path, "rb") as f:
            dag, input_value = pickle.load(f)
    return run(dag, workflow_id=workflow_id, input_value=input_value)


def _store_spec(workflow_id: str, dag: DAGNode, input_value: Any) -> None:
    os.makedirs(_wf_dir(workflow_id), exist_ok=True)
    with open(os.path.join(_wf_dir(workflow_id), "dag.pkl"), "wb") as f:
        cloudpickle.dump((dag, input_value), f)


def get_status(workflow_id: str) -> Dict[str, Any]:
    path = os.path.join(_wf_dir(workflow_id), "meta.pkl")
    if not os.path.exists(path):
        raise KeyError(f"unknown workflow {workflow_id!r}")
    with open(path, "rb") as f:
        return pickle.load(f)


def list_all() -> List[Dict[str, Any]]:
    root = _storage_root()
    if not os.path.isdir(root):
        return []
    out = []
    for wid in sorted(os.listdir(root)):
        try:
            out.append(get_status(wid))
        except KeyError:
            continue
    return out


def delete(workflow_id: str) -> None:
    import shutil

    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)
