"""Node providers: the boundary between the autoscaler and machines.

Reference equivalent: `python/ray/autoscaler/node_provider.py` (the v1
NodeProvider interface) + `_private/fake_multi_node/node_provider.py`
(the in-process provider used by autoscaler tests). A provider knows how
to create/terminate nodes of a given type and report what exists; the
autoscaler never touches machines directly.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class NodeType:
    """A launchable shape (reference: available_node_types entries)."""

    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10


class NodeProvider:
    """Interface. Implementations: LocalNodeProvider (raylet processes on
    this host); cloud/TPU-pod providers plug in the same way the
    reference's AWS/GCP/KubeRay providers do."""

    def create_node(self, node_type: NodeType) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


@dataclass
class _LocalNode:
    node_id: str
    proc: subprocess.Popen
    node_type: str


class LocalNodeProvider(NodeProvider):
    """Spawns extra raylets against an existing GCS — one process per
    'node' (reference: fake multinode docker-less mode)."""

    def __init__(self, gcs_address: str,
                 env: Optional[Dict[str, str]] = None):
        self.gcs_address = gcs_address
        self._env = env or {}
        self._nodes: Dict[str, _LocalNode] = {}

    def create_node(self, node_type: NodeType) -> str:
        from ray_tpu.core.ids import NodeID
        from ray_tpu.core.node import _wait_for_line

        node_id = NodeID.from_random().hex()
        cmd = [sys.executable, "-m", "ray_tpu.core.raylet",
               "--gcs", self.gcs_address, "--node-id", node_id,
               "--resources", json.dumps(node_type.resources)]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(self._env)
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL, env=env)
        _wait_for_line(proc, r"RAYLET_ADDRESS=(\S+)")
        self._nodes[node_id] = _LocalNode(node_id, proc, node_type.name)
        return node_id

    def terminate_node(self, node_id: str) -> None:
        node = self._nodes.pop(node_id, None)
        if node is None:
            return
        node.proc.terminate()
        try:
            node.proc.wait(timeout=5)
        except Exception:
            node.proc.kill()

    def non_terminated_nodes(self) -> List[str]:
        return [nid for nid, n in self._nodes.items()
                if n.proc.poll() is None]
