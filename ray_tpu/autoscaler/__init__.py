"""ray_tpu.autoscaler — demand-driven cluster scaling.

Reference equivalent: `python/ray/autoscaler/` (v2: `autoscaler/v2/`
instance manager + scheduler). The monitor polls the GCS for aggregate
pending demand + node load, asks a NodeProvider for more capacity when
demand is unmet for `upscale_delay_s`, and releases idle nodes after
`idle_timeout_s`. Providers are pluggable; `LocalNodeProvider` spawns
raylet processes on this host (the test/demo provider, like the
reference's fake multinode provider).
"""

from ray_tpu.autoscaler.autoscaler import (Autoscaler, AutoscalerConfig,
                                           StandardAutoscaler)
from ray_tpu.autoscaler.node_provider import (LocalNodeProvider,
                                              NodeProvider)

__all__ = [
    "Autoscaler", "StandardAutoscaler", "AutoscalerConfig",
    "NodeProvider", "LocalNodeProvider",
]
