"""StandardAutoscaler: reconcile cluster size to resource demand.

Reference equivalent: `python/ray/autoscaler/_private/autoscaler.py`
(`StandardAutoscaler.update`, bin-packing in `resource_demand_scheduler.py`)
and the v2 instance-manager loop. Each tick:

1. read node table + per-raylet load (pending lease demands) from GCS,
2. bin-pack unmet demands onto launchable node types,
3. launch what's missing (after `upscale_delay_s` of sustained demand),
4. terminate provider nodes idle longer than `idle_timeout_s`,
honoring each type's min/max and the cluster-wide max.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider, NodeType
from ray_tpu.core.config import ray_config

logger = logging.getLogger(__name__)


@dataclass
class AutoscalerConfig:
    node_types: List[NodeType] = field(default_factory=list)
    max_workers: int = 8
    upscale_delay_s: float = 1.0
    idle_timeout_s: float = 30.0
    tick_interval_s: float = 1.0


class StandardAutoscaler:
    def __init__(self, gcs_address: str, provider: NodeProvider,
                 config: AutoscalerConfig):
        from ray_tpu.core.gcs.client import GcsClient
        from ray_tpu.core.rpc import EventLoopThread

        self.provider = provider
        self.config = config
        self._loop = EventLoopThread(name="autoscaler")
        self._gcs = GcsClient(gcs_address)
        self._loop.run(self._gcs.connect())
        self._demand_since: Optional[float] = None
        self._idle_since: Dict[str, float] = {}
        self._unresolved_since: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.launched: Dict[str, str] = {}   # node_id -> type name

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _run(self) -> None:
        # Satisfy min_workers immediately.
        for nt in self.config.node_types:
            for _ in range(nt.min_workers):
                self._launch(nt)
        while not self._stop.wait(self.config.tick_interval_s):
            try:
                self.update()
            except Exception:
                logger.warning("autoscaler tick failed", exc_info=True)

    # -- one reconcile tick ---------------------------------------------
    def update(self) -> None:
        nodes = self._loop.run(self._gcs.get_nodes(), timeout=10)
        alive = [n for n in nodes if n.get("alive")]
        demands = self._unmet_demands(alive)
        if demands:
            self._idle_since.clear()
            if self._demand_since is None:
                self._demand_since = time.monotonic()
            elif (time.monotonic() - self._demand_since
                  >= self.config.upscale_delay_s):
                self._scale_up(demands)
        else:
            self._demand_since = None
            self._reap_idle(alive)

    def _unmet_demands(self, alive: List[dict]) -> List[Dict[str, float]]:
        """Pending lease demands no alive node can satisfy right now
        (reference: load metrics' pending resource shapes)."""
        demands: List[Dict[str, float]] = []
        for n in alive:
            load = n.get("load") or {}
            shapes = load.get("pending_demands")
            if shapes is None and load.get("pending"):
                shapes = [{"CPU": 1.0}] * int(load["pending"])
            demands.extend(shapes or [])
        if not demands:
            return []
        free = [dict(n.get("resources_available", {})) for n in alive]
        unmet = []
        for demand in demands:
            placed = False
            for avail in free:
                if all(avail.get(k, 0.0) + 1e-9 >= v
                       for k, v in demand.items()):
                    for k, v in demand.items():
                        avail[k] = avail.get(k, 0.0) - v
                    placed = True
                    break
            if not placed:
                unmet.append(demand)
        return unmet

    def _scale_up(self, unmet: List[Dict[str, float]]) -> None:
        current = len(self.provider.non_terminated_nodes())
        # Bin-pack unmet demands onto new nodes, cheapest-first
        # (reference: get_nodes_for in resource_demand_scheduler.py).
        to_launch: List[NodeType] = []
        remaining = [dict(d) for d in unmet]
        while remaining and current + len(to_launch) \
                < self.config.max_workers:
            nt = self._pick_type(remaining[0])
            if nt is None:
                logger.warning("no node type fits demand %s",
                               remaining[0])
                remaining.pop(0)
                continue
            cap = dict(nt.resources)
            fitted = []
            for demand in remaining:
                if all(cap.get(k, 0.0) + 1e-9 >= v
                       for k, v in demand.items()):
                    for k, v in demand.items():
                        cap[k] = cap.get(k, 0.0) - v
                    fitted.append(demand)
            for demand in fitted:
                remaining.remove(demand)
            to_launch.append(nt)
        for nt in to_launch:
            self._launch(nt)

    def _pick_type(self, demand: Dict[str, float]) -> Optional[NodeType]:
        for nt in self.config.node_types:
            count = sum(1 for t in self.launched.values()
                        if t == nt.name)
            if count >= nt.max_workers:
                continue
            if all(nt.resources.get(k, 0.0) >= v
                   for k, v in demand.items()):
                return nt
        return None

    def _launch(self, nt: NodeType) -> None:
        node_id = self.provider.create_node(nt)
        self.launched[node_id] = nt.name
        logger.info("autoscaler launched %s node %s", nt.name,
                    node_id[:8])

    @staticmethod
    def _node_busy(info: Optional[dict]) -> bool:
        if info is None:
            return False
        total = info.get("resources_total", {}) or info.get(
            "Resources", {})
        avail = info.get("resources_available", {})
        busy = any(avail.get(k, 0.0) + 1e-9 < v
                   for k, v in total.items()
                   if k in ("CPU", "TPU"))
        return busy or bool((info.get("load") or {}).get("pending"))

    def _reap_idle(self, alive: List[dict]) -> None:
        now = time.monotonic()
        by_id = {n["node_id"]: n for n in alive}
        # A provider node may be a gang of raylets (a TPU slice):
        # hosts_of maps it to its GCS node ids, and the gang is busy if
        # ANY host is busy — slices terminate atomically or not at all.
        hosts_of = getattr(self.provider, "hosts_of",
                           lambda node_id: [node_id])
        for node_id in self.provider.non_terminated_nodes():
            nt_name = self.launched.get(node_id)
            nt = next((t for t in self.config.node_types
                       if t.name == nt_name), None)
            floor = nt.min_workers if nt else 0
            same_type = sum(
                1 for nid in self.provider.non_terminated_nodes()
                if self.launched.get(nid) == nt_name)
            if same_type <= floor:
                self._idle_since.pop(node_id, None)
                continue
            host_ids = hosts_of(node_id) or [node_id]
            # An unresolvable host mapping (provider can't map the slice
            # to GCS node ids, or a host hasn't registered yet) reads as
            # BUSY within a grace window — reaping on missing info would
            # terminate a live slice whose raylets aren't visible to us
            # yet. But a node whose hosts STAY unresolvable (crashed VM
            # that dropped out of the GCS) must still be reclaimed, or it
            # leaks and pins its max_workers slot forever.
            infos = [by_id.get(h) for h in host_ids]
            if any(i is None for i in infos):
                first = self._unresolved_since.setdefault(
                    node_id, now)
                grace = (ray_config().worker_startup_timeout_s
                         + self.config.idle_timeout_s)
                if now - first < grace:
                    self._idle_since.pop(node_id, None)
                    continue
                # Beyond grace: fall through as idle (reap path below).
            else:
                self._unresolved_since.pop(node_id, None)
                if any(self._node_busy(i) for i in infos):
                    self._idle_since.pop(node_id, None)
                    continue
            first = self._idle_since.setdefault(node_id, now)
            if now - first >= self.config.idle_timeout_s:
                logger.info("autoscaler terminating idle node %s",
                            node_id[:8])
                self.provider.terminate_node(node_id)
                self.launched.pop(node_id, None)
                self._idle_since.pop(node_id, None)
                self._unresolved_since.pop(node_id, None)

    def shutdown(self) -> None:
        self.stop()
        for node_id in list(self.provider.non_terminated_nodes()):
            self.provider.terminate_node(node_id)


Autoscaler = StandardAutoscaler
