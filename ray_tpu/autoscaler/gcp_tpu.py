"""GCP TPU-pod node provider: slices as atomic autoscaling units.

Reference equivalent: `python/ray/autoscaler/_private/gcp/node_provider.py`
(+ TPU handling in `gcp/config.py`). The cloud surface here is a narrow
protocol modeled on the TPU-VM *queued resources* API
(create/get/delete/list); production implements `GcpTpuApi` with real HTTP
calls, tests use `FakeGcpTpuApi`, which either just records state or spawns
one local raylet per slice host — the fake-multinode strategy of
`autoscaler/_private/fake_multi_node/node_provider.py`.

The key departure from generic cloud providers: **a TPU slice is atomic**.
`create_node` provisions every host of the slice in one call, and
`terminate_node` returns them all — a v5e pod cannot grow or shrink by
single hosts. The autoscaler bin-packs demand against the slice's
*aggregate* resources, so eight `{"TPU": 4}` gang members launch exactly
one v5litepod-32 (8 hosts x 4 chips), never eight separate machines.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ray_tpu.autoscaler.node_provider import NodeProvider, NodeType

logger = logging.getLogger(__name__)

# chips per host by TPU generation (reference: accelerators/tpu.py
# chips-per-host bounds; v5e/v5p/v4 pods pack 4 chips per host VM,
# v2/v3 pack 8 tensorcores = 4 chips).
_CHIPS_PER_HOST = {
    "v2": 4, "v3": 4, "v4": 4, "v5litepod": 4, "v5e": 4, "v5p": 4,
    "v6e": 4,
}


def slice_shape(accelerator_type: str) -> Tuple[int, int]:
    """(num_hosts, chips_per_host) for an accelerator type string.

    "v5litepod-32" -> (8, 4); "v5litepod-4" -> (1, 4);
    "v4-16" -> (2, 4) (v4 counts tensorcores: 16 cores = 8 chips).
    """
    gen, _, count_s = accelerator_type.rpartition("-")
    count = int(count_s)
    per_host = _CHIPS_PER_HOST.get(gen, 4)
    # v2-v4 names count tensorcores (2 per chip); v5e+ count chips.
    chips = count // 2 if gen in ("v2", "v3", "v4") else count
    hosts = max(1, chips // per_host)
    return hosts, min(chips, per_host)


@dataclass
class TpuSliceNodeType(NodeType):
    """A launchable slice shape. `resources` is the slice AGGREGATE
    (whole-gang bin-packing); per-host resources derive from the shape."""

    accelerator_type: str = "v5litepod-4"
    runtime_version: str = "v2-alpha-tpuv5-lite"
    cpus_per_host: float = 4.0

    def __post_init__(self):
        hosts, per_host = slice_shape(self.accelerator_type)
        self.num_hosts = hosts
        self.chips_per_host = per_host
        if not self.resources:
            self.resources = {
                "TPU": float(hosts * per_host),
                f"TPU-{self.accelerator_type}": float(hosts * per_host),
                "CPU": self.cpus_per_host * hosts,
            }

    def host_resources(self) -> Dict[str, float]:
        return {
            "TPU": float(self.chips_per_host),
            f"TPU-{self.accelerator_type}": float(self.chips_per_host),
            "CPU": self.cpus_per_host,
        }


class GcpTpuApi:
    """Queued-resources-shaped API surface (the subset the provider
    needs). Real implementation: POST/GET/DELETE against
    tpu.googleapis.com/v2/.../queuedResources."""

    def create_slice(self, name: str, node_type: TpuSliceNodeType) -> dict:
        raise NotImplementedError

    def get_slice(self, name: str) -> Optional[dict]:
        raise NotImplementedError

    def delete_slice(self, name: str) -> None:
        raise NotImplementedError

    def list_slices(self) -> List[dict]:
        raise NotImplementedError


@dataclass
class _FakeSlice:
    name: str
    node_type: TpuSliceNodeType
    state: str = "ACTIVE"
    created_at: float = field(default_factory=time.monotonic)
    procs: List[subprocess.Popen] = field(default_factory=list)
    host_node_ids: List[str] = field(default_factory=list)


class FakeGcpTpuApi(GcpTpuApi):
    """In-memory stub. With `gcs_address` set it also materializes each
    slice host as a local raylet process carrying the host's TPU
    resources and slice labels (RAY_TPU_FAKE_SLICE / TPU_WORKER_ID), so
    autoscaler end-to-end tests exercise real gang scheduling without a
    cloud."""

    def __init__(self, gcs_address: Optional[str] = None):
        self.gcs_address = gcs_address
        self.slices: Dict[str, _FakeSlice] = {}
        self.create_calls = 0
        self._all_procs: List[subprocess.Popen] = []  # lifetime registry

    def create_slice(self, name: str, node_type: TpuSliceNodeType) -> dict:
        if name in self.slices:
            raise ValueError(f"slice {name} already exists")
        self.create_calls += 1
        sl = _FakeSlice(name, node_type, state="PROVISIONING")
        # Register BEFORE the (slow) host bring-up: a real queued-resource
        # exists from the create call onward, and callers must see it —
        # otherwise a second reconcile tick would double-provision.
        self.slices[name] = sl
        if self.gcs_address:
            self._spawn_hosts(sl)
        sl.state = "ACTIVE"
        return {"name": name, "state": sl.state,
                "hosts": sl.host_node_ids or node_type.num_hosts}

    def _spawn_hosts(self, sl: _FakeSlice) -> None:
        from ray_tpu.core.ids import NodeID
        from ray_tpu.core.node import _wait_for_line

        nt = sl.node_type
        for worker_id in range(nt.num_hosts):
            node_id = NodeID.from_random().hex()
            env = dict(os.environ)
            env.update({
                "JAX_PLATFORMS": "cpu",
                "RAY_TPU_FAKE_SLICE":
                    f"{nt.accelerator_type}:{nt.num_hosts}",
                "TPU_WORKER_ID": str(worker_id),
                "TPU_NAME": sl.name,
            })
            cmd = [sys.executable, "-m", "ray_tpu.core.raylet",
                   "--gcs", self.gcs_address, "--node-id", node_id,
                   "--resources", json.dumps(nt.host_resources())]
            proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                    stderr=subprocess.DEVNULL, env=env)
            _wait_for_line(proc, r"RAYLET_ADDRESS=(\S+)")
            sl.procs.append(proc)
            self._all_procs.append(proc)
            sl.host_node_ids.append(node_id)

    def get_slice(self, name: str) -> Optional[dict]:
        sl = self.slices.get(name)
        if sl is None:
            return None
        return {"name": name, "state": sl.state,
                "hosts": sl.host_node_ids or sl.node_type.num_hosts}

    def delete_slice(self, name: str) -> None:
        sl = self.slices.pop(name, None)
        if sl is None:
            return
        for proc in sl.procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in sl.procs:
            try:
                proc.wait(timeout=5)
            except Exception:
                proc.kill()

    def list_slices(self) -> List[dict]:
        return [self.get_slice(n) for n in list(self.slices)]

    def shutdown(self) -> None:
        for name in list(self.slices):
            self.delete_slice(name)
        # Belt-and-braces: anything ever spawned dies with the fake —
        # a slice deleted mid-provisioning can otherwise strand hosts.
        for proc in self._all_procs:
            if proc.poll() is None:
                proc.kill()
        for proc in self._all_procs:
            try:
                proc.wait(timeout=5)
            except Exception:
                pass
        self._all_procs.clear()


class GcpTpuPodProvider(NodeProvider):
    """NodeProvider whose unit is one whole TPU slice."""

    def __init__(self, api: GcpTpuApi, name_prefix: str = "ray-tpu"):
        self.api = api
        self._prefix = name_prefix
        self._counter = 0

    def create_node(self, node_type: NodeType) -> str:
        if not isinstance(node_type, TpuSliceNodeType):
            raise TypeError(
                "GcpTpuPodProvider launches TpuSliceNodeType slices; got "
                f"{type(node_type).__name__}")
        self._counter += 1
        name = f"{self._prefix}-{node_type.accelerator_type}-{self._counter}"
        self.api.create_slice(name, node_type)
        logger.info("provisioned TPU slice %s (%d hosts)", name,
                    node_type.num_hosts)
        return name

    def terminate_node(self, node_id: str) -> None:
        self.api.delete_slice(node_id)

    def non_terminated_nodes(self) -> List[str]:
        return [s["name"] for s in self.api.list_slices()
                if s and s.get("state") in ("ACTIVE", "PROVISIONING")]

    def hosts_of(self, node_id: str) -> List[str]:
        """GCS node ids of this slice's hosts (one raylet per host). The
        autoscaler uses this to judge slice idleness across ALL hosts —
        a slice with one busy host is busy."""
        info = self.api.get_slice(node_id)
        if info is None:
            return []
        hosts = info.get("hosts")
        return hosts if isinstance(hosts, list) else []
