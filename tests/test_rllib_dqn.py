"""DQN (framework=jax): replay buffer + Q-target math + learning.

Reference coverage class: `rllib/algorithms/dqn/tests/test_dqn.py`.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.cluster


@pytest.fixture(scope="module")
def ray_cluster():
    import ray_tpu

    ray_tpu.init(num_cpus=6, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


def test_replay_buffer_fifo_and_sampling():
    from ray_tpu.rllib.algorithms.dqn import ReplayBuffer

    buf = ReplayBuffer(capacity=100, seed=0)
    T, n_envs = 5, 2
    rollout = {
        "obs": np.arange(T * n_envs * 3, dtype=np.float32).reshape(
            T, n_envs, 3),
        "actions": np.ones((T, n_envs), np.int32),
        "rewards": np.full((T, n_envs), 2.0, np.float32),
        "dones": np.zeros((T, n_envs), np.float32),
        "final_obs": np.zeros((n_envs, 3), np.float32),
    }
    assert buf.add_fragment(rollout) == 10
    assert len(buf) == 10
    batch = buf.sample(32)
    assert batch["obs"].shape == (32, 3)
    assert (batch["rewards"] == 2.0).all()
    # next_obs of step t is obs of step t+1 for the same env.
    # (spot-check: any sampled non-final transition obeys the shift)
    # FIFO capacity: overfill evicts oldest.
    small = ReplayBuffer(capacity=8, seed=0)
    small.add_fragment(rollout)
    assert len(small) == 8


def test_dqn_loss_bellman_target():
    """With known Q nets the Huber-TD loss matches a hand computation."""
    import jax

    from ray_tpu.rllib.algorithms.dqn import dqn_loss
    from ray_tpu.rllib.core.rl_module import DiscreteMLPModule

    module = DiscreteMLPModule(obs_dim=4, num_actions=2, hiddens=(8,))
    params = module.init(jax.random.PRNGKey(0))
    target = module.init(jax.random.PRNGKey(1))
    rng = np.random.default_rng(0)
    batch = {
        "obs": rng.normal(size=(16, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, size=16).astype(np.int32),
        "rewards": rng.normal(size=16).astype(np.float32),
        "next_obs": rng.normal(size=(16, 4)).astype(np.float32),
        "dones": (rng.random(16) > 0.8).astype(np.float32),
    }
    loss, stats = dqn_loss(module, params, target, batch, gamma=0.9,
                           double_q=False)
    q, _ = module.apply(params, batch["obs"])
    qn, _ = module.apply(target, batch["next_obs"])
    q_sel = np.take_along_axis(np.asarray(q),
                               batch["actions"][:, None], 1)[:, 0]
    tgt = batch["rewards"] + 0.9 * (1 - batch["dones"]) * \
        np.asarray(qn).max(1)
    td = q_sel - tgt
    expected = np.mean(np.where(np.abs(td) < 1, 0.5 * td ** 2,
                                np.abs(td) - 0.5))
    assert float(loss) == pytest.approx(float(expected), rel=1e-4)


def test_dqn_iteration_end_to_end(ray_cluster):
    from ray_tpu.rllib.algorithms.dqn import DQNConfig

    algo = DQNConfig(num_env_runners=2, num_envs_per_runner=2,
                     rollout_fragment_length=8, learning_starts=32,
                     updates_per_iteration=4, train_batch_size=16,
                     platform="cpu").build()
    try:
        m1 = algo.train()
        assert m1["training_iteration"] == 1
        assert m1["buffer_size"] == 2 * 2 * 8
        m2 = algo.train()
        assert m2["num_updates"] == 4  # past learning_starts now
        assert np.isfinite(m2["learner/total_loss"])
        assert 0.0 <= m2["epsilon"] <= 1.0
    finally:
        algo.stop()


@pytest.mark.slow
def test_dqn_cartpole_learns(ray_cluster):
    from ray_tpu.rllib.algorithms.dqn import DQNConfig

    algo = DQNConfig(num_env_runners=2, num_envs_per_runner=8,
                     rollout_fragment_length=16, lr=1e-3,
                     learning_starts=500, train_batch_size=64,
                     updates_per_iteration=40,
                     target_network_update_freq=100,
                     epsilon_decay_steps=4000,
                     platform="cpu").build()
    try:
        best = 0.0
        for _ in range(80):
            m = algo.train()
            best = max(best, m["episode_return_mean"])
            if best >= 150:
                break
        assert best >= 150, f"DQN failed to learn: best={best}"
    finally:
        algo.stop()
