"""Ray Client: a remote driver over one proxy endpoint.

Reference coverage class: `python/ray/util/client/tests/` — every API
call (tasks, actors, objects, introspection) forwards over a single
connection; disconnect releases the client's refs and actors.
"""

import subprocess
import sys
import time

import numpy as np
import pytest

pytestmark = pytest.mark.cluster


@pytest.fixture(scope="module")
def client_cluster():
    """A real cluster + a client proxy subprocess, then a CLIENT-mode
    driver in this process (ray://)."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.core.node import _wait_for_line

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 4})
    proxy = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.util.client.server",
         "--address", cluster.address, "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
    proxy_addr = _wait_for_line(proxy, r"CLIENT_PROXY_READY (\S+)")
    ray_tpu.init(address=f"ray://{proxy_addr}", ignore_reinit_error=True)
    yield ray_tpu, proxy_addr
    ray_tpu.shutdown()
    proxy.terminate()
    proxy.wait(timeout=10)
    cluster.shutdown()


def test_client_tasks_and_objects(client_cluster):
    ray, _ = client_cluster

    @ray.remote
    def add(a, b):
        return a + b

    assert ray.get(add.remote(2, 3), timeout=120) == 5

    # Large object round trip through put/get.
    arr = np.arange(100_000, dtype=np.float64)
    ref = ray.put(arr)
    np.testing.assert_array_equal(ray.get(ref, timeout=120), arr)

    # Refs as task args (server-side resolution, no client round trip).
    assert ray.get(add.remote(ref, ref), timeout=120)[0] == 0.0

    # Multiple returns.
    @ray.remote(num_returns=2)
    def two():
        return 1, 2

    r1, r2 = two.remote()
    assert ray.get([r1, r2], timeout=120) == [1, 2]

    # wait() semantics.
    refs = [add.remote(i, i) for i in range(4)]
    ready, pending = ray.wait(refs, num_returns=4, timeout=120)
    assert len(ready) == 4 and not pending


def test_client_actors(client_cluster):
    ray, _ = client_cluster

    @ray.remote
    class Counter:
        def __init__(self, start):
            self.n = start

        def bump(self, by=1):
            self.n += by
            return self.n

    c = Counter.remote(10)
    assert ray.get(c.bump.remote(), timeout=120) == 11
    assert ray.get(c.bump.remote(5), timeout=120) == 16

    # Named actor via the client.
    named = Counter.options(name="client_counter").remote(0)
    assert ray.get(named.bump.remote(), timeout=120) == 1
    again = ray.get_actor("client_counter")
    assert ray.get(again.bump.remote(), timeout=120) == 2

    ray.kill(c)
    with pytest.raises(Exception):
        ray.get(c.bump.remote(), timeout=60)


def test_client_errors_propagate(client_cluster):
    ray, _ = client_cluster

    @ray.remote
    def boom():
        raise ValueError("kapow")

    with pytest.raises(Exception) as ei:
        ray.get(boom.remote(), timeout=120)
    assert "kapow" in str(ei.value)


def test_client_cluster_introspection(client_cluster):
    ray, _ = client_cluster

    assert ray.cluster_resources().get("CPU", 0) >= 4
    nodes = ray.nodes()
    assert nodes and any(n.get("Alive") for n in nodes)


def test_client_disconnect_releases_actors(client_cluster):
    """A second client's named actor dies with its connection (the proxy
    reaps per-connection ownership)."""
    ray, proxy_addr = client_cluster
    from ray_tpu.util.client.runtime import ClientRuntime

    other = ClientRuntime(proxy_addr)

    import ray_tpu.core.actor  # noqa: F401  (ActorHandle machinery)

    @ray.remote
    class Ephemeral:
        def ping(self):
            return "pong"

    # Create through the SECOND client connection.
    from ray_tpu.core.options import ActorOptions

    handle = other.create_actor(Ephemeral, ActorOptions(name="ephem"), (),
                                {})
    ref = other.submit_actor_task(handle, "ping", _task_opts(), (), {})
    assert other.get(ref, timeout=120) == "pong"
    other.shutdown()  # drops the connection

    # The proxy kills the ephemeral actor on disconnect.
    deadline = time.time() + 60
    gone = False
    while time.time() < deadline:
        try:
            h = ray.get_actor("ephem")
            ray.get(h.ping.remote(), timeout=5)
        except Exception:
            gone = True
            break
        time.sleep(1.0)
    assert gone, "disconnected client's actor is still alive"


def _task_opts():
    from ray_tpu.core.options import TaskOptions

    return TaskOptions()
