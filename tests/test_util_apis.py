"""ActorPool, Queue, batched wait, GCS persistence.

Reference coverage class: `python/ray/tests/test_actor_pool.py`,
`test_queue.py`, `test_wait.py`, and the GCS FT tests
(`test_gcs_fault_tolerance.py` — here: snapshot/recover).
"""

import subprocess
import sys
import time

import pytest

pytestmark = pytest.mark.cluster


@pytest.fixture(scope="module")
def ray_cluster():
    import ray_tpu

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


class _Sq:
    def compute(self, x):
        time.sleep(0.01 * (x % 3))
        return x * x


def test_actor_pool_map_ordered(ray_cluster):
    from ray_tpu.util.actor_pool import ActorPool

    ray_tpu = ray_cluster
    actors = [ray_tpu.remote(num_cpus=0)(_Sq).remote() for _ in range(2)]
    pool = ActorPool(actors)
    out = list(pool.map(lambda a, v: a.compute.remote(v), range(8)))
    assert out == [v * v for v in range(8)]
    for a in actors:
        ray_tpu.kill(a)


def test_actor_pool_unordered_and_requeue(ray_cluster):
    from ray_tpu.util.actor_pool import ActorPool

    ray_tpu = ray_cluster
    actors = [ray_tpu.remote(num_cpus=0)(_Sq).remote() for _ in range(2)]
    pool = ActorPool(actors)
    out = sorted(pool.map_unordered(
        lambda a, v: a.compute.remote(v), range(8)))
    assert out == sorted(v * v for v in range(8))
    # More submits than actors exercises the pending-queue path.
    for v in range(5):
        pool.submit(lambda a, v: a.compute.remote(v), v)
    got = sorted(pool.get_next() for _ in range(5))
    assert got == [0, 1, 4, 9, 16]
    for a in actors:
        ray_tpu.kill(a)


def test_queue_fifo_and_timeout(ray_cluster):
    from ray_tpu.util.queue import Empty, Queue

    q = Queue(maxsize=4)
    for i in range(3):
        q.put(i)
    assert q.qsize() == 3
    assert [q.get() for _ in range(3)] == [0, 1, 2]
    assert q.empty()
    with pytest.raises(Empty):
        q.get(block=False)
    t0 = time.monotonic()
    with pytest.raises(Empty):
        q.get(timeout=0.3)
    assert time.monotonic() - t0 >= 0.25
    q.shutdown()


def test_queue_maxsize_full(ray_cluster):
    from ray_tpu.util.queue import Full, Queue

    q = Queue(maxsize=2)
    q.put(1)
    q.put(2)
    with pytest.raises(Full):
        q.put(3, block=False)
    assert q.full()
    assert q.get() == 1
    q.put(3)
    q.shutdown()


def test_wait_batched_many_refs(ray_cluster):
    """wait() over many refs must stay cheap (owned refs resolve on local
    futures, no RPC storm) and honor num_returns."""
    ray_tpu = ray_cluster

    def slow(i):
        time.sleep(0.05 + 0.01 * (i % 5))
        return i

    f = ray_tpu.remote(slow)
    refs = [f.remote(i) for i in range(40)]
    t0 = time.monotonic()
    ready, pending = ray_tpu.wait(refs, num_returns=5, timeout=60)
    assert len(ready) >= 5
    assert len(ready) + len(pending) == 40
    ready_all, pending_all = ray_tpu.wait(refs, num_returns=40,
                                          timeout=120)
    assert len(ready_all) == 40 and not pending_all
    assert time.monotonic() - t0 < 60


_GCS_FT_SCRIPT = """
import asyncio, sys
from ray_tpu.core.gcs.server import GcsServer

async def run(phase, path):
    server = GcsServer(port=0, storage_path=path)
    await server.start()
    if phase == "write":
        from types import SimpleNamespace
        conn = None
        await server.handle_kv_put(conn, key=b"k1", value=b"v1",
                                   overwrite=True)
        await server.handle_add_job(conn, job_id="jobA",
                                    info={"driver": "x"})
        await server.handle_register_actor(conn, actor_id="a1",
            info={"name": "det", "namespace": "default",
                  "state": "ALIVE", "detached": True})
        await asyncio.sleep(2.5)  # > snapshot debounce
        print("WROTE", flush=True)
    else:
        v = await server.handle_kv_get(None, key=b"k1")
        job = await server.handle_get_job(None, job_id="jobA")
        actor = await server.handle_get_actor(None, actor_id="a1")
        assert v == b"v1", v
        assert job and job["driver"] == "x"
        assert actor and actor["name"] == "det"
        print("RECOVERED", flush=True)
    await server.stop()

asyncio.run(run(sys.argv[1], sys.argv[2]))
"""


def test_gcs_snapshot_recovery(tmp_path):
    path = str(tmp_path / "gcs.pkl")
    w = subprocess.run([sys.executable, "-c", _GCS_FT_SCRIPT, "write",
                        path], capture_output=True, text=True,
                       timeout=120)
    assert "WROTE" in w.stdout, w.stderr[-2000:]
    r = subprocess.run([sys.executable, "-c", _GCS_FT_SCRIPT, "read",
                        path], capture_output=True, text=True,
                       timeout=120)
    assert "RECOVERED" in r.stdout, r.stderr[-2000:]
