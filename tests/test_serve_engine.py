"""Serve + continuous-batching engine, end to end on a real cluster.

Acceptance for the engine subsystem: a deployment hosting an
`InferenceEngine` streams tokens through BOTH call paths (handle
async-generator and HTTP chunked) with the first token arriving before
generation completes, and under 2x sustained overload the proxy sheds
(503) before queuing while served-request latency stays bounded.
"""

import json
import socket
import threading
import time
import urllib.request

import pytest

pytestmark = pytest.mark.cluster


@pytest.fixture(scope="module")
def ray_cluster():
    import ray_tpu

    ray_tpu.init(num_cpus=8, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


@pytest.fixture()
def serve_instance(ray_cluster):
    from ray_tpu import serve

    yield serve
    serve.shutdown()


def _llm_deployment(serve, step_delay_s=0.0):
    @serve.deployment(max_ongoing_requests=32)
    class LLM:
        def __init__(self, delay):
            from ray_tpu.serve.engine import (EngineConfig,
                                              InferenceEngine, TinyLM)

            self.model = TinyLM(step_delay_s=delay)
            self.engine = InferenceEngine(
                self.model,
                EngineConfig(max_batch_size=8, block_size=8,
                             num_blocks=64, max_queue=64))
            self.engine.start()

        def generate(self, req):
            # Sync generator: one yield per engine token — the
            # streaming entrypoint for handle AND HTTP paths.
            stream = self.engine.submit(req["prompt"],
                                        req.get("max_new_tokens", 8))
            for tok in stream:
                yield tok

        async def __call__(self, req):
            stream = self.engine.submit(req["prompt"],
                                        req.get("max_new_tokens", 8))
            return [tok async for tok in stream]

        def engine_stats(self):
            return self.engine.stats()

    return LLM


def test_engine_in_replica_streaming_handle(serve_instance):
    """Handle streaming path: tokens arrive incrementally (first token
    while the replica is still decoding) and match TinyLM's oracle."""
    from ray_tpu.serve.engine import TinyLM

    serve = serve_instance
    LLM = _llm_deployment(serve)
    handle = serve.run(LLM.bind(0.05), route_prefix="/llm")

    req = {"prompt": [5, 9, 3], "max_new_tokens": 10}
    gen = handle.options(stream=True, method_name="generate").remote(req)
    it = iter(gen)
    t0 = time.perf_counter()
    first = next(it)
    t_first = time.perf_counter() - t0
    first_completed = gen.completed()
    rest = list(it)
    t_total = time.perf_counter() - t0

    oracle = TinyLM().oracle([5, 9, 3], 10)
    assert [first] + rest == oracle
    # First token decouples from completion: it arrived while the
    # replica was still generating (0.05 s/step x 10 steps ~ 0.5 s).
    assert not first_completed, \
        "stream reported completed at the FIRST token"
    assert t_first < t_total * 0.6, (t_first, t_total)

    # The non-streaming path returns the same tokens in one shot.
    out = handle.remote(req).result(timeout_s=60)
    assert out == oracle

    # Async iteration over the same streaming response type (what a
    # composing deployment would do inside its event loop).
    import asyncio

    async def consume():
        agen = handle.options(stream=True,
                              method_name="generate").remote(req)
        return [tok async for tok in agen]

    assert asyncio.run(consume()) == oracle


def test_engine_streaming_http_chunked(serve_instance):
    """HTTP path: Accept: text/event-stream gets chunked transfer with
    one SSE data event per token; the first chunk lands before the
    response completes."""
    from ray_tpu.serve.engine import TinyLM

    serve = serve_instance
    LLM = _llm_deployment(serve)
    serve.run(LLM.bind(0.05), route_prefix="/llm")
    port = serve.start()

    body = json.dumps({"prompt": [7, 2], "max_new_tokens": 8}).encode()
    with socket.create_connection(("127.0.0.1", port),
                                  timeout=60) as sock:
        sock.sendall(
            b"POST /llm?stream=1&method=generate HTTP/1.1\r\n"
            b"Host: localhost\r\n"
            b"Accept: text/event-stream\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: " + str(len(body)).encode() + b"\r\n"
            b"\r\n" + body)
        sock.settimeout(60)
        buf = b""
        first_event_at = None
        t0 = time.perf_counter()
        while b"0\r\n\r\n" not in buf:
            chunk = sock.recv(65536)
            assert chunk, f"connection closed early: {buf!r}"
            buf += chunk
            if first_event_at is None and b"data: " in buf:
                first_event_at = time.perf_counter() - t0
        total = time.perf_counter() - t0

    head, _, rest = buf.partition(b"\r\n\r\n")
    assert b"200 OK" in head
    assert b"Transfer-Encoding: chunked" in head
    assert b"text/event-stream" in head
    tokens = [int(line.split(b"data: ")[1])
              for line in buf.split(b"\n") if line.startswith(b"data: ")]
    assert tokens == TinyLM().oracle([7, 2], 8)
    # Incremental delivery: the first SSE event arrived well before the
    # full 8 x 0.05 s generation finished.
    assert first_event_at is not None and first_event_at < total * 0.6, \
        (first_event_at, total)


def test_proxy_sheds_under_2x_overload_with_bounded_p99(serve_instance):
    """Admission control: with the in-flight gate set, 2x sustained
    overload sheds (503, counted in serve_engine_shed_requests /
    admission_stats) instead of queuing, and the p99 of SERVED requests
    stays bounded."""
    serve = serve_instance
    LLM = _llm_deployment(serve)
    serve.run(LLM.bind(0.002), route_prefix="/llm")
    port = serve.start()
    assert serve.configure_proxy_admission(max_inflight=4)

    n_threads, per_thread = 8, 12
    statuses, latencies = [], []
    lock = threading.Lock()

    def hammer():
        for _ in range(per_thread):
            t0 = time.perf_counter()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/llm",
                data=json.dumps({"prompt": [4, 4],
                                 "max_new_tokens": 4}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=60) as r:
                    code = r.status
                    r.read()
            except urllib.error.HTTPError as e:
                code = e.code
                e.read()
            with lock:
                statuses.append(code)
                if code == 200:
                    latencies.append(time.perf_counter() - t0)

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)

    shed = sum(1 for s in statuses if s == 503)
    served = sum(1 for s in statuses if s == 200)
    assert served > 0, statuses
    assert shed > 0, f"no sheds under 2x overload: {statuses}"
    assert shed + served == len(statuses), statuses
    stats = serve.proxy_admission_stats()
    assert stats["shed_503"] >= shed
    # Bounded tail: the gate caps concurrently-dispatched work, so a
    # served request's latency is a few service times, not the whole
    # backlog. (Generous ceiling: 2-CPU CI boxes.)
    latencies.sort()
    p99 = latencies[min(len(latencies) - 1, int(len(latencies) * 0.99))]
    assert p99 < 10.0, f"p99 {p99:.2f}s under overload"
    # Gate off again for other tests sharing the proxy.
    serve.configure_proxy_admission(max_inflight=None)


def test_engine_stats_surface_through_named_method(serve_instance):
    serve = serve_instance
    LLM = _llm_deployment(serve)
    handle = serve.run(LLM.bind(0.0), route_prefix="/llm")
    handle.remote({"prompt": [3, 3], "max_new_tokens": 5}).result(
        timeout_s=60)
    st = handle.options(method_name="engine_stats").remote().result(
        timeout_s=60)
    assert st["finished"] >= 1
    assert st["tokens_generated"] >= 5
    assert st["cache"]["num_blocks"] == 64
