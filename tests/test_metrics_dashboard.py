"""Metrics facade + dashboard HTTP head.

Reference coverage class: `python/ray/tests/test_metrics_agent.py` +
`dashboard/tests/`. Unit level: instrument semantics and Prometheus
rendering. Cluster level: a user Counter incremented inside a task is
scrapable from the dashboard's /metrics, and the JSON API serves cluster
state.
"""

import json
import urllib.request

import pytest

pytestmark = pytest.mark.cluster


def test_counter_gauge_histogram_semantics():
    from ray_tpu.util.metrics import (Counter, Gauge, Histogram,
                                      MetricsRegistry)

    reg = MetricsRegistry()
    c = Counter("req_total", "requests", tag_keys=("route",), registry=reg)
    c.inc(tags={"route": "/a"})
    c.inc(2.0, tags={"route": "/a"})
    c.inc(tags={"route": "/b"})
    with pytest.raises(ValueError):
        c.inc(-1.0, tags={"route": "/a"})
    with pytest.raises(ValueError):
        c.inc(tags={"bogus": "x"})  # undeclared tag key

    g = Gauge("temp", registry=reg)
    g.set(3.5)
    g.set(1.5)

    h = Histogram("lat", boundaries=[0.1, 1.0], registry=reg)
    for v in (0.05, 0.5, 5.0):
        h.observe(v)

    snap = {m["name"]: m for m in reg.snapshot()}
    by_route = {tuple(s["tags"].items()): s["value"]
                for s in snap["req_total"]["samples"]}
    assert by_route[(("route", "/a"),)] == 3.0
    assert by_route[(("route", "/b"),)] == 1.0
    assert snap["temp"]["samples"][0]["value"] == 1.5
    hs = snap["lat"]["samples"][0]
    assert hs["buckets"] == [1, 1, 1] and hs["count"] == 3
    assert hs["sum"] == pytest.approx(5.55)


def test_prometheus_rendering_and_merge():
    from ray_tpu.util.metrics import (Counter, Histogram, MetricsRegistry,
                                      merge_snapshots, render_prometheus)

    reg = MetricsRegistry()
    Counter("hits", "h", tag_keys=("k",), registry=reg).inc(
        5, tags={"k": "v"})
    Histogram("lat", boundaries=[1.0], registry=reg).observe(0.5)
    merged = merge_snapshots([({"node_id": "abc"}, reg.snapshot())])
    text = render_prometheus(merged)
    assert '# TYPE hits counter' in text
    assert 'hits{k="v",node_id="abc"} 5.0' in text
    # Cumulative histogram buckets + +Inf.
    assert 'lat_bucket' in text and 'le="+Inf"' in text
    assert 'lat_count{node_id="abc"} 1' in text


def test_registry_rejects_type_conflict():
    from ray_tpu.util.metrics import Counter, Gauge, MetricsRegistry

    reg = MetricsRegistry()
    Counter("m1", registry=reg)
    with pytest.raises(ValueError):
        Gauge("m1", registry=reg)


@pytest.fixture(scope="module")
def ray_cluster():
    import ray_tpu

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield ray_tpu
    ray_tpu.shutdown()


def _dashboard_url(ray_tpu) -> str:
    node = ray_tpu._private_node()
    assert node is not None and node.dashboard_address
    return f"http://{node.dashboard_address}"


def _get(url: str):
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.status, resp.read().decode()


def test_dashboard_api_and_cluster_metrics(ray_cluster, tmp_path):
    import time

    import ray_tpu

    base = _dashboard_url(ray_tpu)
    status, body = _get(base + "/api/nodes")
    assert status == 200
    nodes = json.loads(body)
    assert len(nodes) >= 1 and all("node_id" in n for n in nodes)

    status, body = _get(base + "/api/cluster_status")
    assert status == 200
    st = json.loads(body)
    assert st["nodes_alive"] >= 1
    assert st["resources_total"].get("CPU", 0) >= 4

    # /api/cluster folds the control plane's own identity in (round 18:
    # on an HA deployment this also carries leader/term/replication lag).
    status, body = _get(base + "/api/cluster")
    assert status == 200
    st = json.loads(body)
    assert st["nodes_alive"] >= 1
    assert st.get("cluster_id"), st
    assert "num_workers" in st

    # A user metric incremented inside a task reaches /metrics via the
    # worker -> raylet push -> dashboard scrape chain.
    @ray_tpu.remote
    def bump():
        from ray_tpu.util.metrics import Counter

        c = Counter("my_app_events", "events", tag_keys=("kind",))
        c.inc(7, tags={"kind": "test"})
        # Push interval is metrics_report_interval_ms (2s default): hold
        # the worker alive long enough for one flush.
        time.sleep(3.0)
        return True

    assert ray_tpu.get(bump.remote(), timeout=120)
    deadline = time.time() + 30
    text = ""
    while time.time() < deadline:
        _, text = _get(base + "/metrics")
        if "my_app_events" in text:
            break
        time.sleep(1.0)
    assert 'my_app_events{kind="test"' in text, text[:2000]
    # Runtime gauges from the raylet are present too.
    assert "ray_tpu_object_store_capacity_bytes" in text
    assert "ray_tpu_resource_available" in text

    # Actor + object inventories serve without error.
    status, body = _get(base + "/api/actors")
    assert status == 200
    status, body = _get(base + "/api/objects")
    assert status == 200
    assert isinstance(json.loads(body), list)


def _poll_metrics(base, needle, timeout=40):
    import time

    deadline = time.time() + timeout
    text = ""
    while time.time() < deadline:
        _, text = _get(base + "/metrics")
        if needle in text:
            return text
        time.sleep(1.0)
    return text


def test_serve_request_metrics_reach_dashboard(ray_cluster):
    """Acceptance: /metrics exposes serve_* latency histograms after
    requests flow, and /api/serve aggregates per-deployment state."""
    import urllib.request

    import ray_tpu
    from ray_tpu import serve

    base = _dashboard_url(ray_tpu)
    try:
        @serve.deployment
        class Ping:
            def __call__(self, payload):
                return {"pong": True}

        serve.run(Ping.bind(), name="ping", route_prefix="/ping")
        port = serve.start()
        for _ in range(5):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/ping", timeout=60) as r:
                assert r.status == 200

        text = _poll_metrics(base,
                             "serve_deployment_processing_latency_seconds")
        assert "serve_deployment_processing_latency_seconds_bucket" \
            in text, text[:2000]
        assert "serve_request_latency_seconds_bucket" in text
        assert 'serve_num_requests{ingress="http"' in text
        assert "serve_deployment_processed_queries" in text

        status, body = _get(base + "/api/serve")
        assert status == 200
        state = json.loads(body)
        dep = state["deployments"].get("Ping")
        assert dep is not None, state
        assert dep["processed"] >= 5
        assert dep["latency_p50_s"] is not None
        assert state["ingress"]["requests"].get("http", 0) >= 5
    finally:
        serve.shutdown()


def test_log_aggregation_endpoint(ray_cluster):
    """`/api/logs?node=…&worker=…` serves per-worker log tails through
    the raylet `get_worker_logs` RPC (ROADMAP carried-over item)."""
    import time

    import ray_tpu

    base = _dashboard_url(ray_tpu)

    @ray_tpu.remote
    def chatty():
        print("log-aggregation-probe-714")
        import sys

        sys.stdout.flush()
        time.sleep(1.0)   # keep the worker alive for the read
        return 1

    ref = chatty.remote()
    deadline = time.time() + 30
    entries = []
    while time.time() < deadline:
        status, body = _get(base + "/api/logs")
        assert status == 200
        entries = json.loads(body)
        if any("log-aggregation-probe-714" in line
               for e in entries if isinstance(e.get("lines"), list)
               for line in e["lines"]):
            break
        time.sleep(0.5)
    assert ray_tpu.get(ref, timeout=60) == 1
    hit = [e for e in entries
           if any("log-aggregation-probe-714" in line
                  for line in e.get("lines", []))]
    assert hit, f"probe line never surfaced: {entries}"
    entry = hit[0]
    assert entry["worker_id"] and entry["node_id"] and entry["pid"]

    # Filters: a worker-id prefix narrows to that worker; a bogus node
    # prefix yields nothing.
    wid = entry["worker_id"]
    status, body = _get(base + f"/api/logs?worker={wid[:8]}")
    assert status == 200
    filtered = json.loads(body)
    assert filtered and all(e["worker_id"].startswith(wid[:8])
                            for e in filtered)
    status, body = _get(base + "/api/logs?node=ffffffff")
    assert status == 200
    assert json.loads(body) == []


def _telemetry_train_loop(config):
    import time

    from ray_tpu import train

    shard = train.get_dataset_shard("train")
    for _ in range(config["steps"]):
        if shard is not None:
            for _b in shard.iter_batches(batch_size=64):
                pass
        time.sleep(0.02)
        train.report({"loss": 1.0})


def test_train_step_telemetry_reaches_dashboard(ray_cluster):
    """Acceptance: train_* step-time series appear in /metrics; the
    /api/train endpoint aggregates the per-trial step split."""
    import ray_tpu
    from ray_tpu import data
    from ray_tpu.train import JaxConfig, JaxTrainer, RunConfig, ScalingConfig

    base = _dashboard_url(ray_tpu)
    trainer = JaxTrainer(
        _telemetry_train_loop,
        train_loop_config={"steps": 4},
        jax_config=JaxConfig(platform="cpu"),
        scaling_config=ScalingConfig(num_workers=2),
        datasets={"train": data.range(512, parallelism=4)},
        run_config=RunConfig(name="telemetry_probe",
                             storage_path="/tmp/rt_train_obs"))
    result = trainer.fit()
    assert result.error is None

    text = _poll_metrics(base, "train_step_time_seconds")
    assert "train_step_time_seconds_bucket" in text, text[:2000]
    assert "train_data_wait_seconds" in text
    assert "train_compute_seconds" in text
    assert 'trial="telemetry_probe"' in text

    status, body = _get(base + "/api/train")
    assert status == 200
    state = json.loads(body)
    trial = state["trials"].get("telemetry_probe")
    assert trial is not None, state
    assert trial["steps"] >= 4 * 2  # 4 steps x 2 workers
    assert trial["breakdown_s"].get("step_time", 0) > 0
    assert "data_wait" in trial["breakdown_s"]


def test_flight_timeline_endpoint(ray_cluster):
    """`/api/timeline` merges every process's flight-recorder ring into
    Chrome-trace JSON: well-formed on a quiet cluster, and after a task
    burst it carries task-category events from more than one process
    (raylet + workers), clock-aligned to non-negative timestamps."""
    import time

    import ray_tpu

    base = _dashboard_url(ray_tpu)

    # Quiet-cluster shape: valid Chrome trace envelope.
    status, body = _get(base + "/api/timeline?window_s=60")
    assert status == 200
    trace = json.loads(body)
    assert isinstance(trace["traceEvents"], list)
    assert trace["displayTimeUnit"] == "ms"

    @ray_tpu.remote(_metadata={"inline": False})
    def burst_noop():
        return 1

    assert all(v == 1 for v in ray_tpu.get(
        [burst_noop.remote() for _ in range(20)], timeout=120))

    deadline = time.time() + 30
    task_events, pids = [], set()
    while time.time() < deadline:
        status, body = _get(base + "/api/timeline?window_s=120")
        assert status == 200
        trace = json.loads(body)
        task_events = [e for e in trace["traceEvents"]
                       if e.get("cat") == "task"]
        pids = {e["pid"] for e in trace["traceEvents"]
                if e["ph"] != "M"}
        if task_events and len(pids) >= 2:
            break
        time.sleep(0.5)
    assert task_events, "no task-category events after a 20-task burst"
    assert len(pids) >= 2, f"events span only {pids}"
    assert any(e["name"].startswith("exec:") for e in task_events)
    assert all(e["ts"] >= 0 for e in trace["traceEvents"]
               if e["ph"] != "M")
    # process_name metadata labels each merged process — including
    # the DRIVER (registered with its raylet as a flight source), so
    # the timeline spans the submit side too.
    metas = [e for e in trace["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"]
    assert metas and any("worker" in m["args"]["name"] for m in metas)
    assert any("driver" in m["args"]["name"] for m in metas), metas


def test_timeline_attributes_recovery_events(ray_cluster):
    """Round-15 recovery work is attributable in the merged timeline:
    `lineage.reexec` / `pg.reschedule` / `cgraph.restart` events
    recorded in a process's flight ring surface through /api/timeline
    with their categories intact. (The real recovery paths emit them —
    pinned in test_unit_simcluster and test_cgraph; this pins the
    dashboard surface end to end via the driver's registered flight
    source.)"""
    import time

    import ray_tpu
    from ray_tpu.core import flight

    base = _dashboard_url(ray_tpu)
    flight.instant("lineage", "lineage.reexec", arg="probe left=1")
    flight.instant("pg", "pg.reschedule", arg="probe n=1")
    flight.instant("cgraph", "cgraph.restart", arg="probe left=1")
    want = {"lineage.reexec", "pg.reschedule", "cgraph.restart"}
    deadline = time.time() + 30
    names: set = set()
    while time.time() < deadline:
        status, body = _get(base + "/api/timeline?window_s=60")
        assert status == 200
        trace = json.loads(body)
        names = {e["name"] for e in trace["traceEvents"]
                 if e["ph"] != "M"}
        if want <= names:
            break
        time.sleep(0.5)
    assert want <= names, sorted(names)[:40]
    cats = {e["name"]: e.get("cat") for e in trace["traceEvents"]
            if e["ph"] != "M" and e["name"] in want}
    assert cats == {"lineage.reexec": "lineage", "pg.reschedule": "pg",
                    "cgraph.restart": "cgraph"}, cats


def test_flight_stalls_endpoint_shape(ray_cluster):
    """`/api/stalls` always answers with a list; episodes (when any
    process stalled) carry the lag measurement + identity fields."""
    import ray_tpu

    base = _dashboard_url(ray_tpu)
    status, body = _get(base + "/api/stalls")
    assert status == 200
    episodes = json.loads(body)
    assert isinstance(episodes, list)
    for ep in episodes:
        assert "lag_ms" in ep and "loop" in ep and "pid" in ep


def _stall_the_driver_loop():
    import time

    time.sleep(0.25)   # blocks the RPC loop: the frame the report names


def test_induced_driver_stall_produces_report(ray_cluster):
    """Acceptance: blocking the driver's RPC loop >150 ms produces a
    stall episode with the loop-lag measurement, an all-threads stack
    dump naming the blocking frame, and the surrounding ring events."""
    import os
    import time

    import ray_tpu
    from ray_tpu.core import flight

    rt = ray_tpu.core.worker.current_runtime()
    assert flight.enabled, "flight recorder should default on"
    before = len(flight.stalls())
    flight.record("task", "stall-context-marker-4242", dur_us=3)
    rt._loop.loop.call_soon_threadsafe(_stall_the_driver_loop)
    deadline = time.time() + 10
    while time.time() < deadline and len(flight.stalls()) <= before:
        time.sleep(0.05)
    episodes = flight.stalls()[before:]
    assert episodes, "driver stall never produced an episode"
    ep = episodes[-1]
    assert ep["lag_ms"] >= 100        # 250 ms block, 100 ms threshold
    stacks = json.dumps(ep["stacks"])
    assert "_stall_the_driver_loop" in stacks
    assert any(e[3] == "stall-context-marker-4242" for e in ep["events"])
    assert ep["report_path"] and json.load(open(ep["report_path"]))

    # The same episode is visible cluster-wide at /api/stalls (the
    # driver registered itself as a flight source with its raylet).
    base = _dashboard_url(ray_tpu)
    deadline = time.time() + 15
    seen = []
    while time.time() < deadline:
        status, body = _get(base + "/api/stalls")
        assert status == 200
        seen = [s for s in json.loads(body)
                if s.get("loop") == "driver-loop"
                and s.get("pid") == os.getpid()]
        if seen:
            break
        time.sleep(0.5)
    assert seen, "driver stall never surfaced at /api/stalls"
    assert "_stall_the_driver_loop" in json.dumps(seen[0]["stacks"])


def test_per_task_cprofile_optin(ray_cluster):
    """`.options(_metadata={"profile": True})` wraps worker exec in
    cProfile: identical results, pstats dump next to the worker log
    (the directory `/api/logs` serves from)."""
    import glob
    import os
    import time

    import ray_tpu

    node = ray_tpu._private_node()
    assert node is not None

    @ray_tpu.remote
    def crunch(n):
        return sum(i * i for i in range(n))

    plain = ray_tpu.get(crunch.remote(50_000), timeout=120)
    profiled = ray_tpu.get(
        crunch.options(_metadata={"profile": True}).remote(50_000),
        timeout=120)
    assert profiled == plain

    deadline = time.time() + 20
    dumps = []
    while time.time() < deadline:
        dumps = glob.glob(os.path.join(
            node.log_dir, "worker-*-profile-*.pstats.txt"))
        if dumps:
            break
        time.sleep(0.25)
    assert dumps, f"no profile dump in {node.log_dir}"
    text = open(dumps[0]).read()
    assert "cumulative" in text and "crunch" in text


# ---------------------------------------------------------------------------
# Round 17: pushed metrics pipeline endpoints (query, SLO, timeline
# filters, train profiles)
# ---------------------------------------------------------------------------

def test_metrics_query_endpoint(ray_cluster):
    """`/api/metrics/query` serves windowed reads from the GCS
    retention store: raw points for a pushed runtime gauge, and
    rate/group_by over a counter a task just bumped."""
    import time

    import ray_tpu

    base = _dashboard_url(ray_tpu)

    @ray_tpu.remote
    def bump_query_probe():
        from ray_tpu.util.metrics import Counter

        c = Counter("query_probe_total", "probe", tag_keys=("kind",))
        c.inc(30, tags={"kind": "q"})
        time.sleep(3.0)  # one metrics_report_interval flush
        return True

    assert ray_tpu.get(bump_query_probe.remote(), timeout=120)

    deadline = time.time() + 40
    data = {}
    while time.time() < deadline:
        status, body = _get(base + "/api/metrics/query"
                            "?series=query_probe_total&window_s=120"
                            "&agg=sum&labels=kind=q")
        assert status == 200
        data = json.loads(body)
        if data.get("results") and data["results"][0]["value"]:
            break
        time.sleep(1.0)
    assert data.get("matched", 0) >= 1, data
    assert data["results"][0]["value"] == 30.0, data

    # The raylet's own runtime gauges arrive through the same pipe;
    # raw returns per-series points labeled node_id/role at ingest.
    status, body = _get(base + "/api/metrics/query"
                        "?series=ray_tpu_resource_available"
                        "&window_s=120&agg=raw")
    assert status == 200
    data = json.loads(body)
    assert data["matched"] >= 1, data
    rows = data["results"]
    assert any(r["points"] for r in rows), rows
    assert all("node_id" in r["labels"] for r in rows), rows
    assert any(r["labels"].get("role") == "raylet" for r in rows), rows

    # group_by folds the label space server-side.
    status, body = _get(base + "/api/metrics/query"
                        "?series=query_probe_total&window_s=120"
                        "&agg=rate&group_by=kind")
    assert status == 200
    data = json.loads(body)
    assert any(r["labels"].get("kind") == "q" and r["value"] > 0
               for r in data["results"]), data

    # series= is mandatory.
    status, body = _get(base + "/api/metrics/query")
    assert json.loads(body).get("error")


def test_timeline_category_pid_filters_and_cap(ray_cluster):
    """Satellite 2: `/api/timeline` filters by category/pid server-side
    and caps the non-metadata payload (most recent kept, truncation
    reported)."""
    import time

    import ray_tpu

    base = _dashboard_url(ray_tpu)

    @ray_tpu.remote(_metadata={"inline": False})
    def filter_burst():
        return 1

    assert all(v == 1 for v in ray_tpu.get(
        [filter_burst.remote() for _ in range(20)], timeout=120))

    deadline = time.time() + 30
    body_events = []
    while time.time() < deadline:
        status, body = _get(base + "/api/timeline?window_s=120"
                            "&category=task")
        assert status == 200
        trace = json.loads(body)
        body_events = [e for e in trace["traceEvents"]
                       if e.get("ph") != "M"]
        if len(body_events) > 5:
            break
        time.sleep(0.5)
    assert body_events, "no task events after a 20-task burst"
    assert all(e.get("cat") == "task" for e in body_events), \
        {e.get("cat") for e in body_events}

    # pid filter narrows to one process (metadata rows stay).
    pid = body_events[0]["pid"]
    status, body = _get(base + f"/api/timeline?window_s=120&pid={pid}")
    assert status == 200
    trace = json.loads(body)
    filtered = [e for e in trace["traceEvents"] if e.get("ph") != "M"]
    assert filtered and all(e["pid"] == pid for e in filtered)

    # Bounded payload: cap at 5 keeps the 5 most recent events and
    # reports how many were dropped.
    status, body = _get(base + "/api/timeline?window_s=120"
                        "&category=task&max_events=5")
    assert status == 200
    trace = json.loads(body)
    capped = [e for e in trace["traceEvents"] if e.get("ph") != "M"]
    assert len(capped) == 5, len(capped)
    assert trace.get("truncated_events", 0) >= len(body_events) - 5 > 0
    assert all(e.get("ph") == "M" or e.get("cat") == "task"
               for e in trace["traceEvents"])


def test_slo_pages_under_overload_and_burns_on_timeline(ray_cluster):
    """ISSUE 17 acceptance: a declared latency SLO transitions to
    `page` under a deliberately overloaded engine, visible at
    `/api/slo`, and the transition lands as a `slo.burn` event on the
    merged `/api/timeline`."""
    import time
    import urllib.request

    import ray_tpu
    from ray_tpu import serve

    base = _dashboard_url(ray_tpu)
    rt = ray_tpu.core.worker.current_runtime()
    try:
        @serve.deployment
        class Slow:
            def __call__(self, payload):
                time.sleep(0.02)  # every request busts the 0.5ms SLO
                return {"ok": True}

        serve.run(Slow.bind(), name="slow", route_prefix="/slow")
        port = serve.start()

        # p99 < 0.5 ms over 30 s: impossible for a 20 ms handler, so
        # the error budget burns at 100x (page needs >= 10x in both
        # the 30 s and the 2.5 s window).
        rt._loop.run(rt._gcs.register_slo({
            "name": "slow_latency",
            "objective": "latency_quantile",
            "series": "serve_deployment_processing_latency_seconds",
            "labels": {"deployment": "Slow"},
            "q": 0.99, "threshold_s": 0.0005, "window_s": 30.0,
        }), timeout=30)

        deadline = time.time() + 90
        row = {}
        while time.time() < deadline:
            # Keep the overload current: the short burn window needs
            # observations from the last couple of seconds.
            for _ in range(3):
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/slow", timeout=60) as r:
                    assert r.status == 200
            status, body = _get(base + "/api/slo")
            assert status == 200
            rows = {r["name"]: r for r in json.loads(body)}
            row = rows.get("slow_latency", {})
            if row.get("state") == "page":
                break
            time.sleep(1.0)
        assert row.get("state") == "page", row
        assert row["burn_long"] >= 10.0 and row["burn_short"] >= 10.0
        assert row["window_events"] > 0
        assert row["current_quantile_s"] is None \
            or row["current_quantile_s"] > 0.0005

        # The ok->page transition fired a slo.burn flight event in the
        # GCS ring; the merged timeline carries it under category=slo.
        deadline = time.time() + 30
        burns = []
        while time.time() < deadline:
            status, body = _get(base + "/api/timeline?window_s=300"
                                "&category=slo")
            assert status == 200
            trace = json.loads(body)
            burns = [e for e in trace["traceEvents"]
                     if e.get("ph") != "M" and e["name"] == "slo.burn"]
            if burns:
                break
            time.sleep(0.5)
        assert burns, "slo.burn never surfaced on /api/timeline"
        assert any("slow_latency" in (e.get("args", {}).get("arg") or "")
                   for e in burns), burns
    finally:
        try:
            rt._loop.run(rt._gcs.remove_slo("slow_latency"), timeout=10)
        except Exception:
            pass
        serve.shutdown()


def _profiled_train_loop(config):
    from ray_tpu import train

    for _ in range(config["steps"]):
        train.report({"loss": 0.5})


def test_train_profile_capture_and_endpoint(ray_cluster, tmp_path):
    """Satellite 1: TrainConfig(profile_steps=(a, b)) captures a
    jax.profiler trace on the worker; the trace dir is published and
    listed at `/api/train/profile` and linked from `/api/train`."""
    import os
    import time

    import ray_tpu
    from ray_tpu.train import (JaxConfig, JaxTrainer, RunConfig,
                               ScalingConfig, TrainConfig)

    base = _dashboard_url(ray_tpu)
    profile_dir = str(tmp_path / "traces")
    trainer = JaxTrainer(
        _profiled_train_loop,
        train_loop_config={"steps": 3},
        jax_config=JaxConfig(platform="cpu"),
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="profile_probe",
                             storage_path="/tmp/rt_train_prof"),
        train_config=TrainConfig(profile_steps=(1, 2),
                                 profile_dir=profile_dir))
    result = trainer.fit()
    assert result.error is None

    deadline = time.time() + 30
    mine = []
    while time.time() < deadline:
        status, body = _get(base + "/api/train/profile")
        assert status == 200
        mine = [r for r in json.loads(body)
                if r.get("trial") == "profile_probe"]
        if mine:
            break
        time.sleep(0.5)
    assert mine, "published profile never listed"
    row = mine[0]
    assert row["rank"] == 0 and row["steps"] == [1, 2]
    # Single-box test cluster: the worker's trace dir is local —
    # jax.profiler wrote actual artifacts into it.
    assert row["trace_dir"].startswith(profile_dir)
    assert os.path.isdir(row["trace_dir"])
    found = []
    for root, _dirs, files in os.walk(row["trace_dir"]):
        found.extend(files)
    assert found, f"empty trace dir {row['trace_dir']}"

    # The train pane folds the link in.
    status, body = _get(base + "/api/train")
    assert status == 200
    trial = json.loads(body)["trials"].get("profile_probe")
    assert trial is not None
    profs = trial.get("profiles", [])
    assert profs and profs[0]["trace_dir"] == row["trace_dir"]
