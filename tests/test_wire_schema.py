"""Typed wire schema: registry, validated decode, version handshake, fuzz.

Reference coverage class: the protobuf schema guarantees of
`src/ray/protobuf/common.proto` / `gcs_service.proto` — message typing,
field validation, and cross-version compatibility — which the reference
gets from protoc and `ray_tpu` gets from `core/wire.py`.
"""

import asyncio
import random

import pytest

from ray_tpu.core import wire
from ray_tpu.core.wire import (ActorInfo, SchemaMismatchError, TaskSpec,
                               WireDecodeError, WireError, check_digest,
                               from_wire, schema_digest, to_wire)


def make_spec(**over):
    base = dict(task_id="t" * 16, job_id="j" * 8, name="f", fn_key="abc",
                args=b"\x80\x04", num_returns=1,
                resources={"CPU": 1.0})
    base.update(over)
    return TaskSpec(**base)


class TestRoundtrip:
    def test_roundtrip_preserves_fields(self):
        spec = make_spec(pg={"pg_id": "p", "bundle_index": 0})
        d = to_wire(spec)
        assert d["_t"] == "TaskSpec" and d["_v"] == 1
        back = from_wire(d)
        assert back.task_id == spec.task_id
        assert back["fn_key"] == "abc"          # Mapping access
        assert back.get("missing", 42) == 42
        assert back.pg == {"pg_id": "p", "bundle_index": 0}

    def test_defaults_fill_on_decode(self):
        d = to_wire(make_spec())
        del d["max_retries"]
        assert from_wire(d).max_retries == 0

    def test_unknown_fields_carried_through(self):
        # Forward compat: a newer-minor peer's extra field survives decode
        # (a relay must not silently strip what it doesn't understand).
        d = to_wire(make_spec())
        d["added_in_v1_1"] = "x"
        assert from_wire(d)["added_in_v1_1"] == "x"

    def test_replace_copies(self):
        spec = make_spec()
        dup = spec.replace(visible_chips=[0, 1])
        assert dup.visible_chips == [0, 1]
        assert spec.visible_chips is None


class TestDecodeErrors:
    def test_missing_required_field(self):
        d = to_wire(make_spec())
        del d["task_id"]
        with pytest.raises(WireDecodeError, match="task_id"):
            from_wire(d)

    def test_wrong_type(self):
        d = to_wire(make_spec())
        d["num_returns"] = "three"
        with pytest.raises(WireDecodeError, match="num_returns"):
            from_wire(d)

    def test_null_in_non_optional(self):
        d = to_wire(make_spec())
        d["args"] = None
        with pytest.raises(WireDecodeError, match="args"):
            from_wire(d)

    def test_unknown_message_type(self):
        with pytest.raises(WireDecodeError, match="unknown"):
            from_wire({"_t": "NoSuchMessage", "_v": 1})

    def test_missing_envelope(self):
        with pytest.raises(WireDecodeError):
            from_wire({"task_id": "x"})
        with pytest.raises(WireDecodeError):
            from_wire([1, 2, 3])

    def test_expect_mismatch(self):
        with pytest.raises(WireDecodeError, match="expected"):
            from_wire(to_wire(make_spec()), expect="ActorInfo")

    def test_version_mismatch_is_typed(self):
        d = to_wire(make_spec())
        d["_v"] = 99
        with pytest.raises(SchemaMismatchError):
            from_wire(d)


class TestFuzz:
    """Randomly corrupted payloads must fail with a WireError subclass —
    never KeyError/TypeError/AttributeError leaking from a handler."""

    def test_fuzzed_decode_raises_typed_errors_only(self):
        rng = random.Random(7)
        junk = [None, True, 0, -1, 3.14, "", "x", b"\xff" * 8, [], [1],
                {}, {"a": 1}, float("nan")]
        base = to_wire(make_spec(runtime_env={"env_vars": {"A": "1"}}))
        survived = 0
        for _ in range(500):
            d = dict(base)
            for _ in range(rng.randint(1, 4)):
                op = rng.random()
                key = rng.choice(list(d) + ["new_key"])
                if op < 0.45:
                    d[key] = rng.choice(junk)
                elif op < 0.8:
                    d.pop(key, None)
                else:
                    d[rng.choice(["_t", "_v"])] = rng.choice(junk)
            try:
                from_wire(d)
                survived += 1   # corruption hit only optional fields: fine
            except WireError:
                pass            # typed failure: the contract
        assert survived < 500   # the fuzzer actually corrupted things

    def test_fuzz_all_message_types(self):
        rng = random.Random(11)
        for name, (cls, ver) in wire._REGISTRY.items():
            for _ in range(50):
                d = {"_t": name, "_v": ver}
                for fname, _pred, _opt, _req in cls._wire_specs:
                    if rng.random() < 0.7:
                        d[fname] = rng.choice(
                            [None, 1, "s", b"b", [1], {"k": 1}, True])
                try:
                    from_wire(d)
                except WireError:
                    pass


class TestHandshake:
    def test_digest_lists_core_messages(self):
        digest = schema_digest()
        for name in ("TaskSpec", "ActorTaskSpec", "LeaseRequest",
                     "LeaseReply", "ObjectRequest", "ObjectInfo",
                     "ActorInfo", "JobInfo", "NodeInfo", "PubsubMessage"):
            assert digest[name] >= 1

    def test_check_digest_accepts_equal_and_disjoint(self):
        check_digest(schema_digest())
        check_digest({})                       # nothing shared: fine
        check_digest({"TheirNewMessage": 3})   # one-sided: fine

    def test_check_digest_rejects_version_skew(self):
        peer = dict(schema_digest())
        peer["TaskSpec"] += 1
        with pytest.raises(SchemaMismatchError, match="TaskSpec"):
            check_digest(peer)

    def test_rpc_connect_rejects_mixed_version_peer(self, monkeypatch):
        """End-to-end: a server advertising a bumped TaskSpec schema fails
        the client's connection handshake — with the typed error, at
        connect time (the server's digest is faked since client and server
        share one process registry here)."""
        from ray_tpu.core.rpc import RpcClient, RpcServer

        class NoHandlers:
            pass

        async def run():
            server = RpcServer(NoHandlers())
            await server.start()
            try:
                ok_client = RpcClient(server.address)
                await ok_client.connect(timeout=5)     # same version: fine
                await ok_client.close()

                skewed = dict(schema_digest())
                skewed["TaskSpec"] += 1
                monkeypatch.setattr(wire, "schema_digest", lambda: skewed)
                bad_client = RpcClient(server.address)
                with pytest.raises(SchemaMismatchError, match="TaskSpec"):
                    await bad_client.connect(timeout=5)
                await bad_client.close()
            finally:
                await server.stop()

        asyncio.run(run())


class TestActorInfo:
    def test_actor_info_roundtrip(self):
        info = ActorInfo(actor_id="a" * 8, state="PENDING", name="n",
                         namespace="default", max_restarts=2,
                         method_meta={"m": {}})
        back = from_wire(to_wire(info), expect="ActorInfo")
        assert back.state == "PENDING" and back.max_restarts == 2
        # dict(msg) works (handlers build table records this way)
        assert dict(back)["name"] == "n"
