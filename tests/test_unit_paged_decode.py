"""Device-resident paged decode: donated KV pool + in-jit block gather.

Unit tier for PR 20. The KV block pool can live as a jax array
(`KVCacheManager(device_pool=True)`) whose every mutation is a
donated-arg jitted update, and the engine's paged path
(`EngineConfig(paged_decode=True)`) hands the pool + block tables into
ONE fused compiled step per decode iteration (in-jit `jnp.take`
gather, decode math, in-place KV scatter). Correctness here is
token-level: TinyLM's next token is a function of the CACHED kv
contents, so any table/gather/scatter indexing bug changes the output
against `TinyLM.oracle`; the transformer tests compare against the
host-gather engine AND greedy full-recompute. COW, adoption,
preemption and cross-engine shipping semantics must be bit-identical
in both pool residencies.

Everything runs under `JAX_PLATFORMS=cpu` — the device pool is then
host RAM, but the code path (donation, in-jit gather, scatter
write-back) is exactly what a TPU backend executes.
"""

import numpy as np
import pytest

from ray_tpu.serve.engine import (EngineConfig, InferenceEngine,
                                  KVCacheManager, TinyLM)

pytestmark = pytest.mark.unit

KV = (2, 3)          # toy per-token KV shape for manager-level tests


def _drive(eng):
    while eng.step():
        pass


# ---------------------------------------------------------------------------
# device pool: manager-level storage semantics
# ---------------------------------------------------------------------------
def test_device_pool_write_gather_matches_numpy():
    """write / write_range spanning block boundaries through the
    donated scatter land exactly where the numpy pool puts them —
    including a range that starts and ends mid-block."""
    host = KVCacheManager(num_blocks=8, block_size=4, kv_shape=KV)
    dev = KVCacheManager(num_blocks=8, block_size=4, kv_shape=KV,
                         device_pool=True)
    assert dev.pool_residency == "device"
    vals = np.arange(11 * 6, dtype=np.float32).reshape(11, *KV)
    for mgr in (host, dev):
        assert mgr.allocate("s", 11)
        mgr.write_range("s", 0, vals[:3])       # head, mid-block end
        mgr.write_range("s", 3, vals[3:10])     # spans two boundaries
        mgr.write("s", 10, vals[10])            # single-token write
    np.testing.assert_array_equal(np.asarray(dev.gather("s")),
                                  host.gather("s"))
    np.testing.assert_array_equal(np.asarray(dev.gather("s", 5)),
                                  vals[:5])
    assert dev.pool_updates >= 3
    assert dev.pool_bytes == 8 * 4 * 6 * 4      # blocks*size*kv*fp32


def test_device_pool_bfloat16_roundtrip():
    """A bfloat16 pool stores and gathers with bf16 rounding only —
    the dtype a TPU-resident pool would actually use."""
    jnp = pytest.importorskip("jax.numpy")
    mgr = KVCacheManager(num_blocks=4, block_size=4, kv_shape=KV,
                         dtype=jnp.bfloat16, device_pool=True)
    assert mgr.allocate("s", 6)
    vals = np.linspace(0.0, 2.0, 6 * 6, dtype=np.float32).reshape(
        6, *KV)
    mgr.write_range("s", 0, vals)
    out = np.asarray(mgr.gather("s"), np.float32)
    np.testing.assert_allclose(out, vals, atol=0.01)   # bf16 mantissa
    assert mgr.pool_bytes == 4 * 4 * 6 * 2
    assert mgr.stats()["pool_residency"] == "device"


def test_device_pool_cow_privatizes_before_write():
    """A write into a shared block on the device pool copies it first:
    the writer sees its new value, the other holder keeps reading the
    original bytes."""
    mgr = KVCacheManager(num_blocks=8, block_size=4, kv_shape=KV,
                         device_pool=True)
    assert mgr.allocate("a", 4)
    vals = np.ones((4,) + KV, np.float32)
    mgr.write_range("a", 0, vals)
    shared = mgr.block_table("a")[0]
    mgr.adopt("b", [shared], 4)
    mgr.write("b", 2, vals[0] * 7.0)            # COW fault
    assert mgr.block_table("b")[0] != shared
    assert mgr.cow_copies == 1
    np.testing.assert_array_equal(np.asarray(mgr.gather("a")), vals)
    got = np.asarray(mgr.gather("b"))
    np.testing.assert_array_equal(got[2], vals[0] * 7.0)
    np.testing.assert_array_equal(got[:2], vals[:2])


@pytest.mark.parametrize("device_pool", [False, True])
def test_write_step_batched_one_token_writes(device_pool):
    """`write_step` lands row i of a padded [b_pad, *kv] batch at
    entry i's slot; padding rows are dropped (device: scattered out of
    range), and shared blocks privatize first."""
    mgr = KVCacheManager(num_blocks=8, block_size=4, kv_shape=KV,
                         device_pool=device_pool)
    assert mgr.allocate("a", 3) and mgr.allocate("b", 6)
    base = np.zeros((6,) + KV, np.float32)
    mgr.write_range("a", 0, base[:2])
    mgr.write_range("b", 0, base)
    batch = np.zeros((4,) + KV, np.float32)     # b_pad=4, 2 live rows
    batch[0] = 11.0
    batch[1] = 22.0
    batch[2:] = 99.0                            # must never land
    mgr.write_step([("a", 2), ("b", 5)], batch)
    assert mgr.seq_len("a") == 3 and mgr.seq_len("b") == 6
    np.testing.assert_array_equal(np.asarray(mgr.gather("a"))[2],
                                  batch[0])
    np.testing.assert_array_equal(np.asarray(mgr.gather("b"))[5],
                                  batch[1])
    assert not np.any(np.asarray(mgr.gather("b"))[:5] == 99.0)


def test_paged_step_resolves_slots_and_rebinds_pool():
    """`paged_step` hands the model's fused step private (block, off)
    slots (COW backstop included), re-binds the donated pool it
    returns, and advances lens — the whole decode write path in one
    call."""
    mgr = KVCacheManager(num_blocks=8, block_size=4, kv_shape=KV,
                         device_pool=True)
    assert mgr.allocate("a", 4)
    vals = np.ones((4,) + KV, np.float32)
    mgr.write_range("a", 0, vals)
    shared = mgr.block_table("a")[0]
    mgr.adopt("b", [shared], 4)
    assert mgr.allocate("b", 5)                 # room for the step

    seen = {}

    def fused(pool, blocks, offs):
        # stand-in for the model's donated jit: write one row eagerly
        seen["slots"] = (list(blocks), list(offs))
        new = pool.at[blocks[0], offs[0]].set(5.0)
        return "logits", new

    out = mgr.paged_step([("b", 4)], fused)
    assert out == "logits"
    assert mgr.seq_len("b") == 5
    # The written slot was private: COW split "b" off the shared block
    # chain only if the target block was shared (pos 4 lives in b's
    # second block, freshly allocated, so no copy needed here).
    blk, off = seen["slots"][0][0], seen["slots"][1][0]
    assert (blk, off) == (mgr.block_table("b")[1], 0)
    got = np.asarray(mgr.gather("b"))
    assert got[4].flat[0] == 5.0
    np.testing.assert_array_equal(got[:4], vals)   # adopted head intact


def test_with_pool_is_reentrant():
    """`with_pool` callbacks may call public accessors (the scheduler's
    paged prefill reads tables while holding the pool) — the cache lock
    is reentrant."""
    mgr = KVCacheManager(num_blocks=4, block_size=4, kv_shape=KV,
                         device_pool=True)
    assert mgr.allocate("s", 2)
    table = mgr.with_pool(lambda pool: mgr.block_table("s"))
    assert table == mgr.block_table("s")


# ---------------------------------------------------------------------------
# TinyLM: oracle-exact through the paged engine
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("device_pool", [False, True])
def test_tinylm_paged_engine_matches_oracle(device_pool):
    """Paged decode (both pool residencies) reproduces TinyLM.oracle
    token-for-token, with zero host gathers."""
    m = TinyLM(vocab_size=32)
    eng = InferenceEngine(m, EngineConfig(
        max_batch_size=4, block_size=4, num_blocks=64,
        paged_decode=True, device_pool=device_pool))
    prompts = [[1 + (i * 3 + j) % 20 for j in range(3 + i % 5)]
               for i in range(6)]
    streams = [eng.submit(p, 8) for p in prompts]
    _drive(eng)
    for p, s in zip(prompts, streams):
        assert s.tokens_so_far() == m.oracle(p, 8)
    st = eng.stats()
    assert st["paged"] and st["paged_steps"] > 0
    assert st["cache"]["host_gathers"] == 0
    assert st["cache"]["pool_residency"] == (
        "device" if device_pool else "host")


def test_tinylm_paged_survives_preemption_and_adoption():
    """Tight cache forces preempt-requeue mid-generation and prefix
    sharing adopts blocks by reference — the paged read must still be
    oracle-exact afterwards (stale pool rows from freed blocks never
    leak through the block tables)."""
    m = TinyLM(vocab_size=32)
    eng = InferenceEngine(m, EngineConfig(
        max_batch_size=4, block_size=4, num_blocks=8,
        paged_decode=True, device_pool=True, prefix_sharing=True))
    base = [2, 4, 6, 8]
    prompts = [base + [10 + i] for i in range(4)]
    streams = [eng.submit(p, 6) for p in prompts]
    _drive(eng)
    for p, s in zip(prompts, streams):
        assert s.tokens_so_far() == m.oracle(p, 6)
    assert eng.preemptions > 0          # the tight cache actually bit
    assert eng.cache.host_gathers == 0


# ---------------------------------------------------------------------------
# transformer: paged == host-gather == full recompute
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tiny_transformer():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import TransformerConfig, init_params

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_layers=2,
                            n_heads=2, d_ff=64, max_seq_len=128,
                            dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return params, cfg


def _transformer_engine(tiny_transformer, **cfg_kw):
    from ray_tpu.serve.engine import TransformerEngineModel

    params, cfg = tiny_transformer
    model = TransformerEngineModel(params, cfg, max_batch_size=4)
    return model, InferenceEngine(model, EngineConfig(
        max_batch_size=4, block_size=8, num_blocks=24, **cfg_kw))


def test_transformer_paged_matches_host_and_full_recompute(
        tiny_transformer):
    """The fused paged engine (device pool, in-jit gather, in-place
    scatter) emits token-for-token what the host-gather engine emits —
    and both match greedy full-forward recompute."""
    import jax.numpy as jnp

    from ray_tpu.models.transformer import forward

    params, cfg = tiny_transformer
    prompts = [[3, 17, 42, 9, 21, 5], [7, 7], [11, 23, 4, 50, 8, 9, 13]]
    outs = []
    for paged in (False, True):
        _, eng = _transformer_engine(tiny_transformer,
                                     paged_decode=paged)
        streams = [eng.submit(p, 6) for p in prompts]
        _drive(eng)
        outs.append([s.tokens_so_far() for s in streams])
        if paged:
            assert eng.paged_steps > 0
            assert eng.cache.host_gathers == 0
            assert eng.cache.pool_residency == "device"
    assert outs[0] == outs[1]
    for p, toks in zip(prompts, outs[1]):
        seq, oracle = list(p), []
        for _ in range(6):
            lg, _ = forward(params, jnp.asarray([seq], jnp.int32), cfg)
            t = int(np.argmax(np.asarray(lg)[0, -1]))
            oracle.append(t)
            if t == 1:          # engine eos_token
                break
            seq.append(t)
        assert toks == oracle


def test_transformer_sharing_paged_matches_unshared(tiny_transformer):
    """Adoption + paged prefill-from-pool + COW over the real
    transformer: sharing on (paged) == sharing off (paged) — the
    in-jit prefix gather reads exactly what the prefill wrote."""
    base = [3, 17, 42, 9, 21, 5, 11, 2]         # seals one 8-block
    reqs = [(base + [33], 4), (base + [40], 4), (base + [33], 4)]
    outs = []
    for sharing in (False, True):
        _, eng = _transformer_engine(tiny_transformer,
                                     paged_decode=True,
                                     prefix_sharing=sharing)
        streams = []
        for p, n in reqs:       # staged: block seals before next admit
            streams.append(eng.submit(p, n))
            _drive(eng)
        outs.append([s.tokens_so_far() for s in streams])
        assert eng.cache.host_gathers == 0
        if sharing:
            assert eng.prefix_hit_tokens >= 16
    assert outs[0] == outs[1]


def test_transformer_ship_then_paged_decode_parity(tiny_transformer):
    """Cross-engine prefix shipping into a device pool: blocks exported
    from one paged engine and installed into another's jnp pool
    (`read_block`/`install_block` crossing residency) decode to the
    same tokens as computing locally."""
    base = [3, 17, 42, 9, 21, 5, 11, 2]
    tail = [33, 40]
    _, src = _transformer_engine(tiny_transformer, paged_decode=True,
                                 prefix_sharing=True)
    src.submit(base + tail, 4)
    _drive(src)
    chunks, kvs = src.export_prefix(base)
    assert chunks and len(kvs) == len(chunks)

    _, dst = _transformer_engine(tiny_transformer, paged_decode=True,
                                 prefix_sharing=True)
    assert dst.import_prefix(chunks, kvs) == len(base)
    s_dst = dst.submit(base + tail, 4)
    _drive(dst)
    assert dst.prefix_hit_tokens >= len(base)   # adoption engaged

    _, ref = _transformer_engine(tiny_transformer, paged_decode=True)
    s_ref = ref.submit(base + tail, 4)
    _drive(ref)
    assert s_dst.tokens_so_far() == s_ref.tokens_so_far()


# ---------------------------------------------------------------------------
# jit bucket caches + stats surface
# ---------------------------------------------------------------------------
def test_jit_lru_caps_buckets_and_counts_evictions():
    from ray_tpu.serve.engine.model import _JitLRU

    lru = _JitLRU(2)
    lru[1] = "a"
    lru[2] = "b"
    assert lru.get(1) == "a"        # refreshes 1
    lru[3] = "c"                    # evicts 2 (LRU)
    assert len(lru) == 2 and lru.evictions == 1
    assert lru.get(2) is None and lru.get(1) == "a"


def test_transformer_jit_cache_cap_evicts_and_reports(tiny_transformer):
    """A tiny cap forces compiled-bucket evictions under varied shapes;
    the model reports them (`jit_cache_evictions`) and the engine
    surfaces the sum in stats for the counter metric."""
    from ray_tpu.serve.engine import TransformerEngineModel

    params, cfg = tiny_transformer
    model = TransformerEngineModel(params, cfg, max_batch_size=4,
                                   jit_cache_cap=1)
    eng = InferenceEngine(model, EngineConfig(
        max_batch_size=2, block_size=8, num_blocks=24))
    for p, n in (([3], 3), ([4, 5] * 5, 4), ([6] * 20, 5)):
        eng.submit(p, n)
    _drive(eng)
    assert model.jit_cache_evictions > 0
    assert eng.stats()["jit_bucket_evictions"] == \
        model.jit_cache_evictions


def test_engine_stats_surface_pool_and_phase_fields():
    m = TinyLM(vocab_size=32)
    eng = InferenceEngine(m, EngineConfig(
        max_batch_size=2, block_size=4, num_blocks=16,
        paged_decode=True))
    eng.submit([2, 3, 4], 4)
    _drive(eng)
    st = eng.stats()
    assert st["paged"] is True
    assert st["paged_steps"] > 0
    cache = st["cache"]
    assert cache["pool_residency"] == "device"
    assert cache["pool_bytes"] > 0
    assert cache["host_gathers"] == 0
    assert cache["pool_updates"] > 0
    for key in ("kv_gather_s", "model_step_s", "kv_write_s",
                "jit_bucket_evictions"):
        assert key in st
