"""Control-plane survival at 100 nodes — the simulated-raylet harness.

ISSUE 14 acceptance: a 100-node simulated cluster survives a seeded
fault schedule (GCS kill -9 + 10% raylet crashes + 1% message drops)
with zero lost tasks, zero leaked placement-group reservations, and
full re-registration after restart; the same seed reproduces the
identical fault schedule.

Everything here runs real control-plane code — `GcsServer` handlers,
`NodeLedger` 2PC, `schedule_placement_group`, the heartbeat/re-register
contract — over in-process loopback dispatch (`core/simcluster.py`),
in one pytest process, in seconds.
"""

import asyncio
import os

import pytest

pytestmark = pytest.mark.unit


def _run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# ---------------------------------------------------------------------------
# fault plan determinism
# ---------------------------------------------------------------------------

def test_fault_schedule_is_a_pure_function_of_the_seed():
    from ray_tpu.core.faults import FaultPlan

    def build(seed):
        p = FaultPlan(seed)
        p.drop(p=0.05)
        p.delay(method="heartbeat", p=0.1, delay_s=0.002)
        p.duplicate(method="request_sim_lease", p=0.1)
        return p

    a, b = build(17), build(17)
    sched_a = a.preview("driver", "simnode0001", "request_sim_lease", 500)
    sched_b = b.preview("driver", "simnode0001", "request_sim_lease", 500)
    assert [x.key() for x in sched_a] == [x.key() for x in sched_b]
    assert sched_a, "a 5%+10% plan over 500 messages must fault sometimes"

    # A different seed yields a different schedule...
    c = build(18)
    sched_c = c.preview("driver", "simnode0001", "request_sim_lease", 500)
    assert [x.key() for x in sched_a] != [x.key() for x in sched_c]
    # ...and decisions are edge-local: another edge differs too.
    sched_d = a.preview("driver", "simnode0002", "request_sim_lease", 500)
    assert [x.key() for x in sched_a] != [x.key() for x in sched_d]


def test_fault_plan_drop_delay_duplicate_partition_semantics():
    from ray_tpu.core.faults import FaultInjected, FaultPlan
    from ray_tpu.core.rpc import ConnectionLost

    async def scenario():
        plan = FaultPlan(seed=3)
        cut = plan.partition("a", "b")
        with pytest.raises(ConnectionLost):
            await plan.apply("a", "b", "ping")        # one-way: a->b cut
        assert not await plan.apply("b", "a", "ping")  # reverse flows
        plan.heal(cut)
        assert not await plan.apply("a", "b", "ping")

        dup = FaultPlan(seed=3)
        dup.duplicate(p=1.0)
        assert await dup.apply("a", "b", "x") is True

        crash = FaultPlan(seed=3)
        crashed = []
        crash.crash_after("b", 3, on_crash=crashed.append)
        await crash.apply("a", "b", "m")
        await crash.apply("c", "b", "m")
        with pytest.raises(FaultInjected):
            await crash.apply("a", "b", "m")  # b's 3rd received message
        assert crashed == ["b"]
        # the rule fires once
        assert not await crash.apply("a", "b", "m")

    _run(scenario())


def test_faults_hook_into_real_rpc_dispatch():
    """The rpc.py server hook: a drop rule swallows the request (caller
    sees no reply), a duplicate rule dispatches the handler twice."""
    from ray_tpu.core import faults
    from ray_tpu.core.rpc_testing import LoopbackClient

    class Handlers:
        def __init__(self):
            self.calls = 0

        async def handle_bump(self, conn):
            self.calls += 1
            return self.calls

    async def scenario():
        h = Handlers()
        client = LoopbackClient(h)
        await client.connect()
        plan = faults.FaultPlan(seed=0)
        plan.duplicate(method="bump", p=1.0, end=1)   # first call only
        plan.drop(method="bump", p=1.0, start=1, end=2)  # second call
        faults.install(plan)
        try:
            # The genuine dispatch answers; the duplicate redelivery
            # runs concurrently with its reply discarded.
            assert await client.call("bump") == 1
            for _ in range(5):                      # let the dup land
                await asyncio.sleep(0)
            assert h.calls == 2
            with pytest.raises(Exception):
                await client.call("bump")           # dropped: no reply
            assert h.calls == 2
            assert await client.call("bump") == 3   # clean again
        finally:
            faults.uninstall()

    _run(scenario())


# ---------------------------------------------------------------------------
# gcs client backoff
# ---------------------------------------------------------------------------

def test_reconnect_backoff_full_jitter_bounds():
    import random

    from ray_tpu.core.config import ray_config
    from ray_tpu.core.gcs.client import backoff_delay

    cfg = ray_config()
    saved = dict(cfg._values)
    cfg.apply_system_config({"gcs_reconnect_backoff_base_ms": 100.0,
                             "gcs_reconnect_backoff_max_ms": 1000.0})
    try:
        rng = random.Random(0)
        for attempt in range(20):
            ceiling = min(1.0, 0.1 * 2 ** attempt)
            for _ in range(50):
                d = backoff_delay(attempt, rng)
                assert 0.0 <= d <= ceiling + 1e-9
        # FULL jitter: the low end of the range is actually used (a
        # "equal jitter" regression would floor at ceiling/2).
        lows = sum(backoff_delay(6, rng) < 0.5 for _ in range(200))
        assert lows > 40
    finally:
        cfg._values.clear()
        cfg._values.update(saved)


def test_reconnecting_rpc_sleeps_with_jitter(monkeypatch):
    """_ReconnectingRpc._reconnect consults backoff_delay instead of the
    old fixed 0.5 s sleep — pinned by substituting both the sleep and
    the dial so no socket is ever opened."""
    from ray_tpu.core.config import ray_config
    from ray_tpu.core.gcs import client as gcs_client
    from ray_tpu.core.rpc import ConnectionLost

    cfg = ray_config()
    saved = dict(cfg._values)
    cfg.apply_system_config({"gcs_rpc_timeout_s": 0.4,
                             "gcs_reconnect_backoff_base_ms": 40.0,
                             "gcs_reconnect_backoff_max_ms": 120.0})

    sleeps = []

    async def fake_sleep(d):
        sleeps.append(d)

    class DeadClient:
        def __init__(self, address):
            self.connected = False

        async def connect(self, timeout=10.0):
            raise OSError("connection refused")

        async def close(self):
            pass

    async def scenario():
        rpc = gcs_client._ReconnectingRpc("127.0.0.1:1")
        rpc._client = DeadClient("127.0.0.1:1")
        rpc._reconnect_lock = asyncio.Lock()
        monkeypatch.setattr(gcs_client, "RpcClient", DeadClient)
        monkeypatch.setattr(gcs_client.asyncio, "sleep", fake_sleep)
        with pytest.raises(ConnectionLost):
            await rpc._reconnect()

    try:
        _run(scenario())
    finally:
        cfg._values.clear()
        cfg._values.update(saved)
    # fake_sleep never advances the loop clock, so the window closes on
    # wall time spent dialing; at least a few attempts must have slept,
    # each within the jitter ceiling and not all identical (jitter).
    assert len(sleeps) >= 2
    assert all(0.0 <= s <= 0.12 + 1e-9 for s in sleeps)
    assert len(set(sleeps)) > 1


# ---------------------------------------------------------------------------
# scale: registration, heartbeats, scheduling
# ---------------------------------------------------------------------------

def test_100_nodes_register_heartbeat_and_schedule(tmp_path):
    from ray_tpu.core.simcluster import SimCluster

    async def scenario():
        cluster = SimCluster(num_nodes=100, seed=5)
        await cluster.start()
        try:
            assert await cluster.wait_until(
                lambda: cluster.registered_count() == 100, timeout=15)
            # Placement at scale, all four strategies on the real
            # select_pg_nodes + 2PC.
            for strategy in ("PACK", "SPREAD", "STRICT_PACK",
                             "STRICT_SPREAD"):
                pg_id, state = await cluster.driver.create_placement_group(
                    [{"CPU": 1.0}] * 4, strategy=strategy)
                assert state == "CREATED", (strategy, state)
            # Tasks spread across the fleet.
            results = await asyncio.gather(
                *(cluster.driver.submit_task() for _ in range(200)))
            assert all(results)
            assert not cluster.driver.lost
            grants = sum(r.lease_grants
                         for r in cluster.raylets.values())
            assert grants >= 200
        finally:
            await cluster.stop()

    _run(scenario())


def test_pg_rolls_back_when_a_raylet_dies_mid_reserve(tmp_path):
    """A raylet crash between prepare and commit must roll back the
    partial reservations on every OTHER node — the capacity-leak class
    the 2PC exists to prevent."""
    from ray_tpu.core.faults import FaultPlan
    from ray_tpu.core.simcluster import SimCluster

    async def scenario():
        plan = FaultPlan(seed=11)
        # The victim dies when its first prepare_bundle arrives: with
        # STRICT_SPREAD over 4 bundles, up to 3 other nodes already
        # hold a prepared reservation at that instant.
        plan.crash_after("simnode0000", 1, method="prepare_bundle")
        cluster = SimCluster(num_nodes=8, seed=11, plan=plan,
                             resources={"CPU": 2.0})
        await cluster.start()
        try:
            assert await cluster.wait_until(
                lambda: cluster.registered_count() == 8, timeout=10)
            # Force the victim into every placement: all 8 nodes needed.
            pg_id, state = await cluster.driver.create_placement_group(
                [{"CPU": 2.0}] * 8, strategy="STRICT_SPREAD", attempts=2)
            # 7 nodes can't hold 8 STRICT_SPREAD bundles.
            assert state == "INFEASIBLE"
            assert await cluster.wait_until(
                lambda: not cluster.leaked_reservations()
                and not cluster.resource_violations(), timeout=10), (
                cluster.leaked_reservations(),
                cluster.resource_violations())
        finally:
            await cluster.stop()

    _run(scenario())


def test_gcs_restart_grace_no_false_deaths_then_real_deaths(tmp_path):
    """After a GCS kill -9 + restart, recovered nodes are NOT declared
    dead inside the grace window (no false node-death storm), but a
    node that truly died during the outage IS declared dead once the
    grace passes."""
    from ray_tpu.core.simcluster import SimCluster

    async def scenario():
        path = os.path.join(tmp_path, "gcs.pkl")
        cluster = SimCluster(num_nodes=30, seed=2, storage_path=path)
        await cluster.start()
        try:
            assert await cluster.wait_until(
                lambda: cluster.registered_count() == 30, timeout=10)
            # Let the 1 Hz debounce persist the node table.
            await asyncio.sleep(1.2)
            cluster.kill_gcs()
            cluster.crash_raylet("simnode0005")  # dies during the outage
            await asyncio.sleep(0.5)
            await cluster.restart_gcs()
            # Recovery: the persisted membership table is live
            # immediately, stale-marked, inside the grace window.
            recovered = [n for n in cluster.gcs.nodes.values()
                         if n.get("alive")]
            assert len(recovered) == 30
            assert all(n.get("stale_view") for n in recovered)
            # Survivors reconcile via their first heartbeat (no
            # re-register storm: was_dead never fires), the real death
            # is detected after the grace.
            assert await cluster.wait_until(
                lambda: cluster.registered_count() == 29, timeout=10)
            survivors = [n for n in cluster.gcs.nodes.values()
                         if n.get("alive")]
            assert not any(n.get("stale_view") for n in survivors)
            dead = cluster.gcs.nodes["simnode0005"]
            assert not dead["alive"]
        finally:
            await cluster.stop()

    _run(scenario())


def test_committed_bundles_of_lost_groups_are_reconciled(tmp_path):
    """Owner dies between commit and the CREATED CAS: the group stays
    PENDING forever, and the raylet-side reconciler must return the
    committed reservations after pg_stuck_commit_s."""
    from ray_tpu.core.simcluster import SimCluster

    async def scenario():
        cluster = SimCluster(num_nodes=4, seed=9,
                             config={"pg_stuck_commit_s": 0.5})
        await cluster.start()
        try:
            assert await cluster.wait_until(
                lambda: cluster.registered_count() == 4, timeout=10)
            # Drive the 2PC by hand up to (and including) commit, then
            # "die" before the CAS.
            drv = cluster.driver
            pg_id = "simpgorphan"
            await drv._gcs.register_placement_group(pg_id, {
                "bundles": [{"CPU": 1.0}], "strategy": "PACK",
                "state": "PENDING", "owner": "driver",
                "target_node_ids": None})
            client = await drv.raylet_client_for("sim:simnode0000")
            r = await client.call("prepare_bundle", pg_id=pg_id,
                                  bundle_index=0, resources={"CPU": 1.0})
            assert r["ok"]
            assert await client.call("commit_bundle", pg_id=pg_id,
                                     bundle_index=0)
            victim = cluster.raylets["simnode0000"]
            assert any(b.committed for b in victim._bundles.values())
            # No CAS ever arrives. The reconciler returns the orphan.
            assert await cluster.wait_until(
                lambda: not victim._bundles, timeout=10)
            assert victim.resources_available == victim.resources_total
        finally:
            await cluster.stop()

    _run(scenario())


def test_schedule_pg_rolls_back_committed_bundles_when_cas_fails():
    """Review regression: an exception from the CREATED CAS must reach
    the attempt's rollback — an escaped one used to strand every
    committed bundle (invisible to the reconciler once a later attempt
    succeeded on other nodes). And a CAS whose ack was lost but whose
    write APPLIED must be recognized on re-read, not rolled back."""
    from ray_tpu.core.cluster_runtime import schedule_placement_group
    from ray_tpu.core.rpc import ConnectionLost

    class FakeRaylet:
        def __init__(self, log):
            self.log = log

        async def call(self, method, timeout=None, **kw):
            self.log.append((method, kw.get("bundle_index")))
            if method == "prepare_bundle":
                return {"ok": True}
            return True

    class FakeGcs:
        def __init__(self, cas_mode):
            self.state = "PENDING"
            self.cas_mode = cas_mode  # "raise" | "lost_ack"

        async def get_placement_group(self, pg_id):
            return {"state": self.state}

        async def get_nodes(self):
            return [{"node_id": "n1", "alive": True, "address": "a1",
                     "resources_available": {"CPU": 8.0}}]

        async def update_placement_group(self, pg_id, updates,
                                         expect_state=None):
            if updates.get("state") == "CREATED":
                if self.cas_mode == "raise":
                    raise ConnectionLost("gcs gone")
                # lost_ack: the write APPLIES but the reply is lost —
                # modeled as False now, CREATED visible on re-read.
                self.state = "CREATED"
                return False
            if expect_state is not None and self.state != expect_state:
                return False
            self.state = updates["state"]
            return True

    async def scenario():
        # Arm 1: CAS raises every time -> every committed bundle must be
        # returned, and the group ends INFEASIBLE.
        log = []
        gcs = FakeGcs("raise")

        async def client_for(addr):
            return FakeRaylet(log)

        info = {"bundles": [{"CPU": 1.0}] * 2, "strategy": "PACK",
                "target_node_ids": None}
        state = await schedule_placement_group(gcs, client_for, "pgx",
                                               info, attempts=2)
        assert state == "INFEASIBLE"
        commits = [i for m, i in log if m == "commit_bundle"]
        returns = [i for m, i in log if m == "return_bundle"]
        assert commits and sorted(returns) == sorted(commits), log

        # Arm 2: the CAS ack is lost but the write applied -> re-read
        # sees CREATED; no rollback, success reported.
        log2 = []
        gcs2 = FakeGcs("lost_ack")

        async def client_for2(addr):
            return FakeRaylet(log2)

        state = await schedule_placement_group(gcs2, client_for2, "pgy",
                                               info, attempts=2)
        assert state == "CREATED"
        assert not [m for m, _ in log2 if m == "return_bundle"], log2

    _run(scenario())


# ---------------------------------------------------------------------------
# data-plane recovery (round 15): lineage reconstruction + PG rescheduling
# ---------------------------------------------------------------------------

def test_pg_reschedules_onto_survivors_when_member_node_dies():
    """A CREATED group whose member node dies returns to CREATED on the
    survivors: the GCS CAS-transitions it to RESCHEDULING, re-places
    ONLY the lost bundle through the 2PC (surviving bundles keep their
    reservations — same nodes, untouched ledgers), and the terminal CAS
    lands the merged location table. Zero leaked reservations after,
    and the recovery is pinned in the flight ring (`pg.reschedule`)."""
    from ray_tpu.core import flight
    from ray_tpu.core.faults import FaultPlan
    from ray_tpu.core.simcluster import SimCluster

    async def scenario():
        plan = FaultPlan(seed=23)
        plan.drop(p=0.01)
        cluster = SimCluster(num_nodes=8, seed=23, plan=plan)
        await cluster.start()
        try:
            assert await cluster.wait_until(
                lambda: cluster.registered_count() == 8, timeout=10)
            pg_id, state = await cluster.driver.create_placement_group(
                [{"CPU": 1.0}] * 3, strategy="STRICT_SPREAD")
            assert state == "CREATED"
            info = await cluster.driver._gcs.get_placement_group(pg_id)
            locs = [loc["node_id"] for loc in info["bundle_locations"]]
            victim, survivors = locs[1], {locs[0], locs[2]}
            cluster.crash_raylet(victim)

            def rescheduled():
                pg = cluster.gcs.placement_groups.get(pg_id) or {}
                cur = [loc["node_id"]
                       for loc in pg.get("bundle_locations") or []]
                return (pg.get("state") == "CREATED" and cur
                        and victim not in cur)

            assert await cluster.wait_until(rescheduled, timeout=15), (
                cluster.gcs.placement_groups.get(pg_id))
            pg = cluster.gcs.placement_groups[pg_id]
            cur = [loc["node_id"] for loc in pg["bundle_locations"]]
            # Survivors kept their exact placements; only the lost
            # bundle moved, onto a live node not already holding one
            # (STRICT_SPREAD).
            assert cur[0] == locs[0] and cur[2] == locs[2]
            assert cur[1] not in survivors and cur[1] != victim
            assert cluster.raylets[cur[1]].alive
            assert await cluster.wait_until(
                lambda: not cluster.leaked_reservations(), timeout=10), (
                cluster.leaked_reservations())
            # Surviving reservations really are untouched ledgers.
            for idx in (0, 2):
                node = cluster.raylets[cur[idx]]
                assert any(k.startswith(pg_id + ":")
                           for k in node._bundles), cur[idx]
            events = flight.dump(include_events=True)["events"]
            assert any(e[3] == "pg.reschedule" for e in events)
        finally:
            await cluster.stop()

    _run(scenario())


def test_borrower_get_survives_holder_node_death():
    """THE data-plane acceptance core: a borrower's get() of an object
    whose holder node died returns the correct value via lineage
    re-execution — no user-visible error — including RECURSIVE
    reconstruction of a dependency lost with its own node. The
    re-execution is pinned in the flight ring (`lineage.reexec`)."""
    from ray_tpu.core import flight
    from ray_tpu.core.faults import FaultPlan
    from ray_tpu.core.simcluster import SimCluster

    async def scenario():
        plan = FaultPlan(seed=31)
        plan.drop(p=0.01)
        cluster = SimCluster(num_nodes=8, seed=31, plan=plan)
        await cluster.start()
        try:
            assert await cluster.wait_until(
                lambda: cluster.registered_count() == 8, timeout=10)
            drv = cluster.driver
            borrower = cluster.add_driver("borrower")
            base = await drv.create_object("base")
            mid = await drv.create_object("mid", deps=[base])
            assert (await borrower.get_object(mid, owner="driver")
                    == "mid(base())")
            assert drv.exec_counts == {"base": 1, "mid": 1}
            # Kill every node holding a copy: the directory-listed
            # holders AND the borrower's local raylet (its store cached
            # the pulled copy — "the node holding the borrowed object").
            holders = (set(drv._objects[base]["nodes"])
                       | set(drv._objects[mid]["nodes"])
                       | {borrower.node, drv.node} - {None})
            for h in holders:
                cluster.crash_raylet(h)
            # Borrower blocks-and-retries through the re-execution and
            # lands the SAME deterministic value.
            assert (await borrower.get_object(mid, owner="driver",
                                              timeout=20)
                    == "mid(base())")
            assert drv.exec_counts["mid"] == 2
            if len(holders) > 1:
                # base's holder died too: mid's re-execution re-resolved
                # it, which reconstructed base first (recursive).
                assert drv.exec_counts["base"] == 2
            assert drv.lineage.stats()["reexecs"] >= 1
            events = flight.dump(include_events=True)["events"]
            assert any(e[3] == "lineage.reexec" for e in events)
        finally:
            await cluster.stop()

    _run(scenario())


def test_health_loop_rescues_created_group_on_silently_dead_node():
    """Review race: a node that dies while its group is mid-reschedule
    is skipped by _mark_node_dead's CREATED-only scan, so the pass can
    land CREATED with a location naming the fresh corpse. The health
    loop's CREATED-vs-live-node-table scan is the safety net — pinned
    here by marking the node dead WITHOUT the _mark_node_dead trigger
    (its alive guard then makes the scan the only recovery path)."""
    from ray_tpu.core.simcluster import SimCluster

    async def scenario():
        cluster = SimCluster(num_nodes=6, seed=19)
        await cluster.start()
        try:
            assert await cluster.wait_until(
                lambda: cluster.registered_count() == 6, timeout=10)
            pg_id, state = await cluster.driver.create_placement_group(
                [{"CPU": 1.0}] * 2, strategy="STRICT_SPREAD")
            assert state == "CREATED"
            info = cluster.gcs.placement_groups[pg_id]
            victim = info["bundle_locations"][0]["node_id"]
            # The exact post-race state: table says dead, group says
            # CREATED-on-victim, no death event ever fired for it.
            cluster.gcs.nodes[victim]["alive"] = False
            cluster.crash_raylet(victim)

            def rescued():
                pg = cluster.gcs.placement_groups.get(pg_id) or {}
                locs = [loc["node_id"]
                        for loc in pg.get("bundle_locations") or []]
                return (pg.get("state") == "CREATED" and locs
                        and victim not in locs)

            assert await cluster.wait_until(rescued, timeout=15), (
                cluster.gcs.placement_groups.get(pg_id))
            assert await cluster.wait_until(
                lambda: not cluster.leaked_reservations(), timeout=10)
        finally:
            await cluster.stop()

    _run(scenario())


def test_reconstruction_degrades_to_typed_errors():
    """Exhausted budget and disabled retention keep today's typed
    failures: max_retries=0 (or lineage_reconstruction=False) objects
    are final — the borrower's get raises ObjectLostError, never hangs
    and never silently recomputes."""
    from ray_tpu.core.config import ray_config
    from ray_tpu.core.simcluster import SimCluster
    from ray_tpu.exceptions import ObjectLostError

    async def scenario():
        cluster = SimCluster(num_nodes=4, seed=5)
        await cluster.start()
        try:
            assert await cluster.wait_until(
                lambda: cluster.registered_count() == 4, timeout=10)
            drv = cluster.driver
            borrower = cluster.add_driver("borrower")
            # Arm 1: budget 0 -> loss is final.
            frozen = await drv.create_object("frozen", max_retries=0)
            for h in list(drv._objects[frozen]["nodes"]):
                cluster.crash_raylet(h)
            with pytest.raises(ObjectLostError):
                await borrower.get_object(frozen, owner="driver",
                                          timeout=8)
            # Arm 2: flag off -> nothing is retained at all.
            ray_config().apply_system_config(
                {"lineage_reconstruction": False})
            try:
                off = await drv.create_object("off", max_retries=5)
                assert drv.lineage.get(off) is None  # no retention
                for h in list(drv._objects[off]["nodes"]):
                    cluster.crash_raylet(h)
                with pytest.raises(ObjectLostError):
                    await borrower.get_object(off, owner="driver",
                                              timeout=8)
            finally:
                ray_config().apply_system_config(
                    {"lineage_reconstruction": True})
            assert drv.exec_counts == {"frozen": 1, "off": 1}
        finally:
            await cluster.stop()

    _run(scenario())


def test_reconstruction_budget_is_capped_and_spent():
    """The per-object re-execution budget is real: each loss spends one
    re-execution; when it runs out the next loss surfaces
    ObjectLostError. The global lineage_reconstruction_budget caps
    whatever max_retries asked for."""
    from ray_tpu.core.config import ray_config
    from ray_tpu.core.simcluster import SimCluster
    from ray_tpu.exceptions import ObjectLostError

    async def scenario():
        cluster = SimCluster(num_nodes=4, seed=13)
        await cluster.start()
        try:
            assert await cluster.wait_until(
                lambda: cluster.registered_count() == 4, timeout=10)
            drv = cluster.driver
            oid = await drv.create_object("bounded", max_retries=2)
            for round_ in range(2):
                assert cluster.evict_sim_object(oid) >= 1, round_
                assert (await drv.get_object(oid, timeout=20)
                        == "bounded()"), round_
            assert drv.exec_counts["bounded"] == 3  # 1 + 2 re-execs
            assert cluster.evict_sim_object(oid) >= 1
            with pytest.raises(ObjectLostError):
                await drv.get_object(oid, timeout=8)
            # The cap clamps extravagant budgets.
            saved = ray_config().lineage_reconstruction_budget
            ray_config().apply_system_config(
                {"lineage_reconstruction_budget": 1})
            try:
                rec = drv.lineage.retain(["simobj-x"], {"name": "x"},
                                         [], 999)
                assert rec["left"] == 1
            finally:
                ray_config().apply_system_config(
                    {"lineage_reconstruction_budget": saved})
        finally:
            await cluster.stop()

    _run(scenario())


# ---------------------------------------------------------------------------
# THE acceptance scenario
# ---------------------------------------------------------------------------

def _acceptance_run(tmp_path, run_idx):
    """100 nodes; seeded schedule = GCS kill -9 mid-run + 10% raylet
    crashes + 1% message drops; workload = tasks + placement groups.
    Returns (completed, lost, leak, violations, registered, schedule)."""
    from ray_tpu.core.faults import FaultPlan
    from ray_tpu.core.simcluster import SimCluster

    SEED = 1914
    N = 100

    async def scenario():
        path = os.path.join(tmp_path, f"gcs-{run_idx}.pkl")
        plan = FaultPlan(seed=SEED)
        plan.drop(p=0.01)                      # 1% drops, every edge
        rng_victims = [f"simnode{i:04d}" for i in
                       __import__("random").Random(SEED).sample(
                           range(N), 10)]      # 10% of the fleet
        cluster = SimCluster(num_nodes=N, seed=SEED, storage_path=path,
                             plan=plan)
        await cluster.start()
        try:
            assert await cluster.wait_until(
                lambda: cluster.registered_count() == N, timeout=20)
            await asyncio.sleep(1.2)  # persist the membership table

            async def tasks():
                return await asyncio.gather(
                    *(cluster.driver.submit_task(hold_s=0.005)
                      for _ in range(300)))

            async def pgs():
                out = []
                for _ in range(6):
                    out.append(await cluster.driver
                               .create_placement_group([{"CPU": 1.0}] * 4))
                return out

            t_work = asyncio.ensure_future(tasks())
            t_pgs = asyncio.ensure_future(pgs())
            await asyncio.sleep(0.3)
            # The seeded chaos: kill the control plane, crash 10 nodes.
            cluster.kill_gcs()
            for v in rng_victims:
                cluster.crash_raylet(v)
            await asyncio.sleep(0.6)
            await cluster.restart_gcs()

            results = await t_work
            created = await t_pgs
            # zero lost tasks
            assert all(results), f"{results.count(False)} tasks lost"
            assert not cluster.driver.lost
            # full re-registration: every survivor is alive in the
            # recovered table, every victim is declared dead
            assert await cluster.wait_until(
                lambda: cluster.registered_count() == N - 10, timeout=20)
            # groups terminated cleanly; remove them all, then zero
            # leaked reservations cluster-wide
            for pg_id, state in created:
                assert state in ("CREATED", "INFEASIBLE"), state
                await cluster.driver.remove_placement_group(pg_id)
            assert await cluster.wait_until(
                lambda: not cluster.leaked_reservations()
                and not cluster.resource_violations(), timeout=15), (
                cluster.leaked_reservations(),
                cluster.resource_violations())
            # The replayable schedule: pure per-edge previews.
            schedule = plan.preview("driver", "simnode0001",
                                    "request_sim_lease", 200)
            return (len(cluster.driver.completed),
                    [x.key() for x in schedule])
        finally:
            await cluster.stop()

    return _run(scenario(), timeout=180)


def test_acceptance_100_nodes_survive_seeded_fault_schedule(tmp_path):
    completed_a, schedule_a = _acceptance_run(tmp_path, 0)
    assert completed_a == 300
    # Re-running the same seed reproduces the identical fault schedule.
    completed_b, schedule_b = _acceptance_run(tmp_path, 1)
    assert completed_b == 300
    assert schedule_a == schedule_b


def _data_plane_acceptance_run(run_idx):
    """Round-15 acceptance: mid-run, kill the node holding a borrowed
    object AND a placement-group member node, under 1% seeded drops.
    The borrower's in-flight get() must return the reconstructed value
    (no user-visible error), the PG must return to CREATED on the
    survivors, and nothing may leak. Returns the observables a seed
    replay must reproduce exactly."""
    from ray_tpu.core.faults import FaultPlan
    from ray_tpu.core.simcluster import SimCluster

    SEED = 1915

    async def scenario():
        plan = FaultPlan(seed=SEED)
        plan.drop(p=0.01)
        cluster = SimCluster(num_nodes=12, seed=SEED, plan=plan)
        await cluster.start()
        try:
            assert await cluster.wait_until(
                lambda: cluster.registered_count() == 12, timeout=15)
            drv = cluster.driver
            borrower = cluster.add_driver("borrower")
            base = await drv.create_object("base")
            mid = await drv.create_object("mid", deps=[base])
            assert (await borrower.get_object(mid, owner="driver")
                    == "mid(base())")
            pg_id, state = await cluster.driver.create_placement_group(
                [{"CPU": 1.0}] * 3, strategy="STRICT_SPREAD")
            assert state == "CREATED"
            info = await drv._gcs.get_placement_group(pg_id)
            pg_victim = info["bundle_locations"][0]["node_id"]

            # Mid-run: the borrower has a get in flight while the node
            # holding its borrowed object, both producers' stores, and
            # a PG member all die.
            get_inflight = asyncio.ensure_future(
                borrower.get_object(mid, owner="driver", timeout=30))
            await asyncio.sleep(0.01)
            victims = ({pg_victim, borrower.node, drv.node}
                       | set(drv._objects[base]["nodes"])
                       | set(drv._objects[mid]["nodes"])) - {None}
            for v in victims:
                cluster.crash_raylet(v)

            # The in-flight get lands the correct value whether it beat
            # the crash (cached copy) or blocked-and-retried through
            # the re-execution — never a user-visible error.
            assert await get_inflight == "mid(base())"
            # A post-crash get from the re-homed borrower cannot be
            # served by any surviving copy: it MUST reconstruct.
            value = await borrower.get_object(mid, owner="driver",
                                              timeout=30)
            assert value == "mid(base())", value
            assert drv.lineage.stats()["reexecs"] >= 1
            assert drv.exec_counts["mid"] >= 2

            def pg_recovered():
                pg = cluster.gcs.placement_groups.get(pg_id) or {}
                locs = [loc["node_id"]
                        for loc in pg.get("bundle_locations") or []]
                return (pg.get("state") == "CREATED" and locs
                        and all(cluster.raylets[n].alive for n in locs))

            assert await cluster.wait_until(pg_recovered, timeout=20), (
                cluster.gcs.placement_groups.get(pg_id))
            assert await cluster.wait_until(
                lambda: not cluster.leaked_reservations()
                and not cluster.resource_violations(), timeout=15), (
                cluster.leaked_reservations(),
                cluster.resource_violations())
            pg = cluster.gcs.placement_groups[pg_id]
            schedule = plan.preview("borrower", "simnode0000",
                                    "pull_sim_object", 50)
            return (value, pg["state"], len(cluster.leaked_reservations()),
                    [x.key() for x in schedule])
        finally:
            await cluster.stop()

    return _run(scenario(), timeout=120)


def test_acceptance_data_plane_recovery_and_seed_replay():
    value_a, pg_state_a, leaks_a, sched_a = _data_plane_acceptance_run(0)
    assert (value_a, pg_state_a, leaks_a) == ("mid(base())", "CREATED", 0)
    # Identical outcome on seed replay: same reconstructed value, same
    # recovered PG state, zero leaks both times, identical fault
    # schedule.
    value_b, pg_state_b, leaks_b, sched_b = _data_plane_acceptance_run(1)
    assert (value_a, pg_state_a, leaks_a, sched_a) == (
        value_b, pg_state_b, leaks_b, sched_b)


# ---------------------------------------------------------------------------
# scale: 1000 simulated nodes (ROADMAP 3d)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_1000_nodes_register_heartbeat_and_lease():
    """The sim harness holds at 1000 in-process raylets: full
    registration, a lease sweep through the real spillback policy, and
    a placement round — the GCS dispatch profile at this scale is
    recorded in PROFILE.md (round 11). Kept `-m slow`: ~1-2 min on a
    2-CPU box, dominated by 1000 heartbeat loops."""
    from ray_tpu.core.simcluster import SimCluster

    async def scenario():
        # Timers scale with N (PROFILE round 11): at the default sim
        # compression, 1000 heartbeat loops plus full-table view
        # refreshes saturate the loop, heartbeats fall behind the
        # 1.5 s health deadline, and the false-death/re-register storm
        # never converges. A real 1000-node deployment scales these
        # the same way.
        cluster = SimCluster(num_nodes=1000, seed=41, config={
            "raylet_heartbeat_period_ms": 1000,
            "cluster_view_refresh_ms": 10000,
            "health_check_period_ms": 2000,
            "health_check_failure_threshold": 10,
        })
        await cluster.start()
        try:
            assert await cluster.wait_until(
                lambda: cluster.registered_count() == 1000, timeout=120)
            results = await asyncio.gather(
                *(cluster.driver.submit_task() for _ in range(300)))
            assert all(results)
            assert not cluster.driver.lost
            pg_id, state = await cluster.driver.create_placement_group(
                [{"CPU": 1.0}] * 8, strategy="SPREAD")
            assert state == "CREATED"
            await cluster.driver.remove_placement_group(pg_id)
            assert await cluster.wait_until(
                lambda: not cluster.leaked_reservations(), timeout=30)
        finally:
            await cluster.stop()

    _run(scenario(), timeout=600)
