"""Chaos suite: workloads complete correctly while nodes die under them.

Reference coverage class: `release/nightly_tests/setup_chaos.py` +
`python/ray/tests/test_chaos.py` — randomized node kills during a live
workload; task retries and lineage reconstruction must deliver exact
results anyway.
"""

import time

import numpy as np
import pytest

pytestmark = pytest.mark.cluster


@pytest.fixture()
def chaos_cluster():
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    ray_tpu.init(address=cluster.address, ignore_reinit_error=True,
                 _system_config={"task_retry_delay_ms": 200})
    yield ray_tpu, cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def test_task_sweep_survives_node_kills(chaos_cluster):
    """60 idempotent tasks pinned to killable nodes; two nodes die
    mid-sweep (replacements join); every result must still be exact."""
    ray, cluster = chaos_cluster
    from ray_tpu.util.chaos import run_with_chaos

    node_args = {"num_cpus": 2, "resources": {"chaos": 2.0}}
    targets = [cluster.add_node(**node_args) for _ in range(3)]
    cluster.wait_for_nodes(4)

    @ray.remote(resources={"chaos": 0.5}, num_cpus=1, max_retries=16)
    def crunch(i):
        time.sleep(0.15)  # long enough for kills to land mid-flight
        return int(np.sum(np.arange(i + 1)))

    def workload():
        refs = [crunch.remote(i) for i in range(60)]
        return ray.get(refs, timeout=300)

    results, killed = run_with_chaos(
        cluster, workload, targets=targets, interval_s=2.0,
        max_kills=2, replace=True, node_args=node_args, seed=7)
    assert len(killed) >= 1, "chaos never fired — test proved nothing"
    expected = [i * (i + 1) // 2 for i in range(60)]
    assert results == expected


def test_lineage_chain_survives_chaos(chaos_cluster):
    """Large chained objects (stored, not inline) produced on killable
    nodes; getting the tail after kills forces recursive
    reconstruction."""
    ray, cluster = chaos_cluster
    from ray_tpu.util.chaos import NodeKiller

    node_args = {"num_cpus": 2, "resources": {"chaos": 2.0}}
    targets = [cluster.add_node(**node_args) for _ in range(2)]
    cluster.wait_for_nodes(3)

    @ray.remote(resources={"chaos": 0.5}, num_cpus=1, max_retries=16)
    def stage(x, bump):
        return x + np.full(300_000, float(bump))  # ~2.4MB per link

    @ray.remote(resources={"chaos": 0.5}, num_cpus=1, max_retries=16)
    def seed_block():
        return np.zeros(300_000)

    head = seed_block.remote()
    chain = head
    for bump in range(1, 5):
        chain = stage.remote(chain, bump)
    # Materialize the chain, then kill nodes and re-read: the copies die
    # with the nodes, so the get must reconstruct recursively.
    ray.wait([chain], timeout=120)

    killer = NodeKiller(cluster, interval_s=1.0, max_kills=2,
                        replace=True, node_args=node_args, seed=3)
    for t in targets:
        killer.add_target(t)
    killer.start()
    try:
        # Let chaos actually land before re-reading, else the get can
        # win the race and reconstruct nothing.
        deadline = time.time() + 30
        while not killer.killed and time.time() < deadline:
            time.sleep(0.2)
        value = ray.get(chain, timeout=300)
    finally:
        killer.stop()
    assert killer.killed, "no node was killed"
    assert float(value[0]) == 1 + 2 + 3 + 4
    assert value.shape == (300_000,)


def test_actor_pool_survives_chaos(chaos_cluster):
    """Restartable actors on killable nodes keep serving after their
    hosts die (fresh state, max_restarts honored)."""
    ray, cluster = chaos_cluster
    from ray_tpu.util.chaos import NodeKiller

    node_args = {"num_cpus": 2, "resources": {"chaos": 2.0}}
    targets = [cluster.add_node(**node_args) for _ in range(2)]
    cluster.wait_for_nodes(3)

    @ray.remote(resources={"chaos": 0.5}, num_cpus=1, max_restarts=8,
                max_task_retries=8)
    class Adder:
        def add(self, a, b):
            return a + b

    actors = [Adder.remote() for _ in range(4)]
    # Warm them up before chaos.
    assert ray.get([a.add.remote(1, 1) for a in actors], timeout=120) \
        == [2] * 4

    killer = NodeKiller(cluster, interval_s=1.5, max_kills=2,
                        replace=True, node_args=node_args, seed=11)
    for t in targets:
        killer.add_target(t)
    killer.start()
    try:
        total = 0
        for round_i in range(10):
            vals = ray.get([a.add.remote(round_i, j)
                            for j, a in enumerate(actors)], timeout=240)
            total += sum(vals)
            time.sleep(0.3)
    finally:
        killer.stop()
    assert killer.killed, "no node was killed"
    expected = sum(r + j for r in range(10) for j in range(4))
    assert total == expected
