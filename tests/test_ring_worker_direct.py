"""Cluster integration: round-10 worker-direct dispatch rings.

Lifecycle edges ISSUE 10 pins down: a worker killed mid-ring drains to
the typed retry path with no lost or duplicated task (task_events:
exactly one SUBMITTED per task), an oversize spec falls back to the RPC
push on a ring-attached lease (and the pair survives), a lease return
detaches and destroys the pair (segments unlinked), and flag-off
restores pure RPC push (no pair ever attaches).

One module-scoped ring cluster serves the first three tests (ordered so
the worker-kill chaos runs last on it); flag-off boots its own.
"""

import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu.core.config import ray_config

pytestmark = pytest.mark.cluster


def _live_rings(rt):
    return [st for st in rt._worker_rings.values()
            if isinstance(st, dict) and st.get("live")]


@pytest.fixture(scope="module", autouse=True)
def _restore_config():
    """_system_config overrides land in the process-global Config and
    would otherwise leak into later test modules (e.g. re-gate the
    inline tier off for the fastpath suite)."""
    saved = dict(ray_config()._values)
    yield
    ray_config()._values.clear()
    ray_config()._values.update(saved)


@pytest.fixture(scope="module")
def ring_cluster(_restore_config):
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, _system_config={
        "submit_ring": True, "task_inline_execution": False,
        "task_retry_delay_ms": 50})
    yield ray_tpu.core.worker.current_runtime()
    ray_tpu.shutdown()


def test_oversize_spec_falls_back_to_rpc_push(ring_cluster):
    """A delta larger than the slot capacity cannot ride the ring: the
    push must fall back to the RPC path on the SAME ring-attached
    lease, and the pair keeps serving small specs afterwards."""
    from ray_tpu.core import attribution

    rt = ring_cluster

    @ray_tpu.remote
    def size_of(b):
        return len(b)

    ray_tpu.get([size_of.remote(b"x") for _ in range(30)], timeout=120)
    assert _live_rings(rt), rt._worker_rings
    attribution.enable()
    attribution.reset()
    try:
        big = b"y" * (8 * ray_config().submit_ring_slot_bytes)
        assert ray_tpu.get(size_of.remote(big), timeout=60) == len(big)
        snap = attribution.snapshot()
        assert snap.get("ring.fallback", {}).get("count", 0) >= 1, snap
    finally:
        attribution.disable()
    assert _live_rings(rt)
    assert ray_tpu.get(size_of.remote(b"z"), timeout=60) == 1


def test_lease_return_detaches_and_destroys_pair(ring_cluster):
    """An idle lease lingers briefly then returns; the return must
    detach the pair and unlink both shm segments — a recycled worker
    never carries a stale ring into its next lease."""
    rt = ring_cluster

    @ray_tpu.remote
    def one():
        return 1

    ray_tpu.get([one.remote() for _ in range(30)], timeout=120)
    live = _live_rings(rt)
    assert live
    segs = [name for st in live for name, _ in st["files"]]
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and _live_rings(rt):
        time.sleep(0.2)
    assert not _live_rings(rt), rt._worker_rings
    for name in segs:
        assert not os.path.exists(f"/dev/shm/{name}"), name


def test_worker_kill_mid_ring_drains_to_retry_path(ring_cluster):
    """Chaos edge (runs last on the shared cluster): SIGKILL a
    ring-attached worker with a burst in flight. Its ring entries must
    fail onto the ConnectionLost retry path (same as a dead RPC push)
    and re-lease elsewhere — every submission completes, none is lost,
    none is duplicated."""
    rt = ring_cluster

    @ray_tpu.remote
    def pid_add(x):
        return (os.getpid(), x + 1)

    warm = ray_tpu.get([pid_add.remote(i) for i in range(40)],
                       timeout=120)
    pids = sorted({p for p, _ in warm})
    assert _live_rings(rt), rt._worker_rings

    refs = [pid_add.remote(i) for i in range(200)]
    time.sleep(0.05)          # let part of the burst go in flight
    os.kill(pids[0], signal.SIGKILL)
    res = ray_tpu.get(refs, timeout=180)
    assert [x for _, x in res] == [i + 1 for i in range(200)]

    # Exactly-once submission accounting survives the chaos: one
    # SUBMITTED event per task (retries re-EXECUTE, never re-submit).
    task_ids = {r.id().task_id().hex() for r in refs}
    deadline = time.monotonic() + 15
    counts = {}
    while time.monotonic() < deadline:
        counts = {}
        for e in rt.task_events():
            if (e.get("task_id") in task_ids
                    and e.get("event") == "SUBMITTED"):
                counts[e["task_id"]] = counts.get(e["task_id"], 0) + 1
        if len(counts) == len(task_ids):
            break
        time.sleep(0.5)
    assert len(counts) == len(task_ids)
    assert all(n == 1 for n in counts.values()), {
        t: n for t, n in counts.items() if n != 1}


def test_flag_off_restores_pure_rpc_push():
    """Default config: no pair ever attaches; dispatch is the plain
    RPC push, byte-identically to round 8's flag-off contract."""
    ray_tpu.shutdown()
    # submit_ring: False explicitly — _system_config overrides persist
    # in the process-global Config across shutdown/init cycles.
    ray_tpu.init(num_cpus=2, _system_config={
        "submit_ring": False, "task_inline_execution": False})
    try:
        @ray_tpu.remote
        def dbl(x):
            return x * 2

        assert ray_tpu.get([dbl.remote(i) for i in range(30)],
                           timeout=120) == [i * 2 for i in range(30)]
        rt = ray_tpu.core.worker.current_runtime()
        assert rt._worker_rings == {}
        assert rt._task_rings == []
    finally:
        ray_tpu.shutdown()
